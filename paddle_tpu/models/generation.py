"""Autoregressive generation — KV-cached compiled decode.

Parity: the reference's decoding machinery (sampling ops ``top_k_op``/
``multinomial``, ``beam_search_op``/``beam_search_decode_op``, and the fluid
decoder loops PaddleNLP builds on them). TPU-native formulation: the WHOLE
decode — prefill, per-step cache update, logits, top-k/top-p filtering,
sampling — is one jitted program per (prompt-shape, max-length): the step
loop is a ``lax.fori_loop`` whose carry holds the KV caches, so tokens never
bounce to the host between steps.

Works with GPT-style models exposing:
  model.gpt.embeddings(ids, position_ids), model.gpt.layers[i] blocks with
  .ln1/.attn(.qkv/.proj/num_heads/head_dim)/.ln2/.mlp, model.gpt.final_ln,
  tied LM head (embedding weight).
"""
from __future__ import annotations

from typing import Optional

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..core import random as random_state
from ..core.engine import no_grad
from ..core.tensor import Tensor


def top_k_top_p_filtering(logits, top_k=0, top_p=1.0):
    """Mask logits outside top-k / nucleus top-p (reference top_k_op +
    sampling ops role). Pure jnp; usable inside jit."""
    V = logits.shape[-1]
    if top_k and top_k > 0:
        k = min(int(top_k), V)  # top_k beyond vocab keeps everything
        kth = jnp.sort(logits, axis=-1)[..., V - k][..., None]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if top_p is not None and top_p < 1.0:
        sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # keep the smallest prefix with cumulative prob >= top_p
        cutoff_idx = jnp.sum(cum < top_p, axis=-1, keepdims=True)
        cutoff = jnp.take_along_axis(sorted_logits, cutoff_idx, axis=-1)
        logits = jnp.where(logits < cutoff, -jnp.inf, logits)
    return logits


def _layer_weights(layer):
    a = layer.attn
    return {
        "ln1_w": layer.ln1.weight._data, "ln1_b": layer.ln1.bias._data,
        "qkv_w": a.qkv.weight._data, "qkv_b": a.qkv.bias._data,
        "proj_w": a.proj.weight._data, "proj_b": a.proj.bias._data,
        "ln2_w": layer.ln2.weight._data, "ln2_b": layer.ln2.bias._data,
        "up_w": layer.mlp.up.weight._data, "up_b": layer.mlp.up.bias._data,
        "down_w": layer.mlp.down.weight._data, "down_b": layer.mlp.down.bias._data,
    }


def _ln(x, w, b, eps=1e-5):
    mu = x.mean(-1, keepdims=True)
    var = ((x - mu) ** 2).mean(-1, keepdims=True)
    return (x - mu) / jnp.sqrt(var + eps) * w + b


def _block(x, w, H, D, kv=None, pos=None):
    """One decoder block, pure-array. kv=(k_cache, v_cache) enables cached
    attention for a single-step x (B, 1, hidden); kv=None runs full causal
    attention and returns this block's k/v for cache prefill."""
    B, T = x.shape[0], x.shape[1]
    h = _ln(x, w["ln1_w"], w["ln1_b"])
    qkv = h @ w["qkv_w"] + w["qkv_b"]
    qkv = qkv.reshape(B, T, 3, H, D)
    q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
    scale = jnp.asarray(1.0 / np.sqrt(D), x.dtype)  # keep x's dtype under x64
    if kv is None:
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
        mask = jnp.tril(jnp.ones((T, T), bool))
        p = jax.nn.softmax(jnp.where(mask[None, None], s, -jnp.inf), axis=-1)
        o = jnp.einsum("bhqk,bkhd->bqhd", p, v)
        new_kv = (k, v)
    else:
        kc, vc = kv  # (B, T_max, H, D)
        kc = lax.dynamic_update_slice(kc, k, (0, pos, 0, 0))
        vc = lax.dynamic_update_slice(vc, v, (0, pos, 0, 0))
        s = jnp.einsum("bqhd,bkhd->bhqk", q, kc) * scale  # (B,H,1,T_max)
        live = (jnp.arange(kc.shape[1]) <= pos)[None, None, None, :]
        p = jax.nn.softmax(jnp.where(live, s, -jnp.inf), axis=-1)
        o = jnp.einsum("bhqk,bkhd->bqhd", p, vc)
        new_kv = (kc, vc)
    o = o.reshape(B, T, H * D)
    x = x + (o @ w["proj_w"] + w["proj_b"])
    h2 = _ln(x, w["ln2_w"], w["ln2_b"])
    ff = jax.nn.gelu(h2 @ w["up_w"] + w["up_b"], approximate=True) @ w["down_w"] + w["down_b"]
    return x + ff, new_kv


@no_grad()
def generate(
    model,
    input_ids,
    max_new_tokens: int = 32,
    temperature: float = 1.0,
    top_k: int = 0,
    top_p: float = 1.0,
    eos_token_id: Optional[int] = None,
    do_sample: bool = True,
):
    """Sample continuations for a GPTForPretraining-style model. Returns
    (B, T_prompt + max_new_tokens) int ids (generation stops writing after
    eos but shapes stay static — XLA-friendly)."""
    gpt = model.gpt
    cfg = model.config
    H = cfg.num_heads
    D = cfg.hidden_size // H

    ids = input_ids._data if isinstance(input_ids, Tensor) else jnp.asarray(input_ids)
    ids = ids.astype(jnp.int32)
    B, T0 = ids.shape
    T_max = T0 + int(max_new_tokens)
    if T_max > cfg.max_position_embeddings:
        raise ValueError(
            f"generate: {T_max} exceeds max_position_embeddings "
            f"{cfg.max_position_embeddings}"
        )

    qkv_w = gpt.layers[0].attn.qkv.weight._data
    if qkv_w.shape[-1] != 3 * cfg.hidden_size:
        raise NotImplementedError(
            "generate(): weights are physically mp-sharded "
            f"(qkv local shape {qkv_w.shape}); decode assumes full logical "
            "weights — gather them (state_dict round-trip) or generate before "
            "engine.place()"
        )
    params = {
        "wte": gpt.embeddings.word_embeddings.weight._data,
        "wpe": gpt.embeddings.position_embeddings.weight._data,
        "lnf_w": gpt.final_ln.weight._data,
        "lnf_b": gpt.final_ln.bias._data,
        "layers": [_layer_weights(l) for l in gpt.layers],
    }
    key = random_state.next_key()

    # cache by architecture + decode config (NOT id(model): the fn takes all
    # weights as arguments, so it is model-independent)
    cache_key = (H, D, len(params["layers"]), B, T0, int(max_new_tokens),
                 float(temperature), int(top_k), float(top_p), eos_token_id,
                 bool(do_sample))
    fn = _DECODE_CACHE.get(cache_key)
    if fn is None:
        fn = jax.jit(
            _build_decode(H, D, T0, T_max, int(max_new_tokens),
                          float(temperature), int(top_k), float(top_p),
                          eos_token_id, bool(do_sample))
        )
        _DECODE_CACHE[cache_key] = fn
    out = fn(params, ids, key)
    return Tensor(out, stop_gradient=True)


_DECODE_CACHE = {}


def _build_decode(H, D, T0, T_max, max_new_tokens, temperature, top_k, top_p,
                  eos_token_id, do_sample):
    def decode(params, ids, key):
        wte, wpe = params["wte"], params["wpe"]
        lnf_w, lnf_b = params["lnf_w"], params["lnf_b"]
        layer_ws = params["layers"]
        B = ids.shape[0]

        # ---- prefill: full forward over the prompt, caches captured -------
        x = wte[ids] + wpe[jnp.arange(T0)][None]
        caches = []
        for w in layer_ws:
            x, (k, v) = _block(x, w, H, D)
            kc = jnp.zeros((B, T_max, H, D), x.dtype).at[:, :T0].set(k)
            vc = jnp.zeros((B, T_max, H, D), x.dtype).at[:, :T0].set(v)
            caches.append((kc, vc))
        x = _ln(x, lnf_w, lnf_b)
        logits0 = x[:, -1] @ wte.T  # tied head

        out = jnp.zeros((B, T_max), jnp.int32).at[:, :T0].set(ids)
        finished = jnp.zeros((B,), bool)

        def sample_from(logits, key):
            if do_sample:
                logits = logits / max(temperature, 1e-6)
                logits = top_k_top_p_filtering(logits, top_k, top_p)
                return jax.random.categorical(key, logits, axis=-1)
            return jnp.argmax(logits, axis=-1)

        def step(i, carry):
            out, caches, finished, key, logits = carry
            key, sub = jax.random.split(key)
            nxt = sample_from(logits, sub).astype(jnp.int32)
            if eos_token_id is not None:
                nxt = jnp.where(finished, eos_token_id, nxt)
                finished = finished | (nxt == eos_token_id)
            pos = T0 + i
            out = lax.dynamic_update_slice(out, nxt[:, None], (0, pos))
            # one-token forward with cache
            x = wte[nxt][:, None] + wpe[pos][None, None]
            new_caches = []
            for w, kv in zip(layer_ws, caches):
                x, kv = _block(x, w, H, D, kv=kv, pos=pos)
                new_caches.append(kv)
            x = _ln(x, lnf_w, lnf_b)
            logits = x[:, -1] @ wte.T
            return out, tuple(new_caches), finished, key, logits

        out, _, _, _, _ = lax.fori_loop(
            0, max_new_tokens, step,
            (out, tuple(caches), finished, key, logits0),
        )
        return out

    return decode
