"""Autoregressive generation — KV-cached compiled decode.

Parity: the reference's decoding machinery (sampling ops ``top_k_op``/
``multinomial``, ``beam_search_op``/``beam_search_decode_op``, and the fluid
decoder loops PaddleNLP builds on them). TPU-native formulation: the WHOLE
decode — prefill, per-step cache update, logits, top-k/top-p filtering,
sampling — is one jitted program per (architecture, prompt-shape,
max-length): the step loop is a ``lax.fori_loop`` whose carry holds the KV
caches, so tokens never bounce to the host between steps.

Two architecture plugs share one loop driver:
  GPT   — LayerNorm + learned positions + fused qkv + GELU MLP, tied head;
  Llama — RMSNorm + RoPE at absolute cache positions + GQA (grouped-query
          attention against the UN-repeated KV cache) + SwiGLU, untied head.
"""
from __future__ import annotations

from typing import Optional

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..core import random as random_state
from ..core.engine import no_grad
from ..core.tensor import Tensor

_DECODE_CACHE = {}

# Steps actually executed by the most recent non-beam generate() call: the
# eos early-exit while_loop stops as soon as every row is finished, so this
# is < max_new_tokens whenever eos cut the batch short (diagnostic). Holds
# the still-dispatched jax scalar (or a plain int on the beam path).
_LAST_DECODE_STEPS = None


def last_decode_steps() -> Optional[int]:
    """Trip count of the most recent ``generate``/``generate_llama`` decode
    loop on this process (None before the first call). Not thread-safe —
    a diagnostic for tests and telemetry, not an API. The host-blocking
    coercion happens HERE, not in generate(), so the decode dispatch stays
    asynchronous for callers that never ask."""
    return None if _LAST_DECODE_STEPS is None else int(_LAST_DECODE_STEPS)


def top_k_top_p_filtering(logits, top_k=0, top_p=1.0):
    """Mask logits outside top-k / nucleus top-p (reference top_k_op +
    sampling ops role). Pure jnp; usable inside jit."""
    V = logits.shape[-1]
    if top_k and top_k > 0:
        k = min(int(top_k), V)  # top_k beyond vocab keeps everything
        kth = jnp.sort(logits, axis=-1)[..., V - k][..., None]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if top_p is not None and top_p < 1.0:
        sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # keep the smallest prefix with cumulative prob >= top_p
        cutoff_idx = jnp.sum(cum < top_p, axis=-1, keepdims=True)
        cutoff = jnp.take_along_axis(sorted_logits, cutoff_idx, axis=-1)
        logits = jnp.where(logits < cutoff, -jnp.inf, logits)
    return logits


def _grouped_attention(q, kc, vc, live, rep):
    """Attention of q (B,T,H,D) against an UN-repeated KV cache
    (B,Tk,KV,D): GQA via a grouped einsum — the repeats are never
    materialized, so the cache streams once regardless of H/KV."""
    B, T, H, D = q.shape
    KV = kc.shape[2]
    scale = jnp.asarray(1.0 / np.sqrt(D), q.dtype)
    qg = q.reshape(B, T, KV, rep, D)
    s = jnp.einsum("bqgrd,bkgd->bgrqk", qg, kc) * scale  # (B,KV,rep,T,Tk)
    p = jax.nn.softmax(jnp.where(live, s, -jnp.inf), axis=-1)
    o = jnp.einsum("bgrqk,bkgd->bqgrd", p, vc)
    return o.reshape(B, T, H * D)


# ---------------------------------------------------------------------------
# GPT architecture plug
# ---------------------------------------------------------------------------

def _gpt_layer_weights(layer):
    a = layer.attn
    return {
        "ln1_w": layer.ln1.weight._data, "ln1_b": layer.ln1.bias._data,
        "qkv_w": a.qkv.weight._data, "qkv_b": a.qkv.bias._data,
        "proj_w": a.proj.weight._data, "proj_b": a.proj.bias._data,
        "ln2_w": layer.ln2.weight._data, "ln2_b": layer.ln2.bias._data,
        "up_w": layer.mlp.up.weight._data, "up_b": layer.mlp.up.bias._data,
        "down_w": layer.mlp.down.weight._data, "down_b": layer.mlp.down.bias._data,
    }


def _ln(x, w, b, eps=1e-5):
    mu = x.mean(-1, keepdims=True)
    var = ((x - mu) ** 2).mean(-1, keepdims=True)
    return (x - mu) / jnp.sqrt(var + eps) * w + b


def _head_mm(params, rows, key, transpose):
    """LM-head matmul with an optional fused int8 path.

    When the engine attached a quantized head (``params["head_q"]`` — see
    serving/int8.attach_int8_head, behind FLAGS_serve_int8_kernel) the
    weight stays int8 end-to-end through the fused dequant matmul kernel
    (bit-identical to dequantize-then-matmul, so tokens cannot change).
    Otherwise: the exact dense matmul these head fns always did."""
    hq = params.get("head_q") if isinstance(params, dict) else None
    if hq is not None:
        from ..ops.kernels import int8_matmul

        return int8_matmul(rows, hq["q"], hq["scale"], transpose_w=transpose)
    w = params[key]
    return rows @ (w.T if transpose else w)


def _gpt_arch(H, D):
    def embed_prompt(params, ids, T0):
        return params["wte"][ids] + params["wpe"][jnp.arange(T0)][None]

    def embed_token(params, tok, pos):
        return params["wte"][tok][:, None] + params["wpe"][pos][None, None]

    def embed_rows(params, toks, pos):
        # packed decode: one token per row at per-row absolute positions —
        # toks (B,), pos (B,) -> (B, 1, H·D)
        return params["wte"][toks][:, None] + params["wpe"][pos][:, None]

    def head_rows(params, x, idx):
        # logits at each row's own position (per-row prompt lengths): the
        # batch-packed analogue of head()'s x[:, -1]
        h = _ln(x, params["lnf_w"], params["lnf_b"])
        rows = jnp.take_along_axis(h, idx[:, None, None], axis=1)[:, 0]
        return _head_mm(params, rows, "wte", True)

    def head_all(params, x):
        # logits at EVERY fed position (speculative verify reads all k+1)
        return _head_mm(params, _ln(x, params["lnf_w"], params["lnf_b"]),
                        "wte", True)

    def embed_tail(params, ids, starts):
        # T tokens per row at per-row absolute positions starts + [0..T)
        T = ids.shape[1]
        pos = starts[:, None] + jnp.arange(T)[None, :]
        return params["wte"][ids] + params["wpe"][pos]

    def block_tail(w, x, k_ctx, v_ctx, live, starts):
        # multi-token packed pass against a gathered paged context: x
        # (B,T,H·D) holds T consecutive tokens per row starting at absolute
        # position starts (B,); their fresh K/V overwrite the in-context
        # slots starts+[0..T) before attention (the joint causal pass over
        # the feeds — token j attends to the fresh K/V of tokens <= j plus
        # the cached context), live (B,T,Tp) masks per (row, feed). The
        # caller scatters (k_new, v_new) (B,T,KV,D) back into the pool.
        B, T = x.shape[0], x.shape[1]
        rows = jnp.arange(B)[:, None]
        posm = starts[:, None] + jnp.arange(T)[None, :]
        h = _ln(x, w["ln1_w"], w["ln1_b"])
        qkv = (h @ w["qkv_w"] + w["qkv_b"]).reshape(B, T, 3, H, D)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        k_new, v_new = k, v
        kc = k_ctx.at[rows, posm].set(k_new)
        vc = v_ctx.at[rows, posm].set(v_new)
        o = _grouped_attention(q, kc, vc, live[:, None, None], rep=1)
        x = x + (o @ w["proj_w"] + w["proj_b"])
        h2 = _ln(x, w["ln2_w"], w["ln2_b"])
        ff = jax.nn.gelu(h2 @ w["up_w"] + w["up_b"], approximate=True) @ w["down_w"] + w["down_b"]
        return x + ff, k_new, v_new

    def block_rows(w, x, k_ctx, v_ctx, live, pos):
        # single-token decode against a GATHERED paged context: x (B,1,H·D);
        # k_ctx/v_ctx (B,Tp,KV,D) hold each row's blocks in sequence order
        # with a stale slot at pos that the fresh k/v overwrites in-ctx;
        # live (B,Tp) masks positions <= pos. The caller owns scattering
        # (k_new, v_new) back into the pool for future steps.
        B = x.shape[0]
        rows = jnp.arange(B)
        h = _ln(x, w["ln1_w"], w["ln1_b"])
        qkv = (h @ w["qkv_w"] + w["qkv_b"]).reshape(B, 1, 3, H, D)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        k_new, v_new = k[:, 0], v[:, 0]
        kc = k_ctx.at[rows, pos].set(k_new)
        vc = v_ctx.at[rows, pos].set(v_new)
        o = _grouped_attention(q, kc, vc, live[:, None, None, None, :], rep=1)
        x = x + (o @ w["proj_w"] + w["proj_b"])
        h2 = _ln(x, w["ln2_w"], w["ln2_b"])
        ff = jax.nn.gelu(h2 @ w["up_w"] + w["up_b"], approximate=True) @ w["down_w"] + w["down_b"]
        return x + ff, k_new, v_new

    def qkv_rows(w, x, pos):
        # the projection half of block_rows (same ops, same order — the
        # kernel decode path must trace byte-identical math around the
        # attention read): x (B,1,H·D) -> q (B,H,D), k_new/v_new (B,H,D)
        B = x.shape[0]
        h = _ln(x, w["ln1_w"], w["ln1_b"])
        qkv = (h @ w["qkv_w"] + w["qkv_b"]).reshape(B, 1, 3, H, D)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        return q[:, 0], k[:, 0], v[:, 0]

    def attn_out_rows(w, x, o):
        # the post-attention half of block_rows: o (B,1,H·D) attention read
        x = x + (o @ w["proj_w"] + w["proj_b"])
        h2 = _ln(x, w["ln2_w"], w["ln2_b"])
        ff = jax.nn.gelu(h2 @ w["up_w"] + w["up_b"], approximate=True) @ w["down_w"] + w["down_b"]
        return x + ff

    def block(w, x, kv=None, pos=None):
        B, T = x.shape[0], x.shape[1]
        h = _ln(x, w["ln1_w"], w["ln1_b"])
        qkv = (h @ w["qkv_w"] + w["qkv_b"]).reshape(B, T, 3, H, D)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        if kv is None:
            live = jnp.tril(jnp.ones((T, T), bool))[None, None, None]
            o = _grouped_attention(q, k, v, live, rep=1)
            new_kv = (k, v)
        else:
            kc = lax.dynamic_update_slice(kv[0], k, (0, pos, 0, 0))
            vc = lax.dynamic_update_slice(kv[1], v, (0, pos, 0, 0))
            live = (jnp.arange(kc.shape[1]) <= pos)[None, None, None, None, :]
            o = _grouped_attention(q, kc, vc, live, rep=1)
            new_kv = (kc, vc)
        x = x + (o @ w["proj_w"] + w["proj_b"])
        h2 = _ln(x, w["ln2_w"], w["ln2_b"])
        ff = jax.nn.gelu(h2 @ w["up_w"] + w["up_b"], approximate=True) @ w["down_w"] + w["down_b"]
        return x + ff, new_kv

    def head(params, x):
        x = _ln(x, params["lnf_w"], params["lnf_b"])
        return _head_mm(params, x[:, -1], "wte", True)  # tied head

    return {"embed_prompt": embed_prompt, "embed_token": embed_token,
            "embed_rows": embed_rows, "head_rows": head_rows,
            "head_all": head_all, "embed_tail": embed_tail,
            "block_rows": block_rows, "block_tail": block_tail,
            "qkv_rows": qkv_rows, "attn_out_rows": attn_out_rows,
            "block": block, "head": head, "kv_heads": H, "head_dim": D}


# ---------------------------------------------------------------------------
# Llama architecture plug
# ---------------------------------------------------------------------------

def _llama_layer_weights(layer):
    a = layer.self_attn
    m = layer.mlp
    return {
        "ln1_w": layer.input_layernorm.weight._data,
        "q_w": a.q_proj.weight._data, "k_w": a.k_proj.weight._data,
        "v_w": a.v_proj.weight._data, "o_w": a.o_proj.weight._data,
        "ln2_w": layer.post_attention_layernorm.weight._data,
        "gate_w": m.gate_proj.weight._data, "up_w": m.up_proj.weight._data,
        "down_w": m.down_proj.weight._data,
    }


def _rms(x, w, eps):
    var = jnp.mean(jnp.square(x.astype(jnp.float32)), -1, keepdims=True)
    return (x * lax.rsqrt(var + eps).astype(x.dtype)) * w


def _rope_at(x, pos0, theta):
    """Rotary embedding at absolute positions pos0 + [0..T)."""
    B, T, H, D = x.shape
    pos = pos0 + jnp.arange(T, dtype=jnp.float32)
    inv = 1.0 / (theta ** (jnp.arange(0, D, 2, dtype=jnp.float32) / D))
    ang = pos[:, None] * inv[None, :]
    cos = jnp.cos(ang)[None, :, None, :].astype(x.dtype)
    sin = jnp.sin(ang)[None, :, None, :].astype(x.dtype)
    x1, x2 = x[..., ::2], x[..., 1::2]
    o1 = x1 * cos - x2 * sin
    o2 = x2 * cos + x1 * sin
    return jnp.stack([o1, o2], axis=-1).reshape(x.shape)


def _rope_rows(x, pos, theta):
    """Rotary embedding for ONE token per row at per-row absolute positions
    (packed decode): x (B, 1, H, D), pos (B,) int."""
    D = x.shape[-1]
    inv = 1.0 / (theta ** (jnp.arange(0, D, 2, dtype=jnp.float32) / D))
    ang = pos.astype(jnp.float32)[:, None] * inv[None, :]  # (B, D/2)
    cos = jnp.cos(ang)[:, None, None, :].astype(x.dtype)
    sin = jnp.sin(ang)[:, None, None, :].astype(x.dtype)
    x1, x2 = x[..., ::2], x[..., 1::2]
    o1 = x1 * cos - x2 * sin
    o2 = x2 * cos + x1 * sin
    return jnp.stack([o1, o2], axis=-1).reshape(x.shape)


def _rope_grid(x, pos, theta):
    """Rotary embedding at a per-(row, token) position grid (tail prefill /
    speculative verify): x (B, T, H, D), pos (B, T) int."""
    D = x.shape[-1]
    inv = 1.0 / (theta ** (jnp.arange(0, D, 2, dtype=jnp.float32) / D))
    ang = pos.astype(jnp.float32)[:, :, None] * inv[None, None, :]  # (B,T,D/2)
    cos = jnp.cos(ang)[:, :, None, :].astype(x.dtype)
    sin = jnp.sin(ang)[:, :, None, :].astype(x.dtype)
    x1, x2 = x[..., ::2], x[..., 1::2]
    o1 = x1 * cos - x2 * sin
    o2 = x2 * cos + x1 * sin
    return jnp.stack([o1, o2], axis=-1).reshape(x.shape)


def _llama_arch(H, KV, D, theta, eps):
    rep = H // KV

    def embed_prompt(params, ids, T0):
        return params["wte"][ids]

    def embed_token(params, tok, pos):
        return params["wte"][tok][:, None]

    def embed_rows(params, toks, pos):
        return params["wte"][toks][:, None]

    def head_rows(params, x, idx):
        h = _rms(x, params["lnf_w"], eps)
        rows = jnp.take_along_axis(h, idx[:, None, None], axis=1)[:, 0]
        return _head_mm(params, rows, "head_w", False)

    def head_all(params, x):
        return _head_mm(params, _rms(x, params["lnf_w"], eps),
                        "head_w", False)

    def embed_tail(params, ids, starts):
        return params["wte"][ids]

    def block_tail(w, x, k_ctx, v_ctx, live, starts):
        # see the GPT plug for the contract; RoPE at each (row, feed)'s own
        # absolute position, GQA against the un-repeated gathered cache
        B, T = x.shape[0], x.shape[1]
        rows = jnp.arange(B)[:, None]
        posm = starts[:, None] + jnp.arange(T)[None, :]
        h = _rms(x, w["ln1_w"], eps)
        q = (h @ w["q_w"]).reshape(B, T, H, D)
        k = (h @ w["k_w"]).reshape(B, T, KV, D)
        v = (h @ w["v_w"]).reshape(B, T, KV, D)
        q = _rope_grid(q, posm, theta)
        k = _rope_grid(k, posm, theta)
        k_new, v_new = k, v
        kc = k_ctx.at[rows, posm].set(k_new)
        vc = v_ctx.at[rows, posm].set(v_new)
        o = _grouped_attention(q, kc, vc, live[:, None, None], rep)
        x = x + o @ w["o_w"]
        h2 = _rms(x, w["ln2_w"], eps)
        ff = (jax.nn.silu(h2 @ w["gate_w"]) * (h2 @ w["up_w"])) @ w["down_w"]
        return x + ff, k_new, v_new

    def block_rows(w, x, k_ctx, v_ctx, live, pos):
        # see the GPT plug for the contract; RoPE applied at each row's own
        # absolute position, GQA against the un-repeated gathered cache
        B = x.shape[0]
        rows = jnp.arange(B)
        h = _rms(x, w["ln1_w"], eps)
        q = (h @ w["q_w"]).reshape(B, 1, H, D)
        k = (h @ w["k_w"]).reshape(B, 1, KV, D)
        v = (h @ w["v_w"]).reshape(B, 1, KV, D)
        q = _rope_rows(q, pos, theta)
        k = _rope_rows(k, pos, theta)
        k_new, v_new = k[:, 0], v[:, 0]
        kc = k_ctx.at[rows, pos].set(k_new)
        vc = v_ctx.at[rows, pos].set(v_new)
        o = _grouped_attention(q, kc, vc, live[:, None, None, None, :], rep)
        x = x + o @ w["o_w"]
        h2 = _rms(x, w["ln2_w"], eps)
        ff = (jax.nn.silu(h2 @ w["gate_w"]) * (h2 @ w["up_w"])) @ w["down_w"]
        return x + ff, k_new, v_new

    def qkv_rows(w, x, pos):
        # projection half of block_rows (same ops/order — see the GPT plug):
        # RoPE at each row's own absolute position, un-repeated KV heads
        B = x.shape[0]
        h = _rms(x, w["ln1_w"], eps)
        q = (h @ w["q_w"]).reshape(B, 1, H, D)
        k = (h @ w["k_w"]).reshape(B, 1, KV, D)
        v = (h @ w["v_w"]).reshape(B, 1, KV, D)
        q = _rope_rows(q, pos, theta)
        k = _rope_rows(k, pos, theta)
        return q[:, 0], k[:, 0], v[:, 0]

    def attn_out_rows(w, x, o):
        x = x + o @ w["o_w"]
        h2 = _rms(x, w["ln2_w"], eps)
        ff = (jax.nn.silu(h2 @ w["gate_w"]) * (h2 @ w["up_w"])) @ w["down_w"]
        return x + ff

    def block(w, x, kv=None, pos=None):
        B, T = x.shape[0], x.shape[1]
        h = _rms(x, w["ln1_w"], eps)
        q = (h @ w["q_w"]).reshape(B, T, H, D)
        k = (h @ w["k_w"]).reshape(B, T, KV, D)
        v = (h @ w["v_w"]).reshape(B, T, KV, D)
        pos0 = jnp.float32(0.0) if kv is None else pos.astype(jnp.float32)
        q = _rope_at(q, pos0, theta)
        k = _rope_at(k, pos0, theta)
        if kv is None:
            live = jnp.tril(jnp.ones((T, T), bool))[None, None, None]
            o = _grouped_attention(q, k, v, live, rep)
            new_kv = (k, v)  # cache the KV heads, not the repeats
        else:
            kc = lax.dynamic_update_slice(kv[0], k, (0, pos, 0, 0))
            vc = lax.dynamic_update_slice(kv[1], v, (0, pos, 0, 0))
            live = (jnp.arange(kc.shape[1]) <= pos)[None, None, None, None, :]
            o = _grouped_attention(q, kc, vc, live, rep)
            new_kv = (kc, vc)
        x = x + o @ w["o_w"]
        h2 = _rms(x, w["ln2_w"], eps)
        ff = (jax.nn.silu(h2 @ w["gate_w"]) * (h2 @ w["up_w"])) @ w["down_w"]
        return x + ff, new_kv

    def head(params, x):
        return _head_mm(params, _rms(x, params["lnf_w"], eps)[:, -1],
                        "head_w", False)

    return {"embed_prompt": embed_prompt, "embed_token": embed_token,
            "embed_rows": embed_rows, "head_rows": head_rows,
            "head_all": head_all, "embed_tail": embed_tail,
            "block_rows": block_rows, "block_tail": block_tail,
            "qkv_rows": qkv_rows, "attn_out_rows": attn_out_rows,
            "block": block, "head": head, "kv_heads": KV, "head_dim": D}


# ---------------------------------------------------------------------------
# Shared decode driver
# ---------------------------------------------------------------------------

def _build_decode(arch, T0, T_max, max_new_tokens, temperature, top_k, top_p,
                  eos_token_id, do_sample):
    KV, D = arch["kv_heads"], arch["head_dim"]

    def decode(params, ids, key):
        layer_ws = params["layers"]
        B = ids.shape[0]

        # ---- prefill: full forward over the prompt, caches captured -------
        x = arch["embed_prompt"](params, ids, T0)
        caches = []
        for w in layer_ws:
            x, (k, v) = arch["block"](w, x)
            kc = jnp.zeros((B, T_max, KV, D), x.dtype).at[:, :T0].set(k)
            vc = jnp.zeros((B, T_max, KV, D), x.dtype).at[:, :T0].set(v)
            caches.append((kc, vc))
        logits0 = arch["head"](params, x)

        # Tail pre-filled with eos: a finished row's remaining slots already
        # hold the pad value, so its writes below are no-ops (live-row
        # freeze) and the while_loop can exit as soon as EVERY row is done
        # instead of burning steps to max_new_tokens.
        fill = 0 if eos_token_id is None else int(eos_token_id)
        out = jnp.full((B, T_max), fill, jnp.int32).at[:, :T0].set(ids)
        finished = jnp.zeros((B,), bool)

        def sample_from(logits, key):
            if do_sample:
                logits = logits / max(temperature, 1e-6)
                logits = top_k_top_p_filtering(logits, top_k, top_p)
                return jax.random.categorical(key, logits, axis=-1)
            return jnp.argmax(logits, axis=-1)

        def step(carry):
            i, out, caches, finished, key, logits = carry
            key, sub = jax.random.split(key)
            nxt = sample_from(logits, sub).astype(jnp.int32)
            if eos_token_id is not None:
                # frozen rows re-write the eos their slot already holds
                nxt = jnp.where(finished, eos_token_id, nxt)
                finished = finished | (nxt == eos_token_id)
            pos = T0 + i
            out = lax.dynamic_update_slice(
                out, nxt[:, None], (jnp.asarray(0, pos.dtype), pos)
            )
            x = arch["embed_token"](params, nxt, pos)
            new_caches = []
            for w, kv in zip(layer_ws, caches):
                x, kv = arch["block"](w, x, kv=kv, pos=pos)
                new_caches.append(kv)
            logits = arch["head"](params, x)
            return i + 1, out, tuple(new_caches), finished, key, logits

        def cond(carry):
            i, _, _, finished, _, _ = carry
            live = i < max_new_tokens
            if eos_token_id is not None:
                live = live & ~jnp.all(finished)
            return live

        steps, out, _, _, _, _ = lax.while_loop(
            cond, step,
            # default int dtype (x64-dependent) so `pos = T0 + i` matches the
            # literal indices inside arch["block"]'s dynamic_update_slice
            (jnp.asarray(0), out, tuple(caches), finished, key, logits0),
        )
        return out, steps

    return decode


def _build_beam_decode(arch, T0, T_max, max_new_tokens, num_beams, eos_token_id,
                       length_penalty):
    """Beam search inside ONE jitted program (reference
    ``operators/math/beam_search.cc`` + ``beam_search_op``/
    ``beam_search_decode_op`` roles): the KV caches are stacked per beam
    (B·K leading dim) and re-gathered along the beam axis every step inside
    the ``lax.fori_loop`` carry — no host round trips."""
    KV, D = arch["kv_heads"], arch["head_dim"]
    K = int(num_beams)

    def decode(params, ids, key):
        layer_ws = params["layers"]
        B = ids.shape[0]

        # ---- prefill on the raw batch, then tile caches across beams ------
        x = arch["embed_prompt"](params, ids, T0)
        caches = []
        for w in layer_ws:
            x, (k, v) = arch["block"](w, x)
            kc = jnp.zeros((B, T_max, KV, D), x.dtype).at[:, :T0].set(k)
            vc = jnp.zeros((B, T_max, KV, D), x.dtype).at[:, :T0].set(v)
            caches.append(
                (jnp.repeat(kc, K, axis=0), jnp.repeat(vc, K, axis=0))
            )
        logits0 = jnp.repeat(arch["head"](params, x), K, axis=0)  # (B*K, V)

        out = jnp.zeros((B * K, T_max), jnp.int32).at[:, :T0].set(
            jnp.repeat(ids, K, axis=0)
        )
        # only beam 0 is live initially so step 1 draws K distinct tokens
        scores = jnp.tile(
            jnp.asarray([0.0] + [-1e30] * (K - 1), jnp.float32), (B, 1)
        )  # (B, K)
        finished = jnp.zeros((B, K), bool)

        def gather_beams(t, beam_idx):
            # t: (B*K, ...) → reorder rows by beam_idx (B, K)
            flat = beam_idx + (jnp.arange(B) * K)[:, None]  # (B, K) global rows
            return jnp.take(t, flat.reshape(-1), axis=0)

        def step(i, carry):
            out, caches, scores, finished, logits = carry
            V = logits.shape[-1]
            logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
            logp = logp.reshape(B, K, V)
            if eos_token_id is not None:
                # a finished beam may only extend with eos at no cost
                eos_only = jnp.full((V,), -jnp.inf).at[eos_token_id].set(0.0)
                logp = jnp.where(finished[..., None], eos_only[None, None], logp)
            total = scores[..., None] + logp  # (B, K, V)
            flat = total.reshape(B, K * V)
            new_scores, idx = lax.top_k(flat, K)  # (B, K)
            beam_idx = idx // V
            token = (idx % V).astype(jnp.int32)

            out = gather_beams(out, beam_idx)
            caches = tuple(
                (gather_beams(kc, beam_idx), gather_beams(vc, beam_idx))
                for kc, vc in caches
            )
            finished = jnp.take_along_axis(finished, beam_idx, axis=1)
            if eos_token_id is not None:
                finished = finished | (token == eos_token_id)

            pos = T0 + i
            out = lax.dynamic_update_slice(out, token.reshape(-1)[:, None], (0, pos))
            x = arch["embed_token"](params, token.reshape(-1), pos)
            new_caches = []
            for w, kv in zip(layer_ws, caches):
                x, kv = arch["block"](w, x, kv=kv, pos=pos)
                new_caches.append(kv)
            logits = arch["head"](params, x)
            return out, tuple(new_caches), new_scores, finished, logits

        out, _, scores, _, _ = lax.fori_loop(
            0, max_new_tokens, step,
            (out, tuple(caches), scores, finished, logits0),
        )
        # GNMT-style length penalty (reference beam_search length
        # normalization); generated length is uniform here so it only
        # matters when eos ended beams early — scores already froze then
        norm = scores / (float(T0 + max_new_tokens) ** float(length_penalty))
        best = jnp.argmax(norm, axis=1)  # (B,)
        rows = best + jnp.arange(B) * K
        return jnp.take(out, rows, axis=0)

    return decode


def _run(arch_key, arch, params, ids_in, T0, max_new_tokens, temperature,
         top_k, top_p, eos_token_id, do_sample, num_beams=1, length_penalty=0.0):
    global _LAST_DECODE_STEPS
    B = ids_in.shape[0]
    T_max = T0 + int(max_new_tokens)
    key = random_state.next_key()
    if num_beams and int(num_beams) > 1:
        cache_key = arch_key + ("beam", B, T0, int(max_new_tokens),
                                int(num_beams), eos_token_id, float(length_penalty))
        fn = _DECODE_CACHE.get(cache_key)
        if fn is None:
            fn = jax.jit(_build_beam_decode(
                arch, T0, T_max, int(max_new_tokens), int(num_beams),
                eos_token_id, float(length_penalty)))
            _DECODE_CACHE[cache_key] = fn
        _LAST_DECODE_STEPS = int(max_new_tokens)  # beam loop has no early exit
        return Tensor(fn(params, ids_in, key), stop_gradient=True)
    cache_key = arch_key + (B, T0, int(max_new_tokens), float(temperature),
                            int(top_k), float(top_p), eos_token_id,
                            bool(do_sample))
    fn = _DECODE_CACHE.get(cache_key)
    if fn is None:
        fn = jax.jit(_build_decode(
            arch, T0, T_max, int(max_new_tokens), float(temperature),
            int(top_k), float(top_p), eos_token_id, bool(do_sample)))
        _DECODE_CACHE[cache_key] = fn
    out, steps = fn(params, ids_in, key)
    _LAST_DECODE_STEPS = steps  # dispatched jax scalar; coerced on read
    return Tensor(out, stop_gradient=True)


@no_grad()
def generate(
    model,
    input_ids,
    max_new_tokens: int = 32,
    temperature: float = 1.0,
    top_k: int = 0,
    top_p: float = 1.0,
    eos_token_id: Optional[int] = None,
    do_sample: bool = True,
    num_beams: int = 1,
    length_penalty: float = 0.0,
):
    """Sample continuations for a GPTForPretraining-style model. Returns
    (B, T_prompt + max_new_tokens) int ids (generation stops writing after
    eos but shapes stay static — XLA-friendly)."""
    arch_key, arch, params, max_pos = gpt_decode_state(model)
    ids = input_ids._data if isinstance(input_ids, Tensor) else jnp.asarray(input_ids)
    ids = ids.astype(jnp.int32)
    T0 = ids.shape[1]
    if T0 + int(max_new_tokens) > max_pos:
        raise ValueError(
            f"generate: {T0 + int(max_new_tokens)} exceeds "
            f"max_position_embeddings {max_pos}"
        )
    return _run(arch_key, arch, params, ids, T0, max_new_tokens,
                temperature, top_k, top_p, eos_token_id, do_sample,
                num_beams=num_beams, length_penalty=length_penalty)


@no_grad()
def generate_llama(
    model, input_ids, max_new_tokens=32, temperature=1.0, top_k=0, top_p=1.0,
    eos_token_id=None, do_sample=True,
):
    """KV-cached compiled decode for LlamaForCausalLM: RoPE applied at
    absolute cache positions; GQA attends against the un-repeated KV cache."""
    arch_key, arch, params, max_pos = llama_decode_state(model)
    ids = input_ids._data if isinstance(input_ids, Tensor) else jnp.asarray(input_ids)
    ids = ids.astype(jnp.int32)
    T0 = ids.shape[1]
    if T0 + int(max_new_tokens) > max_pos:
        raise ValueError("generate: length exceeds max_position_embeddings")
    return _run(arch_key, arch, params, ids, T0, max_new_tokens,
                temperature, top_k, top_p, eos_token_id, do_sample)


# ---------------------------------------------------------------------------
# Scheduler-drivable decode state + paged prefill/step programs
# ---------------------------------------------------------------------------
# The serving engine (paddle_tpu/serving/) drives these directly: the state
# extractors are the single weight-tree + arch-plug extraction point shared
# with generate(), and the builders return batch-packed, cache-position-
# explicit pure functions the engine jits per bucket shape.

def gpt_decode_state(model):
    """(arch_key, arch, params, max_positions) for a GPTForPretraining-style
    model — the extraction point shared by ``generate()`` and the serving
    engine's paged prefill/decode programs."""
    gpt = model.gpt
    cfg = model.config
    H = cfg.num_heads
    D = cfg.hidden_size // H
    qkv_w = gpt.layers[0].attn.qkv.weight._data
    if qkv_w.shape[-1] != 3 * cfg.hidden_size:
        raise NotImplementedError(
            "generate(): weights are physically mp-sharded "
            f"(qkv local shape {qkv_w.shape}); decode assumes full logical "
            "weights — gather them (state_dict round-trip) or generate before "
            "engine.place()"
        )
    params = {
        "wte": gpt.embeddings.word_embeddings.weight._data,
        "wpe": gpt.embeddings.position_embeddings.weight._data,
        "lnf_w": gpt.final_ln.weight._data,
        "lnf_b": gpt.final_ln.bias._data,
        "layers": [_gpt_layer_weights(l) for l in gpt.layers],
    }
    arch_key = ("gpt", H, D, len(params["layers"]))
    return arch_key, _gpt_arch(H, D), params, cfg.max_position_embeddings


def llama_decode_state(model):
    """(arch_key, arch, params, max_positions) for LlamaForCausalLM."""
    cfg = model.model.config
    H = cfg.num_heads
    KV = cfg.kv_heads
    D = cfg.hidden_size // H
    q_w = model.model.layers[0].self_attn.q_proj.weight._data
    if q_w.shape[-1] != cfg.hidden_size:
        raise NotImplementedError("generate: physically mp-sharded weights")
    params = {
        "wte": model.model.embed_tokens.weight._data,
        "lnf_w": model.model.norm.weight._data,
        "head_w": model.lm_head.weight._data,
        "layers": [_llama_layer_weights(l) for l in model.model.layers],
    }
    # theta/eps are baked into the compiled fn: they MUST key the cache
    arch_key = ("llama", H, KV, D, len(params["layers"]),
                float(cfg.rope_theta), float(cfg.rms_norm_eps))
    arch = _llama_arch(H, KV, D, float(cfg.rope_theta), float(cfg.rms_norm_eps))
    return arch_key, arch, params, cfg.max_position_embeddings


def build_paged_prefill(arch, B, T_bucket, block_size, max_blocks):
    """Compiled prompt prefill over a length-bucketed batch, writing KV into
    the paged pool.

    The returned pure fn ``prefill(params, ids, lens, tables, kpool, vpool)``
    runs the dense causal forward over ``ids`` (B, T_bucket) — causality
    makes the K/V of every REAL position exact regardless of the padding
    behind it — reshapes each layer's (B, T_bucket, KV, D) K/V into
    ``T_bucket // block_size`` blocks and scatters them at ``tables[:, :nb]``
    (rows shorter than the bucket point their tail entries at the reserved
    trash block 0), and returns ``(kpool, vpool, logits)`` with logits taken
    at each row's true last prompt token (``lens - 1``)."""
    KV, D = arch["kv_heads"], arch["head_dim"]
    if T_bucket % block_size:
        raise ValueError(
            f"prefill bucket {T_bucket} must be a multiple of block_size "
            f"{block_size}"
        )
    nb = T_bucket // block_size
    if nb > max_blocks:
        raise ValueError("prefill bucket exceeds max sequence blocks")

    def prefill(params, ids, lens, tables, kpool, vpool):
        layer_ws = params["layers"]
        x = arch["embed_prompt"](params, ids, T_bucket)
        tb = tables[:, :nb]
        for li, w in enumerate(layer_ws):
            x, (k, v) = arch["block"](w, x)
            kpool = kpool.at[li, tb].set(k.reshape(B, nb, block_size, KV, D))
            vpool = vpool.at[li, tb].set(v.reshape(B, nb, block_size, KV, D))
        logits = arch["head_rows"](params, x, lens - 1)
        return kpool, vpool, logits

    return prefill


def build_paged_decode(arch, B, block_size, max_blocks):
    """One packed continuous-batching decode step over the paged KV cache.

    The returned pure fn
    ``step(params, kpool, vpool, tables, pos, toks, temps, key)`` feeds one
    token per row (``toks`` at per-row write positions ``pos``), gathers each
    row's context from its block table (``kpool[l][tables]`` — the
    gather-based paged attention read), overwrites the slot at ``pos`` with
    the fresh K/V in-context, masks positions ``> pos`` (per-row live
    lengths), scatters the new K/V back into the pool for future steps, and
    returns ``(kpool, vpool, next_tokens)``. Rows with ``temps > 0`` sample
    at that temperature (one PRNG key per step — not replay-stable across
    batch compositions); rows at 0 are greedy. Dead/padding rows should
    point their tables at the trash block with ``pos = 0``; their outputs
    are garbage the scheduler ignores."""
    KV, D = arch["kv_heads"], arch["head_dim"]
    T_pad = block_size * max_blocks

    def step(params, kpool, vpool, tables, pos, toks, temps, key):
        layer_ws = params["layers"]
        x = arch["embed_rows"](params, toks, pos)
        bids = jnp.take_along_axis(tables, (pos // block_size)[:, None], axis=1)[:, 0]
        offs = pos % block_size
        live = jnp.arange(T_pad)[None, :] <= pos[:, None]
        # all context gathers hoisted above the scatter chain: layer li's
        # gather reads kpool[li], which scatters to layers < li never touch,
        # so the values are identical — but with gathers interleaved, every
        # scatter's operand has a later reader and XLA copy-on-writes the
        # whole pool per layer (CPU: ~L pool-sized temps per step); hoisted,
        # only the first scatter pays one copy
        ctx = [(kpool[li][tables].reshape(B, T_pad, KV, D),
                vpool[li][tables].reshape(B, T_pad, KV, D))
               for li in range(len(layer_ws))]
        for li, w in enumerate(layer_ws):
            x, k_new, v_new = arch["block_rows"](w, x, ctx[li][0], ctx[li][1],
                                                 live, pos)
            kpool = kpool.at[li, bids, offs].set(k_new)
            vpool = vpool.at[li, bids, offs].set(v_new)
        logits = arch["head"](params, x)
        greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        scaled = (logits / jnp.maximum(temps, 1e-6)[:, None]).astype(jnp.float32)
        sampled = jax.random.categorical(key, scaled, axis=-1).astype(jnp.int32)
        nxt = jnp.where(temps > 0, sampled, greedy)
        return kpool, vpool, nxt

    return step


def build_paged_decode_kernel(arch, B, block_size, max_blocks):
    """``build_paged_decode`` with the attention read done by the
    block-table-aware Pallas kernel (``ops/kernels/paged_attention``)
    instead of the gather-then-dense path. Same step signature, same
    sampling — a drop-in the engine selects behind FLAGS_serve_paged_kernel.

    Differences from the gather builder, neither visible in the output:
    - no ``kpool[li][tables]`` HBM materialization — the kernel DMAs each
      row's blocks straight out of the pool;
    - the fresh K/V is scattered into the pool BEFORE the kernel reads it
      (the gather path overwrites the gathered copy at ``pos`` in-context —
      same values land in the same slot, so attention sees identical state).
    The surrounding per-layer math is the same ``block_rows`` code factored
    into ``qkv_rows``/``attn_out_rows``, so the whole step is bit-identical
    to the gather builder on the CPU tier (kernel in interpret mode)."""
    KV, D = arch["kv_heads"], arch["head_dim"]

    def step(params, kpool, vpool, tables, pos, toks, temps, key):
        from ..ops.kernels import paged_attention_rows

        layer_ws = params["layers"]
        x = arch["embed_rows"](params, toks, pos)
        bids = jnp.take_along_axis(tables, (pos // block_size)[:, None], axis=1)[:, 0]
        offs = pos % block_size
        for li, w in enumerate(layer_ws):
            q, k_new, v_new = arch["qkv_rows"](w, x, pos)
            kpool = kpool.at[li, bids, offs].set(k_new)
            vpool = vpool.at[li, bids, offs].set(v_new)
            o = paged_attention_rows(q, kpool[li], vpool[li], tables, pos)
            x = arch["attn_out_rows"](w, x, o[:, None])
        logits = arch["head"](params, x)
        greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        scaled = (logits / jnp.maximum(temps, 1e-6)[:, None]).astype(jnp.float32)
        sampled = jax.random.categorical(key, scaled, axis=-1).astype(jnp.int32)
        nxt = jnp.where(temps > 0, sampled, greedy)
        return kpool, vpool, nxt

    return step


def kv_block_checksums(kpool, vpool, bids):
    """Per-block content fingerprints of paged KV state — the resume-at-
    position validation entry for serving snapshots.

    A re-attached sequence resumes mid-decode through ``build_paged_decode``
    with its restored block table and ``pos`` — the compiled step needs no
    special resume path, but the KV bytes it reads must be the ones the dead
    engine wrote. This computes, for each block id in ``bids``, a
    deterministic ``(Σ|K|, Σ|V|)`` float64 reduction over that block's rows
    across all layers. ``Engine.snapshot()`` records the fingerprints of
    every owned block and ``Engine.adopt()`` recomputes them over the
    handed-over arrays: a mismatch (tampered/zeroed pool rows, dtype drift)
    is a structured ``SnapshotError`` — never a wrong-KV serve. Same arrays
    + same backend → bit-identical sums, so a clean handoff always matches.

    Returns an ``np.ndarray`` of shape ``(len(bids), 2)``; O(blocks) device
    work on the recovery path only."""
    if not len(bids):
        return np.zeros((0, 2), dtype=np.float64)
    idx = jnp.asarray(np.asarray(bids, dtype=np.int32))
    k = jnp.abs(kpool[:, idx].astype(jnp.float32)).sum(axis=(0, 2, 3, 4))
    v = jnp.abs(vpool[:, idx].astype(jnp.float32)).sum(axis=(0, 2, 3, 4))
    return np.stack([np.asarray(k), np.asarray(v)], axis=1).astype(np.float64)


def build_paged_tail_prefill(arch, B, T_bucket, block_size, max_blocks):
    """Prefix-cache tail prefill: prompt heads already live in shared pool
    blocks, only the TAIL tokens run the forward pass.

    The returned pure fn
    ``prefill(params, ids, starts, lens, tables, kpool, vpool)`` feeds each
    row's tail ``ids`` (B, T_bucket, padded) at absolute positions
    ``starts + [0..T)`` (``starts`` is the cached token count, a multiple of
    ``block_size``), gathers the full context from the block table exactly
    like decode, overwrites the tail's in-context slots with fresh K/V
    before the joint causal attention (so tail token j sees the cached
    prefix plus tail tokens <= j — the batched pass is mathematically the
    sequential one), scatters the tail's blocks into the pool at table
    columns ``starts//block_size + j``, and returns ``(kpool, vpool,
    logits)`` at each row's true last tail token (``lens - 1``). Shared
    prefix blocks sit BELOW every written column, so a sharer's tail
    prefill can never touch a peer's mapped block. Rows whose tail bucket
    overshoots the table (or padding rows) write to the trash block."""
    KV, D = arch["kv_heads"], arch["head_dim"]
    if T_bucket % block_size:
        raise ValueError(
            f"tail-prefill bucket {T_bucket} must be a multiple of "
            f"block_size {block_size}"
        )
    nb = T_bucket // block_size
    T_pad = block_size * max_blocks

    def prefill(params, ids, starts, lens, tables, kpool, vpool):
        layer_ws = params["layers"]
        x = arch["embed_tail"](params, ids, starts)
        posm = starts[:, None] + jnp.arange(T_bucket)[None, :]  # (B, T)
        live = jnp.arange(T_pad)[None, None, :] <= posm[:, :, None]  # (B,T,Tp)
        cols = (starts // block_size)[:, None] + jnp.arange(nb)[None, :]
        bids = jnp.take_along_axis(
            tables, jnp.minimum(cols, max_blocks - 1), axis=1)
        bids = jnp.where(cols < max_blocks, bids, 0)  # 0 = trash block
        # gathers hoisted above the scatter chain (see build_paged_decode):
        # avoids a whole-pool copy-on-write per layer
        ctx = [(kpool[li][tables].reshape(B, T_pad, KV, D),
                vpool[li][tables].reshape(B, T_pad, KV, D))
               for li in range(len(layer_ws))]
        for li, w in enumerate(layer_ws):
            x, k_new, v_new = arch["block_tail"](w, x, ctx[li][0], ctx[li][1],
                                                 live, starts)
            kpool = kpool.at[li, bids].set(
                k_new.reshape(B, nb, block_size, KV, D))
            vpool = vpool.at[li, bids].set(
                v_new.reshape(B, nb, block_size, KV, D))
        logits = arch["head_rows"](params, x, lens - 1)
        return kpool, vpool, logits

    return prefill


def build_paged_spec_decode(arch, B, k, block_size, max_blocks):
    """Speculative verify: ONE batched paged-decode step that feeds k+1
    tokens per row — the row's pending next-input token followed by k
    drafted tokens — and returns the target model's greedy continuation at
    EVERY fed position.

    The returned pure fn
    ``step(params, kpool, vpool, tables, pos, toks, temps, key)`` takes
    ``toks`` (B, k+1) fed at absolute positions ``pos + [0..k]``, gathers
    the paged context, overwrites the k+1 in-context slots with fresh K/V
    before the joint causal attention (feed j attends to the cache plus
    feeds <= j, so position j's logits are exactly what j sequential decode
    steps would produce — the bit-identity guarantee), scatters all k+1
    fresh K/V into the pool, and returns ``(kpool, vpool, greedy, sampled)``
    with ``greedy`` (B, k+1) argmax rows and ``sampled`` (B,) drawn from the
    j=0 logits at ``temps`` (sampling rows accept no drafts; their one
    token per step matches plain decode's behavior). The host accepts the
    longest prefix where ``greedy[:, j-1] == toks[:, j]`` and emits
    ``greedy[:, :m+1]`` — K/V written for rejected feeds is dead weight
    the next step's feeds overwrite before any read (position p only
    becomes attendable by a LATER feed, which re-writes slot p first)."""
    KV, D = arch["kv_heads"], arch["head_dim"]
    T = k + 1
    T_pad = block_size * max_blocks

    def step(params, kpool, vpool, tables, pos, toks, temps, key):
        layer_ws = params["layers"]
        x = arch["embed_tail"](params, toks, pos)
        posm = pos[:, None] + jnp.arange(T)[None, :]  # (B, k+1)
        live = jnp.arange(T_pad)[None, None, :] <= posm[:, :, None]
        cols = posm // block_size
        bids = jnp.take_along_axis(
            tables, jnp.minimum(cols, max_blocks - 1), axis=1)
        bids = jnp.where(cols < max_blocks, bids, 0)  # 0 = trash block
        offs = posm % block_size
        # gathers hoisted above the scatter chain (see build_paged_decode):
        # avoids a whole-pool copy-on-write per layer
        ctx = [(kpool[li][tables].reshape(B, T_pad, KV, D),
                vpool[li][tables].reshape(B, T_pad, KV, D))
               for li in range(len(layer_ws))]
        for li, w in enumerate(layer_ws):
            x, k_new, v_new = arch["block_tail"](w, x, ctx[li][0], ctx[li][1],
                                                 live, pos)
            kpool = kpool.at[li, bids, offs].set(k_new)
            vpool = vpool.at[li, bids, offs].set(v_new)
        logits = arch["head_all"](params, x)  # (B, k+1, V)
        greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        scaled = (logits[:, 0]
                  / jnp.maximum(temps, 1e-6)[:, None]).astype(jnp.float32)
        sampled = jax.random.categorical(key, scaled, axis=-1).astype(jnp.int32)
        return kpool, vpool, greedy, sampled

    return step


# ---------------------------------------------------------------------------
# Tensor-parallel serving programs (mesh-native engine)
# ---------------------------------------------------------------------------
# The serving engine shards attention heads, the FFN columns, the LM head,
# and the paged KV pool over a "tp" mesh axis via shard_map. The sharding is
# CONCAT-partitioned, never sum-partitioned: every weight matrix that is
# split is split by OUTPUT columns (heads / FFN features / vocab rows), each
# device computes its column slice of the activation, and the tp boundary is
# an all_gather that concatenates the slices back in order. A column slice
# of a matmul output is the same per-element dot products the single-chip
# program computes, and the post-gather matmuls (attention proj / FFN down /
# argmax) run replicated on identical inputs — so greedy decode is
# bit-identical to the single-chip engine, which a psum of partial products
# could never guarantee. Embeddings and norms stay replicated (tiny); GPT's
# tied head gets its OWN vocab-row-sharded copy of wte while the replicated
# wte keeps serving the embedding lookup. The host-side block tables,
# PagePool bookkeeping, and scheduler state stay replicated — only the
# device KV arrays are sharded (on the kv-heads axis), so pool conservation,
# prefix-cache chaining, snapshot/adopt, and preemption are unchanged.

_INT8_TAG = "__int8__"  # serving/int8.quantize_params leaf encoding


def _tp_dims(arch_key):
    """(kind, H, KV, D, L, theta, eps) from a decode-state ``arch_key``."""
    if arch_key[0] == "gpt":
        _, H, D, L = arch_key
        return "gpt", H, H, D, L, None, None
    _, H, KV, D, L, theta, eps = arch_key
    return "llama", H, KV, D, L, theta, eps


def _tp_leaf(leaf, fn):
    """Apply ``fn`` to a weight leaf, looking through the int8 tagged-dict
    encoding. The scale is per-TENSOR, so slice-then-dequantize is bitwise
    dequantize-then-slice — an int8 engine shards the int8 bytes and
    dequantizes inside the shard_map body."""
    if isinstance(leaf, dict) and _INT8_TAG in leaf:
        return {_INT8_TAG: fn(leaf[_INT8_TAG]), "scale": leaf["scale"]}
    return fn(leaf)


def _tp_shape(leaf):
    if isinstance(leaf, dict) and _INT8_TAG in leaf:
        return leaf[_INT8_TAG].shape
    return leaf.shape


def tp_validate(arch_key, params, tp):
    """Shard-divisibility requirements for a tp degree; returns
    ``(ffn_width, vocab)``. Heads, kv heads, and the FFN width must divide
    evenly (the vocab is zero-padded to a tp multiple instead — padded
    logits are sliced off after the gather, so they can never win argmax)."""
    kind, H, KV, D, L, _, _ = _tp_dims(arch_key)
    ffn = _tp_shape(params["layers"][0]["up_w"])[1]
    vocab = (_tp_shape(params["wte"])[0] if kind == "gpt"
             else _tp_shape(params["head_w"])[1])
    for name, n in (("attention heads", H), ("kv heads", KV),
                    ("ffn width", ffn)):
        if n % tp:
            raise ValueError(
                f"serving: tp={tp} must divide the model's {name} ({n})")
    return ffn, vocab


def tp_pack_params(arch_key, params, tp):
    """Host-side split of a decode weight tree (float or int8-tagged) into
    ``({"rep": replicated_tree, "shard": stacked_tree}, vocab)``.

    ``shard`` holds, per weight, the tp per-device column slices stacked on
    a NEW leading axis (tp, ...) — placed with ``P("tp")`` the leading axis
    shards one standard-layout slice per device, and the shard_map body
    squeezes it with ``leaf[0]``. GPT's fused qkv is sliced through its
    (H·D, 3, H, D) view so each device owns whole (q, k, v) triples for its
    heads; the head weight is vocab-sliced after zero-padding the vocab to a
    tp multiple."""
    kind, H, KV, D, L, _, _ = _tp_dims(arch_key)
    ffn, vocab = tp_validate(arch_key, params, tp)
    Hl, KVl, Fl = H // tp, KV // tp, ffn // tp
    HD = H * D
    vp = -(-vocab // tp) * tp
    Vl = vp // tp

    def pad_vocab(a, axis):
        if vp == vocab:
            return a
        width = [(0, 0)] * a.ndim
        width[axis] = (0, vp - vocab)
        return jnp.pad(a, width)

    def dev_tree(d):
        if kind == "gpt":
            head = _tp_leaf(params["wte"], lambda a: pad_vocab(a, 0)[
                d * Vl:(d + 1) * Vl])
            layers = [{
                "qkv_w": _tp_leaf(w["qkv_w"], lambda a: a.reshape(
                    HD, 3, H, D)[:, :, d * Hl:(d + 1) * Hl].reshape(
                        HD, 3 * Hl * D)),
                "qkv_b": w["qkv_b"].reshape(3, H, D)[
                    :, d * Hl:(d + 1) * Hl].reshape(-1),
                "up_w": _tp_leaf(w["up_w"], lambda a: a[:, d * Fl:(d + 1) * Fl]),
                "up_b": w["up_b"][d * Fl:(d + 1) * Fl],
            } for w in params["layers"]]
        else:
            head = _tp_leaf(params["head_w"], lambda a: pad_vocab(a, 1)[
                :, d * Vl:(d + 1) * Vl])
            layers = [{
                "q_w": _tp_leaf(w["q_w"], lambda a: a.reshape(HD, H, D)[
                    :, d * Hl:(d + 1) * Hl].reshape(HD, Hl * D)),
                "k_w": _tp_leaf(w["k_w"], lambda a: a.reshape(HD, KV, D)[
                    :, d * KVl:(d + 1) * KVl].reshape(HD, KVl * D)),
                "v_w": _tp_leaf(w["v_w"], lambda a: a.reshape(HD, KV, D)[
                    :, d * KVl:(d + 1) * KVl].reshape(HD, KVl * D)),
                "gate_w": _tp_leaf(w["gate_w"],
                                   lambda a: a[:, d * Fl:(d + 1) * Fl]),
                "up_w": _tp_leaf(w["up_w"], lambda a: a[:, d * Fl:(d + 1) * Fl]),
            } for w in params["layers"]]
        return {"head_w": head, "layers": layers}

    if kind == "gpt":
        rep = {k: params[k] for k in ("wte", "wpe", "lnf_w", "lnf_b")}
        rep_keys = ("ln1_w", "ln1_b", "proj_w", "proj_b", "ln2_w", "ln2_b",
                    "down_w", "down_b")
    else:
        rep = {k: params[k] for k in ("wte", "lnf_w")}
        rep_keys = ("ln1_w", "o_w", "ln2_w", "down_w")
    rep["layers"] = [{k: w[k] for k in rep_keys} for w in params["layers"]]
    devs = [dev_tree(d) for d in range(tp)]
    shard = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *devs)
    return {"rep": rep, "shard": shard}, vocab


def tp_collective_bytes(arch_key, params, B, tp):
    """Per-decode-step tensor-parallel all_gather wire bytes as
    ``(fp32_bytes, int8_bytes)`` — the payload crossing the tp boundary per
    step (attention output + FFN intermediate per layer, plus the padded
    logits), counted over all devices. The int8 figure includes the f32
    blockwise scales (one per 128 elements, per-device payload padded to a
    block multiple) — the wire cost the EQuARX-style quantized-collective
    flag actually pays."""
    kind, H, KV, D, L, _, _ = _tp_dims(arch_key)
    ffn, vocab = tp_validate(arch_key, params, tp)
    vp = -(-vocab // tp) * tp
    sizes = [B * H * D, B * ffn] * L + [B * vp]

    def wire(n, int8):
        if not int8:
            return n * 4
        blocks = -(-(n // tp) // 128)
        return tp * blocks * (128 * 1 + 4)

    return (sum(wire(n, False) for n in sizes),
            sum(wire(n, True) for n in sizes))


def _tp_gather(y, quantized):
    """Concat-partitioned tp boundary: all_gather the column shards along
    the last axis. Bitwise exact — every element of the gathered tensor is
    the very dot product the single-chip program computes, just computed on
    one device and copied. With ``quantized`` (FLAGS_serve_tp_int8) the
    payload crosses the wire as blockwise int8 + f32 scales (EQuARX-style,
    ~3.9x fewer bytes, LOSSY — greedy tokens may differ)."""
    if not quantized:
        return lax.all_gather(y, "tp", axis=y.ndim - 1, tiled=True)
    from ..distributed.collective import (blockwise_dequantize,
                                          blockwise_quantize)

    flat = y.reshape(-1)
    m = flat.shape[0]
    pad = -m % 128
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
    q, s = blockwise_quantize(flat)
    qg = lax.all_gather(q, "tp")  # (tp, blocks, 128) int8
    sg = lax.all_gather(s, "tp")
    parts = [blockwise_dequantize(qg[i], sg[i], y.dtype)[:m].reshape(y.shape)
             for i in range(qg.shape[0])]
    return jnp.concatenate(parts, axis=y.ndim - 1)


def _tp_arch(arch_key, tp, vocab, int8_wire):
    """Per-device layer drivers for the tp programs: local column-sharded
    projections + local grouped attention, an all_gather at the attention
    and FFN boundaries, replicated second matmuls. Mirrors the single-chip
    arch plugs op for op so the concat of the shards is bitwise the
    single-chip activation."""
    kind, H, KV, D, L, theta, eps = _tp_dims(arch_key)
    Hl, KVl = H // tp, KV // tp
    rep = H // KV  # GQA group width is tp-invariant (both axes sharded)

    def embed_prompt(rw, ids, T0):
        if kind == "gpt":
            return rw["wte"][ids] + rw["wpe"][jnp.arange(T0)][None]
        return rw["wte"][ids]

    def embed_rows(rw, toks, pos):
        if kind == "gpt":
            return rw["wte"][toks][:, None] + rw["wpe"][pos][:, None]
        return rw["wte"][toks][:, None]

    def embed_tail(rw, ids, starts):
        if kind == "gpt":
            T = ids.shape[1]
            pos = starts[:, None] + jnp.arange(T)[None, :]
            return rw["wte"][ids] + rw["wpe"][pos]
        return rw["wte"][ids]

    def qkv(rwl, swl, x, posm):
        # local projections: x (B,T,H·D) replicated -> q (B,T,Hl,D),
        # k/v (B,T,KVl,D) — column slices of the single-chip projections
        B, T = x.shape[0], x.shape[1]
        if kind == "gpt":
            h = _ln(x, rwl["ln1_w"], rwl["ln1_b"])
            qkv_ = (h @ swl["qkv_w"] + swl["qkv_b"]).reshape(B, T, 3, Hl, D)
            return qkv_[:, :, 0], qkv_[:, :, 1], qkv_[:, :, 2]
        h = _rms(x, rwl["ln1_w"], eps)
        q = (h @ swl["q_w"]).reshape(B, T, Hl, D)
        k = (h @ swl["k_w"]).reshape(B, T, KVl, D)
        v = (h @ swl["v_w"]).reshape(B, T, KVl, D)
        return _rope_grid(q, posm, theta), _rope_grid(k, posm, theta), v

    def post_attn(rwl, swl, x, o):
        # o (B,T,Hl·D) local attention read -> gathered full heads, then
        # the replicated proj/down matmuls (identical inputs everywhere)
        o = _tp_gather(o, int8_wire)
        if kind == "gpt":
            x = x + (o @ rwl["proj_w"] + rwl["proj_b"])
            h2 = _ln(x, rwl["ln2_w"], rwl["ln2_b"])
            ff = _tp_gather(jax.nn.gelu(h2 @ swl["up_w"] + swl["up_b"],
                                        approximate=True), int8_wire)
            return x + (ff @ rwl["down_w"] + rwl["down_b"])
        x = x + o @ rwl["o_w"]
        h2 = _rms(x, rwl["ln2_w"], eps)
        ff = _tp_gather(jax.nn.silu(h2 @ swl["gate_w"]) * (h2 @ swl["up_w"]),
                        int8_wire)
        return x + ff @ rwl["down_w"]

    def layer_rows(rwl, swl, x, k_ctx, v_ctx, live, pos):
        # decode mirror of block_rows against the gathered local-shard ctx
        B = x.shape[0]
        rows_i = jnp.arange(B)
        q, k, v = qkv(rwl, swl, x, pos[:, None])
        k_new, v_new = k[:, 0], v[:, 0]
        kc = k_ctx.at[rows_i, pos].set(k_new)
        vc = v_ctx.at[rows_i, pos].set(v_new)
        o = _grouped_attention(q, kc, vc, live[:, None, None, None, :], rep)
        return post_attn(rwl, swl, x, o), k_new, v_new

    def layer_tail(rwl, swl, x, k_ctx, v_ctx, live, starts):
        # multi-token mirror of block_tail (tail prefill / chunked prefill)
        B, T = x.shape[0], x.shape[1]
        rows_i = jnp.arange(B)[:, None]
        posm = starts[:, None] + jnp.arange(T)[None, :]
        q, k, v = qkv(rwl, swl, x, posm)
        kc = k_ctx.at[rows_i, posm].set(k)
        vc = v_ctx.at[rows_i, posm].set(v)
        o = _grouped_attention(q, kc, vc, live[:, None, None], rep)
        return post_attn(rwl, swl, x, o), k, v

    def layer_full(rwl, swl, x):
        # dense causal prefill mirror of arch["block"]'s prefill branch
        B, T = x.shape[0], x.shape[1]
        posm = jnp.broadcast_to(jnp.arange(T), (B, T))
        q, k, v = qkv(rwl, swl, x, posm)
        live = jnp.tril(jnp.ones((T, T), bool))[None, None, None]
        o = _grouped_attention(q, k, v, live, rep)
        return post_attn(rwl, swl, x, o), k, v

    def head_rows(rw, sw, x, idx):
        if kind == "gpt":
            h = _ln(x, rw["lnf_w"], rw["lnf_b"])
        else:
            h = _rms(x, rw["lnf_w"], eps)
        rows = jnp.take_along_axis(h, idx[:, None, None], axis=1)[:, 0]
        loc = rows @ (sw["head_w"].T if kind == "gpt" else sw["head_w"])
        # padded vocab columns are sliced off post-gather (static slice)
        return _tp_gather(loc, int8_wire)[:, :vocab]

    return {"embed_prompt": embed_prompt, "embed_rows": embed_rows,
            "embed_tail": embed_tail, "qkv": qkv, "post_attn": post_attn,
            "layer_rows": layer_rows, "layer_tail": layer_tail,
            "layer_full": layer_full, "head_rows": head_rows,
            "n_layers": L, "kv_local": KVl, "head_dim": D}


def _tp_pool_spec():
    from jax.sharding import PartitionSpec as P

    return P(None, None, None, "tp", None)


def tp_pool_sharding(mesh):
    """NamedSharding splitting a (L, NB, BS, KV, D) pool on its kv-heads
    axis — each device owns heads/tp of EVERY block, so the replicated
    host-side block tables index every shard identically."""
    from jax.sharding import NamedSharding

    return NamedSharding(mesh, _tp_pool_spec())


def tp_param_shardings(mesh):
    """(replicated, stacked-shard) NamedShardings for tp_pack_params trees."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    return NamedSharding(mesh, P()), NamedSharding(mesh, P("tp"))


def _tp_shard_map(body, mesh, in_specs, out_specs):
    from ..core import compat

    return compat.shard_map(body, mesh=mesh, in_specs=in_specs,
                            out_specs=out_specs,
                            **compat.shard_map_check_kwargs(False))


def _tp_local(shard_tree, dtype):
    """Squeeze the stacked (1, ...) local view and dequantize int8 leaves
    INSIDE the shard_map body (per-tensor scales make it bitwise equal to
    dequantize-then-slice)."""
    from ..serving.int8 import dequantize_tree

    sq = jax.tree_util.tree_map(lambda a: a[0], shard_tree)
    return dequantize_tree(sq, dtype)


def build_tp_paged_decode(arch_key, B, block_size, max_blocks, mesh, vocab,
                          dtype, use_kernel=False, int8_wire=False):
    """Tensor-parallel ``build_paged_decode`` (or ``_kernel`` with
    ``use_kernel``): same step signature with the packed param tree from
    :func:`tp_pack_params` in place of ``params``, kpool/vpool tp-sharded on
    the kv-heads axis, tables/pos/toks/temps/key replicated. Greedy tokens
    are bit-identical to the single-chip builders (see the section comment);
    the paged-attention kernel path works unchanged on the local shard —
    its block DMA reads local (NB, BS, KVl, D) pools and H/KV keeps the
    same GQA ratio."""
    from jax.sharding import PartitionSpec as P

    tp = mesh.shape["tp"]
    arch = _tp_arch(arch_key, tp, vocab, int8_wire)
    L, KVl, D = arch["n_layers"], arch["kv_local"], arch["head_dim"]
    T_pad = block_size * max_blocks
    pool_s = _tp_pool_spec()

    def body(rep_tree, shard_tree, kpool, vpool, tables, pos, toks, temps,
             key):
        from ..serving.int8 import dequantize_tree

        rw = dequantize_tree(rep_tree, dtype)
        sw = _tp_local(shard_tree, dtype)
        x = arch["embed_rows"](rw, toks, pos)
        bids = jnp.take_along_axis(tables, (pos // block_size)[:, None],
                                   axis=1)[:, 0]
        offs = pos % block_size
        if use_kernel:
            from ..ops.kernels import paged_attention_rows

            for li in range(L):
                rwl = rw["layers"][li]
                swl = sw["layers"][li]
                q, k, v = arch["qkv"](rwl, swl, x, pos[:, None])
                kpool = kpool.at[li, bids, offs].set(k[:, 0])
                vpool = vpool.at[li, bids, offs].set(v[:, 0])
                o = paged_attention_rows(q[:, 0], kpool[li], vpool[li],
                                         tables, pos)
                x = arch["post_attn"](rwl, swl, x, o[:, None])
        else:
            live = jnp.arange(T_pad)[None, :] <= pos[:, None]
            # gathers hoisted above the scatter chain (see build_paged_decode)
            ctx = [(kpool[li][tables].reshape(B, T_pad, KVl, D),
                    vpool[li][tables].reshape(B, T_pad, KVl, D))
                   for li in range(L)]
            for li in range(L):
                x, k_new, v_new = arch["layer_rows"](
                    rw["layers"][li], sw["layers"][li], x,
                    ctx[li][0], ctx[li][1], live, pos)
                kpool = kpool.at[li, bids, offs].set(k_new)
                vpool = vpool.at[li, bids, offs].set(v_new)
        logits = arch["head_rows"](rw, sw, x, jnp.zeros((B,), jnp.int32))
        greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        scaled = (logits / jnp.maximum(temps, 1e-6)[:, None]).astype(
            jnp.float32)
        sampled = jax.random.categorical(key, scaled, axis=-1).astype(
            jnp.int32)
        nxt = jnp.where(temps > 0, sampled, greedy)
        return kpool, vpool, nxt

    wrapped = _tp_shard_map(
        body, mesh,
        (P(), P("tp"), pool_s, pool_s, P(), P(), P(), P(), P()),
        (pool_s, pool_s, P()))

    def step(packed, kpool, vpool, tables, pos, toks, temps, key):
        return wrapped(packed["rep"], packed["shard"], kpool, vpool, tables,
                       pos, toks, temps, key)

    return step


def build_tp_paged_prefill(arch_key, B, T_bucket, block_size, max_blocks,
                           mesh, vocab, dtype, int8_wire=False):
    """Tensor-parallel ``build_paged_prefill``: same signature with the
    packed param tree; each device scatters its local (B, nb, BS, KVl, D)
    K/V shard into its pool shard at the REPLICATED block table."""
    from jax.sharding import PartitionSpec as P

    tp = mesh.shape["tp"]
    if T_bucket % block_size:
        raise ValueError(
            f"prefill bucket {T_bucket} must be a multiple of block_size "
            f"{block_size}")
    nb = T_bucket // block_size
    if nb > max_blocks:
        raise ValueError("prefill bucket exceeds max sequence blocks")
    arch = _tp_arch(arch_key, tp, vocab, int8_wire)
    L, KVl, D = arch["n_layers"], arch["kv_local"], arch["head_dim"]
    pool_s = _tp_pool_spec()

    def body(rep_tree, shard_tree, ids, lens, tables, kpool, vpool):
        from ..serving.int8 import dequantize_tree

        rw = dequantize_tree(rep_tree, dtype)
        sw = _tp_local(shard_tree, dtype)
        x = arch["embed_prompt"](rw, ids, T_bucket)
        tb = tables[:, :nb]
        for li in range(L):
            x, k, v = arch["layer_full"](rw["layers"][li], sw["layers"][li],
                                         x)
            kpool = kpool.at[li, tb].set(
                k.reshape(B, nb, block_size, KVl, D))
            vpool = vpool.at[li, tb].set(
                v.reshape(B, nb, block_size, KVl, D))
        logits = arch["head_rows"](rw, sw, x, lens - 1)
        return kpool, vpool, logits

    wrapped = _tp_shard_map(
        body, mesh, (P(), P("tp"), P(), P(), P(), pool_s, pool_s),
        (pool_s, pool_s, P()))

    def prefill(packed, ids, lens, tables, kpool, vpool):
        return wrapped(packed["rep"], packed["shard"], ids, lens, tables,
                       kpool, vpool)

    return prefill


def build_tp_paged_tail_prefill(arch_key, B, T_bucket, block_size, max_blocks,
                                mesh, vocab, dtype, int8_wire=False):
    """Tensor-parallel ``build_paged_tail_prefill`` — also the chunked-
    prefill workhorse: a chunk at a block-aligned offset IS a tail feed at
    absolute positions, reading the earlier chunks' K/V through the block
    table and writing its own through the same paged scatter."""
    from jax.sharding import PartitionSpec as P

    tp = mesh.shape["tp"]
    if T_bucket % block_size:
        raise ValueError(
            f"tail-prefill bucket {T_bucket} must be a multiple of "
            f"block_size {block_size}")
    nb = T_bucket // block_size
    T_pad = block_size * max_blocks
    arch = _tp_arch(arch_key, tp, vocab, int8_wire)
    L, KVl, D = arch["n_layers"], arch["kv_local"], arch["head_dim"]
    pool_s = _tp_pool_spec()

    def body(rep_tree, shard_tree, ids, starts, lens, tables, kpool, vpool):
        from ..serving.int8 import dequantize_tree

        rw = dequantize_tree(rep_tree, dtype)
        sw = _tp_local(shard_tree, dtype)
        x = arch["embed_tail"](rw, ids, starts)
        posm = starts[:, None] + jnp.arange(T_bucket)[None, :]
        live = jnp.arange(T_pad)[None, None, :] <= posm[:, :, None]
        cols = (starts // block_size)[:, None] + jnp.arange(nb)[None, :]
        bids = jnp.take_along_axis(
            tables, jnp.minimum(cols, max_blocks - 1), axis=1)
        bids = jnp.where(cols < max_blocks, bids, 0)  # 0 = trash block
        ctx = [(kpool[li][tables].reshape(B, T_pad, KVl, D),
                vpool[li][tables].reshape(B, T_pad, KVl, D))
               for li in range(L)]
        for li in range(L):
            x, k_new, v_new = arch["layer_tail"](
                rw["layers"][li], sw["layers"][li], x,
                ctx[li][0], ctx[li][1], live, starts)
            kpool = kpool.at[li, bids].set(
                k_new.reshape(B, nb, block_size, KVl, D))
            vpool = vpool.at[li, bids].set(
                v_new.reshape(B, nb, block_size, KVl, D))
        logits = arch["head_rows"](rw, sw, x, lens - 1)
        return kpool, vpool, logits

    wrapped = _tp_shard_map(
        body, mesh, (P(), P("tp"), P(), P(), P(), P(), pool_s, pool_s),
        (pool_s, pool_s, P()))

    def prefill(packed, ids, starts, lens, tables, kpool, vpool):
        return wrapped(packed["rep"], packed["shard"], ids, starts, lens,
                       tables, kpool, vpool)

    return prefill


def build_window_draft(arch, B, W, k):
    """Model drafter: k greedy proposals per row from a SMALL same-family
    model over a dense sliding window of the newest ``W`` tokens.

    The returned pure fn ``draft(params, ids, lens)`` prefills the window
    (``ids`` (B, W) left-aligned, ``lens`` real lengths in [1, W]) with
    window-relative positions — an approximation for position-embedding
    models once the stream outgrows the window, which only costs acceptance
    rate, never correctness: the target verifies every proposal — then runs
    k single-token greedy steps against a dense per-row cache and returns
    the proposals (B, k) int32."""
    KV, D = arch["kv_heads"], arch["head_dim"]
    T_max = W + k

    def draft(params, ids, lens):
        layer_ws = params["layers"]
        rows = jnp.arange(B)
        x = arch["embed_prompt"](params, ids, W)
        caches = []
        for w in layer_ws:
            x, (kk, vv) = arch["block"](w, x)
            kc = jnp.zeros((B, T_max, KV, D), x.dtype).at[:, :W].set(kk)
            vc = jnp.zeros((B, T_max, KV, D), x.dtype).at[:, :W].set(vv)
            caches.append((kc, vc))
        logits = arch["head_rows"](params, x, lens - 1)
        out = jnp.zeros((B, k), jnp.int32)
        for j in range(k):
            nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            out = out.at[:, j].set(nxt)
            pos = lens + j  # per-row write position of the new token
            x = arch["embed_rows"](params, nxt, pos)
            live = jnp.arange(T_max)[None, :] <= pos[:, None]
            new_caches = []
            for w, (kc, vc) in zip(layer_ws, caches):
                x, k_new, v_new = arch["block_rows"](w, x, kc, vc, live, pos)
                new_caches.append((kc.at[rows, pos].set(k_new),
                                   vc.at[rows, pos].set(v_new)))
            caches = new_caches
            logits = arch["head"](params, x)
        return out

    return draft
