"""Model zoo (language models; vision models live in paddle_tpu.vision.models)."""
from .gpt import GPTConfig, GPTModel, GPTForPretraining, gpt3_1p3b, gpt_tiny  # noqa: F401
from .llama import LlamaConfig, LlamaModel, LlamaForCausalLM, llama_7b, llama_tiny  # noqa: F401
from .ernie import ErnieConfig, ErnieModel, ErnieForPretraining, ernie_3_base  # noqa: F401
