"""GPT model family — the flagship training config (BASELINE: GPT-3 1.3B).

Parity: the reference trains GPT via PaddleNLP on Fleet hybrid parallel
(BASELINE.json); the in-tree building blocks are the fused transformer ops
(``paddle/fluid/operators/fused/fused_attention_op.cc``) and the Megatron
layers (``fleet/meta_parallel/parallel_layers/mp_layers.py``). This model is
built TPU-first:

 * every matmul is a Megatron-shardable layer — weights carry PartitionSpecs
   ("mp" column/row sharding) that GSPMD partitions when compiled on a mesh;
 * sequence-parallel activations: hidden states carry ("dp", "sp") sharding
   constraints so long sequences shard over the 'sp' axis;
 * attention runs through the fused scaled_dot_product_attention functional
   (Pallas flash kernel on TPU) or ring attention under explicit shard_map;
 * the decoder stack is uniform — pipeline-stageable by construction
   (pp_layers.PipelineLayer segments it; the spmd pipeline stacks it).
"""
from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Optional

import numpy as np

from .. import nn
from ..core.tensor import Tensor
from ..nn import functional as F
from ..distributed.fleet.meta_parallel.mp_layers import (
    ColumnParallelLinear, RowParallelLinear, VocabParallelEmbedding, ParallelCrossEntropy,
)
from ..distributed.sharding_api import shard_tensor

try:
    from jax.sharding import PartitionSpec as P
except Exception:  # pragma: no cover
    P = None


@dataclass
class GPTConfig:
    vocab_size: int = 50304
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: Optional[int] = None
    max_position_embeddings: int = 1024
    hidden_dropout: float = 0.1
    attention_dropout: float = 0.1
    initializer_range: float = 0.02
    use_mp_layers: bool = True  # Megatron-shardable weights (GSPMD specs)
    fused_lm_loss: bool = True  # blockwise head+CE, no (B·T,V) logits tensor
    remat: bool = False  # jax.checkpoint each decoder layer (1.3B-on-a-chip)
    sequence_parallel: bool = False  # annotate activations with 'sp'
    # "auto": ring attention whenever sequence_parallel and the mesh has an
    # 'sp' axis >1 (the long-context path — O(T/sp) memory per device, K/V
    # blocks rotate the ICI ring); "exact"/"flash" force those kernels.
    attention_impl: str = "auto"

    @property
    def ffn_size(self):
        return self.intermediate_size or 4 * self.hidden_size


def _sp_constrain(x, config):
    """Sequence-parallel activation sharding: (B, T, H) → P('dp','sp',None)."""
    if config.sequence_parallel and P is not None:
        try:
            return shard_tensor(x, placement=P("dp", "sp", None))
        except Exception:
            return x
    return x


class GPTAttention(nn.Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        h = config.hidden_size
        self.num_heads = config.num_heads
        self.head_dim = h // config.num_heads
        self.qkv = ColumnParallelLinear(h, 3 * h, has_bias=True, gather_output=False)
        self.proj = RowParallelLinear(h, h, has_bias=True, input_is_parallel=True)
        self.attn_dropout = config.attention_dropout
        if config.attention_impl not in ("auto", "ring", "exact", "flash"):
            raise ValueError(
                f"attention_impl must be auto|ring|exact|flash, got {config.attention_impl!r}"
            )
        self.config = config

    def _ring_mesh(self):
        """The global mesh iff ring attention should run: sequence_parallel
        on, causal, an 'sp' axis of size >1 present, and no attention dropout
        in play (ring, like flash, never materializes the score matrix a
        dropout mask would apply to)."""
        if not self.config.sequence_parallel or self.config.attention_impl not in ("auto", "ring"):
            return None
        if self.attn_dropout and self.training:
            if self.config.attention_impl == "ring":
                raise ValueError(
                    "attention_impl='ring' does not support attention_dropout>0 "
                    "while training; set attention_dropout=0.0"
                )
            return None  # auto: fall back to sdpa so dropout semantics hold
        try:
            from ..distributed.mesh import global_mesh

            mesh = global_mesh()
        except Exception:
            return None
        if mesh is None:
            return None
        sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
        return mesh if sizes.get("sp", 1) > 1 else None

    def _ring_attention(self, q, k, v, mesh):
        """shard_map island inside the GSPMD program: q/k/v (B,T,heads,D) get
        sequence-sharded over 'sp' (batch over 'dp', heads over 'mp' when
        present) and K/V blocks rotate via ppermute — the long-context path
        the reference lacks. Attention dropout is skipped on this path (as in
        flash kernels)."""
        from jax.sharding import PartitionSpec as P

        from ..distributed.mesh import shard_map_compat

        _shard_map, _check = shard_map_compat()
        from ..core.dispatch import eager_call
        from ..distributed.fleet.meta_parallel.sequence_parallel import ring_attention

        sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
        dp = "dp" if sizes.get("dp", 1) > 1 else None
        hp = "mp" if sizes.get("mp", 1) > 1 else None
        spec = P(dp, "sp", hp, None)
        fn = _shard_map(
            lambda a, b, c: ring_attention(a, b, c, "sp", causal=True),
            mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec, **_check,
        )
        return eager_call("ring_attention_spmd", fn, [q, k, v])

    def forward(self, x, attn_mask=None):
        B, T = x.shape[0], x.shape[1]
        qkv = self.qkv(x)  # (B, T, 3H/mp)
        local_h = qkv.shape[-1] // 3
        local_heads = local_h // self.head_dim
        qkv = qkv.reshape([B, T, 3, local_heads, self.head_dim])
        q, k, v = qkv.unbind(axis=2)
        ring_mesh = self._ring_mesh() if attn_mask is None else None
        if ring_mesh is not None:
            out = self._ring_attention(q, k, v, ring_mesh)
        else:
            impl = self.config.attention_impl
            out = F.scaled_dot_product_attention(
                q, k, v, attn_mask=attn_mask, is_causal=attn_mask is None,
                dropout_p=self.attn_dropout, training=self.training,
                impl=impl if impl in ("exact", "flash") else None,
            )
        out = out.reshape([B, T, local_h])
        return self.proj(out)


class GPTMLP(nn.Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        h = config.hidden_size
        self.up = ColumnParallelLinear(h, config.ffn_size, has_bias=True, gather_output=False)
        self.down = RowParallelLinear(config.ffn_size, h, has_bias=True, input_is_parallel=True)

    def forward(self, x):
        return self.down(F.gelu(self.up(x), approximate=True))


class GPTDecoderLayer(nn.Layer):
    """Pre-LN decoder block — the uniform pipeline stage unit."""

    def __init__(self, config: GPTConfig):
        super().__init__()
        self.ln1 = nn.LayerNorm(config.hidden_size, epsilon=1e-5)
        self.attn = GPTAttention(config)
        self.ln2 = nn.LayerNorm(config.hidden_size, epsilon=1e-5)
        self.mlp = GPTMLP(config)
        self.dropout = nn.Dropout(config.hidden_dropout)
        self.config = config

    def forward(self, x, attn_mask=None):
        x = x + self.dropout(self.attn(self.ln1(x), attn_mask))
        x = _sp_constrain(x, self.config)
        x = x + self.dropout(self.mlp(self.ln2(x)))
        return _sp_constrain(x, self.config)


class GPTEmbeddings(nn.Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        init = nn.initializer.Normal(std=config.initializer_range)
        if config.use_mp_layers:
            self.word_embeddings = VocabParallelEmbedding(config.vocab_size, config.hidden_size, weight_attr=init)
        else:
            self.word_embeddings = nn.Embedding(config.vocab_size, config.hidden_size, weight_attr=init)
        self.position_embeddings = nn.Embedding(config.max_position_embeddings, config.hidden_size, weight_attr=init)
        self.dropout = nn.Dropout(config.hidden_dropout)
        self.config = config

    def forward(self, input_ids, position_ids=None):
        from ..ops.creation import arange

        T = input_ids.shape[1]
        if position_ids is None:
            position_ids = arange(T, dtype="int64").unsqueeze(0)
        x = self.word_embeddings(input_ids) + self.position_embeddings(position_ids)
        return _sp_constrain(self.dropout(x), self.config)


class GPTModel(nn.Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = config
        self.embeddings = GPTEmbeddings(config)
        self.layers = nn.LayerList([GPTDecoderLayer(config) for _ in range(config.num_layers)])
        self.final_ln = nn.LayerNorm(config.hidden_size, epsilon=1e-5)

    def forward(self, input_ids, position_ids=None, attn_mask=None):
        x = self.embeddings(input_ids, position_ids)
        if self.config.remat:
            # activation checkpointing: drop per-layer residuals, XLA
            # rematerializes them in the backward (HBM for FLOPs — the
            # single-chip 1.3B training config needs this)
            from ..distributed.fleet.utils import recompute

            for layer in self.layers:
                if attn_mask is None:
                    x = recompute(lambda h, _l=layer: _l(h, None), x)
                else:
                    # mask travels as a tensor ARG (a closed-over tensor would
                    # change the flush-cache key every step and a pending
                    # LazyArray cannot cross the jax.checkpoint boundary)
                    x = recompute(lambda h, m, _l=layer: _l(h, m), x, attn_mask)
        else:
            for layer in self.layers:
                x = layer(x, attn_mask)
        return self.final_ln(x)


class GPTForPretraining(nn.Layer):
    """LM head tied to the word embedding (reference: SharedLayerDesc tying)."""

    def __init__(self, config: GPTConfig):
        super().__init__()
        self.gpt = GPTModel(config)
        self.config = config

    def forward(self, input_ids, position_ids=None, attn_mask=None):
        x = self.gpt(input_ids, position_ids, attn_mask)
        w = self.gpt.embeddings.word_embeddings.weight
        logits = F.linear(x, _transpose(w))
        return logits

    def loss(self, input_ids, labels):
        if getattr(self.config, "fused_lm_loss", True):
            # blockwise fused projection+CE: never materializes the
            # (B·T, vocab) fp32 logits (ops/fused_ce.py) — this is what
            # bounds trainable batch size at V≈50k
            x = self.gpt(input_ids)
            w = self.gpt.embeddings.word_embeddings.weight
            return F.fused_linear_cross_entropy(x, w, labels)
        logits = self(input_ids)
        return F.cross_entropy(
            logits.reshape([-1, logits.shape[-1]]), labels.reshape([-1])
        )

    def generate(self, input_ids, max_new_tokens=32, temperature=1.0, top_k=0,
                 top_p=1.0, eos_token_id=None, do_sample=True, num_beams=1,
                 length_penalty=0.0):
        """KV-cached compiled autoregressive decoding (see
        models/generation.py — prefill + lax.fori_loop sampling in ONE jitted
        program; the reference's top_k/multinomial/beam_search op roles).
        ``num_beams>1`` runs stacked-beam search (beam_search_op role)."""
        from .generation import generate as _generate

        return _generate(
            self, input_ids, max_new_tokens=max_new_tokens,
            temperature=temperature, top_k=top_k, top_p=top_p,
            eos_token_id=eos_token_id, do_sample=do_sample,
            num_beams=num_beams, length_penalty=length_penalty,
        )


def _transpose(w):
    from ..ops.manipulation import transpose

    return transpose(w, [1, 0])


# -- standard configs --------------------------------------------------------
def gpt_tiny(**kw):
    return GPTConfig(
        vocab_size=1024, hidden_size=128, num_layers=4, num_heads=4,
        max_position_embeddings=256, **kw,
    )


def gpt3_1p3b(**kw):
    """GPT-3 1.3B (BASELINE north-star config)."""
    return GPTConfig(
        vocab_size=50304, hidden_size=2048, num_layers=24, num_heads=16,
        max_position_embeddings=2048, **kw,
    )


def gpt3_13b(**kw):
    return GPTConfig(
        vocab_size=50304, hidden_size=5120, num_layers=40, num_heads=40,
        max_position_embeddings=2048, **kw,
    )
