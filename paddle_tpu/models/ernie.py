"""ERNIE (BERT-style bidirectional encoder) — BASELINE config ERNIE-3.0.

Encoder with token/position/segment embeddings, MLM + NSP-style heads;
Megatron-shardable like GPT.
"""
from __future__ import annotations

from dataclasses import dataclass

from .. import nn
from ..nn import functional as F
from ..distributed.fleet.meta_parallel.mp_layers import (
    ColumnParallelLinear, RowParallelLinear, VocabParallelEmbedding,
)


@dataclass
class ErnieConfig:
    vocab_size: int = 40000
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: int = 3072
    max_position_embeddings: int = 2048
    type_vocab_size: int = 4
    hidden_dropout: float = 0.1
    attention_dropout: float = 0.1
    initializer_range: float = 0.02


class ErnieSelfAttention(nn.Layer):
    def __init__(self, config: ErnieConfig):
        super().__init__()
        h = config.hidden_size
        self.num_heads = config.num_heads
        self.head_dim = h // config.num_heads
        self.qkv = ColumnParallelLinear(h, 3 * h, has_bias=True, gather_output=False)
        self.out = RowParallelLinear(h, h, has_bias=True, input_is_parallel=True)
        self.dropout = config.attention_dropout

    def forward(self, x, attn_mask=None):
        B, T = x.shape[0], x.shape[1]
        qkv = self.qkv(x)
        local_h = qkv.shape[-1] // 3
        qkv = qkv.reshape([B, T, 3, local_h // self.head_dim, self.head_dim])
        q, k, v = qkv.unbind(axis=2)
        o = F.scaled_dot_product_attention(
            q, k, v, attn_mask=attn_mask, is_causal=False, dropout_p=self.dropout, training=self.training
        )
        return self.out(o.reshape([B, T, local_h]))


class ErnieLayer(nn.Layer):
    def __init__(self, config: ErnieConfig):
        super().__init__()
        h = config.hidden_size
        self.attn = ErnieSelfAttention(config)
        self.ln1 = nn.LayerNorm(h)
        self.up = ColumnParallelLinear(h, config.intermediate_size, has_bias=True, gather_output=False)
        self.down = RowParallelLinear(config.intermediate_size, h, has_bias=True, input_is_parallel=True)
        self.ln2 = nn.LayerNorm(h)
        self.dropout = nn.Dropout(config.hidden_dropout)

    def forward(self, x, attn_mask=None):
        x = self.ln1(x + self.dropout(self.attn(x, attn_mask)))
        x = self.ln2(x + self.dropout(self.down(F.gelu(self.up(x)))))
        return x


class ErnieModel(nn.Layer):
    def __init__(self, config: ErnieConfig):
        super().__init__()
        self.config = config
        init = nn.initializer.Normal(std=config.initializer_range)
        self.word_emb = VocabParallelEmbedding(config.vocab_size, config.hidden_size, weight_attr=init)
        self.pos_emb = nn.Embedding(config.max_position_embeddings, config.hidden_size, weight_attr=init)
        self.type_emb = nn.Embedding(config.type_vocab_size, config.hidden_size, weight_attr=init)
        self.emb_ln = nn.LayerNorm(config.hidden_size)
        self.dropout = nn.Dropout(config.hidden_dropout)
        self.layers = nn.LayerList([ErnieLayer(config) for _ in range(config.num_layers)])
        self.pooler = nn.Linear(config.hidden_size, config.hidden_size)

    def forward(self, input_ids, token_type_ids=None, position_ids=None, attn_mask=None):
        from ..ops.creation import arange, zeros_like

        T = input_ids.shape[1]
        if position_ids is None:
            position_ids = arange(T, dtype="int64").unsqueeze(0)
        if token_type_ids is None:
            token_type_ids = zeros_like(input_ids)
        x = self.word_emb(input_ids) + self.pos_emb(position_ids) + self.type_emb(token_type_ids)
        x = self.dropout(self.emb_ln(x))
        for layer in self.layers:
            x = layer(x, attn_mask)
        pooled = F.tanh(self.pooler(x[:, 0]))
        return x, pooled


class ErnieForPretraining(nn.Layer):
    def __init__(self, config: ErnieConfig):
        super().__init__()
        self.ernie = ErnieModel(config)
        self.mlm_transform = nn.Linear(config.hidden_size, config.hidden_size)
        self.mlm_ln = nn.LayerNorm(config.hidden_size)
        self.nsp_head = nn.Linear(config.hidden_size, 2)
        self.config = config

    def forward(self, input_ids, token_type_ids=None, attn_mask=None):
        seq, pooled = self.ernie(input_ids, token_type_ids, attn_mask=attn_mask)
        from ..ops.manipulation import transpose

        h = self.mlm_ln(F.gelu(self.mlm_transform(seq)))
        mlm_logits = F.linear(h, transpose(self.ernie.word_emb.weight, [1, 0]))
        nsp_logits = self.nsp_head(pooled)
        return mlm_logits, nsp_logits

    def loss(self, input_ids, mlm_labels, nsp_labels=None):
        mlm_logits, nsp_logits = self(input_ids)
        loss = F.cross_entropy(
            mlm_logits.reshape([-1, mlm_logits.shape[-1]]), mlm_labels.reshape([-1]), ignore_index=-100
        )
        if nsp_labels is not None:
            loss = loss + F.cross_entropy(nsp_logits, nsp_labels)
        return loss


def ernie_3_base(**kw):
    return ErnieConfig(**kw)
