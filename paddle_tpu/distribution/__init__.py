"""paddle.distribution parity (reference python/paddle/distribution/)."""
from __future__ import annotations

import math

import numpy as np
import jax
import jax.numpy as jnp

from ..core import random as random_state
from ..core.tensor import Tensor
from ..core.dispatch import as_tensor, eager_call


class Distribution:
    def sample(self, shape=()):
        raise NotImplementedError

    def entropy(self):
        raise NotImplementedError

    def log_prob(self, value):
        raise NotImplementedError

    def probs(self, value):
        from ..ops.math import exp

        return exp(self.log_prob(value))

    def kl_divergence(self, other):
        raise NotImplementedError


class Uniform(Distribution):
    def __init__(self, low, high, name=None):
        self.low = as_tensor(low)
        self.high = as_tensor(high)

    def sample(self, shape=(), seed=0):
        key = random_state.next_key()
        shape = tuple(shape) + tuple(np.broadcast_shapes(tuple(self.low.shape), tuple(self.high.shape)))
        u = jax.random.uniform(key, shape, dtype=jnp.float32)
        return Tensor(self.low._data + u * (self.high._data - self.low._data))

    def log_prob(self, value):
        return eager_call(
            "uniform_log_prob",
            lambda v, lo, hi: jnp.where(
                (v >= lo) & (v < hi), -jnp.log(hi - lo), -jnp.inf
            ),
            [as_tensor(value), self.low, self.high],
        )

    def entropy(self):
        return eager_call("uniform_entropy", lambda lo, hi: jnp.log(hi - lo), [self.low, self.high])


class Normal(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = as_tensor(loc)
        self.scale = as_tensor(scale)

    def sample(self, shape=(), seed=0):
        key = random_state.next_key()
        shape = tuple(shape) + tuple(np.broadcast_shapes(tuple(self.loc.shape), tuple(self.scale.shape)))
        z = jax.random.normal(key, shape, dtype=jnp.float32)
        return Tensor(self.loc._data + z * self.scale._data)

    def log_prob(self, value):
        return eager_call(
            "normal_log_prob",
            lambda v, m, s: -((v - m) ** 2) / (2 * s**2) - jnp.log(s) - 0.5 * math.log(2 * math.pi),
            [as_tensor(value), self.loc, self.scale],
        )

    def entropy(self):
        return eager_call(
            "normal_entropy", lambda s: 0.5 + 0.5 * math.log(2 * math.pi) + jnp.log(s), [self.scale]
        )

    def kl_divergence(self, other):
        return eager_call(
            "normal_kl",
            lambda m1, s1, m2, s2: jnp.log(s2 / s1) + (s1**2 + (m1 - m2) ** 2) / (2 * s2**2) - 0.5,
            [self.loc, self.scale, other.loc, other.scale],
        )


class Categorical(Distribution):
    def __init__(self, logits, name=None):
        self.logits = as_tensor(logits)

    def sample(self, shape=()):
        key = random_state.next_key()
        out = jax.random.categorical(key, self.logits._data, shape=tuple(shape) + tuple(self.logits.shape[:-1]))
        return Tensor(out.astype(np.int64))

    def log_prob(self, value):
        return eager_call(
            "cat_log_prob",
            lambda lg, v: jnp.take_along_axis(
                jax.nn.log_softmax(lg, axis=-1), v.astype(jnp.int32)[..., None], axis=-1
            )[..., 0],
            [self.logits, as_tensor(value)],
        )

    def entropy(self):
        return eager_call(
            "cat_entropy",
            lambda lg: -jnp.sum(jax.nn.softmax(lg, -1) * jax.nn.log_softmax(lg, -1), axis=-1),
            [self.logits],
        )


class Bernoulli(Distribution):
    def __init__(self, probs, name=None):
        self.probs_t = as_tensor(probs)

    def sample(self, shape=()):
        key = random_state.next_key()
        return Tensor(
            jax.random.bernoulli(key, self.probs_t._data, tuple(shape) + tuple(self.probs_t.shape)).astype(np.float32)
        )

    def log_prob(self, value):
        return eager_call(
            "bern_log_prob",
            lambda p, v: v * jnp.log(jnp.clip(p, 1e-12)) + (1 - v) * jnp.log(jnp.clip(1 - p, 1e-12)),
            [self.probs_t, as_tensor(value)],
        )


def kl_divergence(p, q):
    return p.kl_divergence(q)
