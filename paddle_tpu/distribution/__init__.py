"""paddle.distribution parity (reference python/paddle/distribution/)."""
from __future__ import annotations

import math

import numpy as np
import jax
import jax.numpy as jnp

from ..core.lazy import concrete as _concrete

from ..core import random as random_state
from ..core.tensor import Tensor
from ..core.dispatch import as_tensor, eager_call


class Distribution:
    def sample(self, shape=()):
        raise NotImplementedError

    def entropy(self):
        raise NotImplementedError

    def log_prob(self, value):
        raise NotImplementedError

    def probs(self, value):
        from ..ops.math import exp

        return exp(self.log_prob(value))

    def kl_divergence(self, other):
        raise NotImplementedError


class Uniform(Distribution):
    def __init__(self, low, high, name=None):
        self.low = as_tensor(low)
        self.high = as_tensor(high)

    def sample(self, shape=(), seed=0):
        key = random_state.next_key()
        shape = tuple(shape) + tuple(np.broadcast_shapes(tuple(self.low.shape), tuple(self.high.shape)))
        u = jax.random.uniform(key, shape, dtype=jnp.float32)
        return Tensor(self.low._data + u * (self.high._data - self.low._data))

    def log_prob(self, value):
        return eager_call(
            "uniform_log_prob",
            lambda v, lo, hi: jnp.where(
                (v >= lo) & (v < hi), -jnp.log(hi - lo), -jnp.inf
            ),
            [as_tensor(value), self.low, self.high],
        )

    def entropy(self):
        return eager_call("uniform_entropy", lambda lo, hi: jnp.log(hi - lo), [self.low, self.high])


class Normal(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = as_tensor(loc)
        self.scale = as_tensor(scale)

    def sample(self, shape=(), seed=0):
        key = random_state.next_key()
        shape = tuple(shape) + tuple(np.broadcast_shapes(tuple(self.loc.shape), tuple(self.scale.shape)))
        z = jax.random.normal(key, shape, dtype=jnp.float32)
        return Tensor(self.loc._data + z * self.scale._data)

    def log_prob(self, value):
        return eager_call(
            "normal_log_prob",
            lambda v, m, s: -((v - m) ** 2) / (2 * s**2) - jnp.log(s) - 0.5 * math.log(2 * math.pi),
            [as_tensor(value), self.loc, self.scale],
        )

    def entropy(self):
        return eager_call(
            "normal_entropy", lambda s: 0.5 + 0.5 * math.log(2 * math.pi) + jnp.log(s), [self.scale]
        )

    def kl_divergence(self, other):
        return eager_call(
            "normal_kl",
            lambda m1, s1, m2, s2: jnp.log(s2 / s1) + (s1**2 + (m1 - m2) ** 2) / (2 * s2**2) - 0.5,
            [self.loc, self.scale, other.loc, other.scale],
        )


class Categorical(Distribution):
    def __init__(self, logits, name=None):
        self.logits = as_tensor(logits)

    def sample(self, shape=()):
        key = random_state.next_key()
        out = jax.random.categorical(key, _concrete(self.logits._data), shape=tuple(shape) + tuple(self.logits.shape[:-1]))
        return Tensor(out.astype(np.int64))

    def log_prob(self, value):
        return eager_call(
            "cat_log_prob",
            lambda lg, v: jnp.take_along_axis(
                jax.nn.log_softmax(lg, axis=-1), v.astype(jnp.int32)[..., None], axis=-1
            )[..., 0],
            [self.logits, as_tensor(value)],
        )

    def entropy(self):
        return eager_call(
            "cat_entropy",
            lambda lg: -jnp.sum(jax.nn.softmax(lg, -1) * jax.nn.log_softmax(lg, -1), axis=-1),
            [self.logits],
        )


class Bernoulli(Distribution):
    def __init__(self, probs, name=None):
        self.probs_t = as_tensor(probs)

    def sample(self, shape=()):
        key = random_state.next_key()
        return Tensor(
            jax.random.bernoulli(key, _concrete(self.probs_t._data), tuple(shape) + tuple(self.probs_t.shape)).astype(np.float32)
        )

    def log_prob(self, value):
        return eager_call(
            "bern_log_prob",
            lambda p, v: v * jnp.log(jnp.clip(p, 1e-12)) + (1 - v) * jnp.log(jnp.clip(1 - p, 1e-12)),
            [self.probs_t, as_tensor(value)],
        )


def kl_divergence(p, q):
    fn = _registered_kl(p, q)
    if fn is not None:
        return fn(p, q)
    return p.kl_divergence(q)


class ExponentialFamily(Distribution):
    """Base for exponential-family distributions (reference
    distribution/exponential_family.py): entropy via the Bregman identity
    over the log-normalizer, computed with autodiff."""

    # Subclasses implement entropy()/log_prob() directly (closed forms);
    # the reference's Bregman-identity entropy over the log-normalizer is a
    # fallback our concrete distributions don't need.


class Beta(ExponentialFamily):
    """reference distribution/beta.py."""

    def __init__(self, alpha, beta, name=None):
        self.alpha = as_tensor(alpha, dtype="float32")
        self.beta = as_tensor(beta, dtype="float32")

    @property
    def mean(self):
        return eager_call("beta_mean", lambda a, b: a / (a + b), [self.alpha, self.beta])

    @property
    def variance(self):
        return eager_call(
            "beta_var",
            lambda a, b: a * b / ((a + b) ** 2 * (a + b + 1)),
            [self.alpha, self.beta],
        )

    def sample(self, shape=()):
        key = random_state.next_key()
        a, b = self.alpha._data, self.beta._data
        out_shape = tuple(shape) + np.broadcast_shapes(a.shape, b.shape)
        return Tensor(jax.random.beta(key, a, b, out_shape or None), stop_gradient=True)

    def log_prob(self, value):
        return eager_call(
            "beta_log_prob",
            lambda a, b, v: (
                (a - 1) * jnp.log(jnp.clip(v, 1e-12))
                + (b - 1) * jnp.log(jnp.clip(1 - v, 1e-12))
                - (jax.scipy.special.gammaln(a) + jax.scipy.special.gammaln(b)
                   - jax.scipy.special.gammaln(a + b))
            ),
            [self.alpha, self.beta, as_tensor(value)],
        )

    def entropy(self):
        return eager_call(
            "beta_entropy",
            lambda a, b: (
                jax.scipy.special.gammaln(a) + jax.scipy.special.gammaln(b)
                - jax.scipy.special.gammaln(a + b)
                - (a - 1) * jax.scipy.special.digamma(a)
                - (b - 1) * jax.scipy.special.digamma(b)
                + (a + b - 2) * jax.scipy.special.digamma(a + b)
            ),
            [self.alpha, self.beta],
        )


class Dirichlet(ExponentialFamily):
    """reference distribution/dirichlet.py."""

    def __init__(self, concentration, name=None):
        self.concentration = as_tensor(concentration, dtype="float32")

    @property
    def mean(self):
        return eager_call(
            "dir_mean", lambda c: c / jnp.sum(c, -1, keepdims=True), [self.concentration]
        )

    @property
    def variance(self):
        def fn(c):
            a0 = jnp.sum(c, -1, keepdims=True)
            return c * (a0 - c) / (a0 * a0 * (a0 + 1))
        return eager_call("dir_var", fn, [self.concentration])

    def sample(self, shape=()):
        key = random_state.next_key()
        c = self.concentration._data
        return Tensor(
            jax.random.dirichlet(key, c, tuple(shape) + c.shape[:-1] or None),
            stop_gradient=True,
        )

    def log_prob(self, value):
        return eager_call(
            "dir_log_prob",
            lambda c, v: (
                jnp.sum((c - 1) * jnp.log(jnp.clip(v, 1e-12)), -1)
                + jax.scipy.special.gammaln(jnp.sum(c, -1))
                - jnp.sum(jax.scipy.special.gammaln(c), -1)
            ),
            [self.concentration, as_tensor(value)],
        )

    def entropy(self):
        def fn(c):
            a0 = jnp.sum(c, -1)
            K = c.shape[-1]
            logB = jnp.sum(jax.scipy.special.gammaln(c), -1) - jax.scipy.special.gammaln(a0)
            return (
                logB + (a0 - K) * jax.scipy.special.digamma(a0)
                - jnp.sum((c - 1) * jax.scipy.special.digamma(c), -1)
            )
        return eager_call("dir_entropy", fn, [self.concentration])


class Multinomial(Distribution):
    """reference distribution/multinomial.py."""

    def __init__(self, total_count, probs, name=None):
        self.total_count = int(total_count)
        self.probs_t = as_tensor(probs, dtype="float32")

    @property
    def mean(self):
        return eager_call(
            "multi_mean", lambda p, n=1: n * p, [self.probs_t],
            attrs={"n": self.total_count},
        )

    @property
    def variance(self):
        return eager_call(
            "multi_var", lambda p, n=1: n * p * (1 - p), [self.probs_t],
            attrs={"n": self.total_count},
        )

    def sample(self, shape=()):
        key = random_state.next_key()
        p = self.probs_t._data
        batch = p.shape[:-1]
        # n independent categorical draws summed into counts (batched probs
        # supported: draws carry shape (*shape, n, *batch))
        draws = jax.random.categorical(
            key, jnp.log(jnp.clip(p, 1e-12)),
            shape=tuple(shape) + (self.total_count,) + batch,
        )
        counts = jax.nn.one_hot(draws, p.shape[-1]).sum(axis=len(shape))
        return Tensor(counts, stop_gradient=True)

    def log_prob(self, value):
        return eager_call(
            "multi_log_prob",
            lambda p, v: (
                jax.scipy.special.gammaln(jnp.sum(v, -1) + 1)
                - jnp.sum(jax.scipy.special.gammaln(v + 1), -1)
                + jnp.sum(v * jnp.log(jnp.clip(p, 1e-12)), -1)
            ),
            [self.probs_t, as_tensor(value)],
        )


_KL_REGISTRY = {}


def register_kl(cls_p, cls_q):
    """reference distribution/kl.py register_kl decorator."""

    def decorator(fn):
        _KL_REGISTRY[(cls_p, cls_q)] = fn
        return fn

    return decorator


def _registered_kl(p, q):
    for (cp, cq), fn in _KL_REGISTRY.items():
        if isinstance(p, cp) and isinstance(q, cq):
            return fn
    return None


@register_kl(Beta, Beta)
def _kl_beta_beta(p, q):
    def fn(a1, b1, a2, b2):
        S1 = a1 + b1
        return (
            jax.scipy.special.gammaln(S1) - jax.scipy.special.gammaln(a1) - jax.scipy.special.gammaln(b1)
            - (jax.scipy.special.gammaln(a2 + b2) - jax.scipy.special.gammaln(a2) - jax.scipy.special.gammaln(b2))
            + (a1 - a2) * jax.scipy.special.digamma(a1)
            + (b1 - b2) * jax.scipy.special.digamma(b1)
            + (a2 - a1 + b2 - b1) * jax.scipy.special.digamma(S1)
        )
    return eager_call("kl_beta", fn, [p.alpha, p.beta, q.alpha, q.beta])


@register_kl(Dirichlet, Dirichlet)
def _kl_dir_dir(p, q):
    def fn(c1, c2):
        a0 = jnp.sum(c1, -1)
        return (
            jax.scipy.special.gammaln(a0) - jnp.sum(jax.scipy.special.gammaln(c1), -1)
            - jax.scipy.special.gammaln(jnp.sum(c2, -1)) + jnp.sum(jax.scipy.special.gammaln(c2), -1)
            + jnp.sum((c1 - c2) * (jax.scipy.special.digamma(c1)
                                   - jax.scipy.special.digamma(a0)[..., None]), -1)
        )
    return eager_call("kl_dir", fn, [p.concentration, q.concentration])
