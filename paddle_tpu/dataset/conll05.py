"""dataset.conll05 (reference dataset/conll05.py) — generator API over
text.Conll05st."""
from ..text import Conll05st


def _reader(mode):
    def reader():
        ds = Conll05st(mode=mode)
        for i in range(len(ds)):
            yield tuple(ds[i]) if isinstance(ds[i], (list, tuple)) else (ds[i],)
    return reader


def train():
    return _reader("train")


def test():
    return _reader("test")
