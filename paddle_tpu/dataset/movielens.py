"""dataset.movielens (reference dataset/movielens.py) — generator API over
text.Movielens."""
from ..text import Movielens


def _reader(mode):
    def reader():
        ds = Movielens(mode=mode)
        for i in range(len(ds)):
            yield tuple(ds[i]) if isinstance(ds[i], (list, tuple)) else (ds[i],)
    return reader


def train():
    return _reader("train")


def test():
    return _reader("test")
