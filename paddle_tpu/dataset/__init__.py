"""paddle.dataset — the v1 generator-style dataset namespace (reference
python/paddle/dataset/): each sub-module exposes ``train()``/``test()``
reader creators yielding plain numpy samples. Backed by this framework's
class-based datasets (vision.datasets / text datasets with synthetic
fallbacks — no network egress here), so v1 training scripts keep working.
"""
from . import (  # noqa: F401
    cifar, common, conll05, imdb, imikolov, mnist, movielens, uci_housing,
    wmt14, wmt16,
)

__all__ = ["mnist", "cifar", "imdb", "imikolov", "uci_housing", "movielens",
           "conll05", "wmt14", "wmt16", "common"]
