"""dataset.cifar (reference dataset/cifar.py) — generator API over
vision.datasets.Cifar10."""
from ..vision.datasets import Cifar10


def _reader(mode):
    def reader():
        ds = Cifar10(mode=mode)
        for i in range(len(ds)):
            img, label = ds[i]
            yield img.reshape(-1) if hasattr(img, "reshape") else img, int(label)
    return reader


def train():
    return _reader("train")


def test():
    return _reader("test")
