"""dataset.uci_housing (reference dataset/uci_housing.py) — generator API over
text.UCIHousing."""
from ..text import UCIHousing


def _reader(mode):
    def reader():
        ds = UCIHousing(mode=mode)
        for i in range(len(ds)):
            yield tuple(ds[i]) if isinstance(ds[i], (list, tuple)) else (ds[i],)
    return reader


def train():
    return _reader("train")


def test():
    return _reader("test")
