"""dataset.mnist (reference dataset/mnist.py) — generator API over
vision.datasets.MNIST."""
from ..vision.datasets import MNIST


def _reader(mode):
    def reader():
        ds = MNIST(mode=mode)
        for i in range(len(ds)):
            img, label = ds[i]
            yield img.reshape(-1) if hasattr(img, "reshape") else img, int(label)
    return reader


def train():
    return _reader("train")


def test():
    return _reader("test")
