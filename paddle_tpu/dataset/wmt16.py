"""dataset.wmt16 (reference dataset/wmt16.py) — generator API over
text.WMT16."""
from ..text import WMT16


def _reader(mode):
    def reader():
        ds = WMT16(mode=mode)
        for i in range(len(ds)):
            yield tuple(ds[i]) if isinstance(ds[i], (list, tuple)) else (ds[i],)
    return reader


def train():
    return _reader("train")


def test():
    return _reader("test")
