"""dataset.common (reference dataset/common.py): shared paths + md5 utils."""
from __future__ import annotations

import hashlib

from ..io import data_home as _data_home

DATA_HOME = _data_home()  # one cache root shared with paddle.io


def md5file(fname):
    h = hashlib.md5()
    with open(fname, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def download(url, module_name, md5sum, save_name=None):
    raise RuntimeError(
        "paddle_tpu.dataset runs with zero network egress; datasets load "
        "from local files or synthesize deterministic fallbacks")
