"""dataset.imdb (reference dataset/imdb.py) — generator API over
text.Imdb."""
from ..text import Imdb


def _reader(mode):
    def reader():
        ds = Imdb(mode=mode)
        for i in range(len(ds)):
            yield tuple(ds[i]) if isinstance(ds[i], (list, tuple)) else (ds[i],)
    return reader


def train():
    return _reader("train")


def test():
    return _reader("test")
