"""dataset.imikolov (reference dataset/imikolov.py) — generator API over
text.Imikolov."""
from ..text import Imikolov


def _reader(mode):
    def reader():
        ds = Imikolov(mode=mode)
        for i in range(len(ds)):
            yield tuple(ds[i]) if isinstance(ds[i], (list, tuple)) else (ds[i],)
    return reader


def train():
    return _reader("train")


def test():
    return _reader("test")
