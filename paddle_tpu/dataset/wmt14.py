"""dataset.wmt14 (reference dataset/wmt14.py) — generator API over
text.WMT14."""
from ..text import WMT14


def _reader(mode):
    def reader():
        ds = WMT14(mode=mode)
        for i in range(len(ds)):
            yield tuple(ds[i]) if isinstance(ds[i], (list, tuple)) else (ds[i],)
    return reader


def train():
    return _reader("train")


def test():
    return _reader("test")
