"""paddle.metric parity (reference python/paddle/metric/metrics.py).

Readback discipline (async runtime): every ``update()`` coalesces its device
reads into ONE host sync via :func:`_host` — the old per-tensor
``np.asarray`` pattern forced 2+ blocking device→host readbacks per batch,
each of which also split the lazy engine's fused step. Accumulators stay on
host (plain floats/ints/np arrays), so ``accumulate()`` never touches the
device.
"""
from __future__ import annotations

import numpy as np
import jax

from ..core.tensor import Tensor
from ..core.dispatch import as_tensor
from ..core import lazy as _lazy


def _host(*xs):
    """Materialize every argument with a single device sync: one lazy flush
    (the first ``concrete`` call dispatches the whole pending graph), one
    attributed wait, one batched ``jax.device_get`` transfer — instead of
    one blocking ``np.asarray`` per tensor."""
    arrs = [_lazy.concrete(as_tensor(x)._data) for x in xs]
    _lazy.timed_block(arrs, "metric_update")
    return [np.asarray(a) for a in jax.device_get(arrs)]


class Metric:
    def __init__(self):
        pass

    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self):
        return self.__class__.__name__

    def compute(self, *args):
        return args


class Accuracy(Metric):
    def __init__(self, topk=(1,), name=None, *args, **kwargs):
        super().__init__()
        self.topk = topk if isinstance(topk, (list, tuple)) else (topk,)
        self._name = name or "acc"
        self.reset()

    def reset(self):
        self.total = [0.0] * len(self.topk)
        self.count = [0] * len(self.topk)

    def compute(self, pred, label, *args):
        pred, label = _host(pred, label)  # one sync, not two
        if label.ndim == 1:
            label = label.reshape(-1, 1)
        maxk = max(self.topk)
        idx = np.argsort(-pred, axis=-1)[..., :maxk]
        correct = idx == label
        return Tensor(correct.astype(np.float32))

    def update(self, correct, *args):
        (correct,) = _host(correct)
        accs = []
        for i, k in enumerate(self.topk):
            num = correct[..., :k].sum()
            self.total[i] += float(num)
            self.count[i] += int(correct.shape[0])
            accs.append(float(num) / max(correct.shape[0], 1))
        return accs[0] if len(accs) == 1 else accs

    def accumulate(self):
        res = [t / max(c, 1) for t, c in zip(self.total, self.count)]
        return res[0] if len(res) == 1 else res

    def name(self):
        return [f"{self._name}_top{k}" for k in self.topk] if len(self.topk) > 1 else [self._name]


class Precision(Metric):
    def __init__(self, name=None, *args, **kwargs):
        super().__init__()
        self._name = name or "precision"
        self.reset()

    def reset(self):
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        preds, labels = _host(preds, labels)
        preds = preds.round().astype(np.int32).reshape(-1)
        labels = labels.astype(np.int32).reshape(-1)
        self.tp += int(((preds == 1) & (labels == 1)).sum())
        self.fp += int(((preds == 1) & (labels == 0)).sum())

    def accumulate(self):
        return self.tp / max(self.tp + self.fp, 1)

    def name(self):
        return [self._name]


class Recall(Metric):
    def __init__(self, name=None, *args, **kwargs):
        super().__init__()
        self._name = name or "recall"
        self.reset()

    def reset(self):
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        preds, labels = _host(preds, labels)
        preds = preds.round().astype(np.int32).reshape(-1)
        labels = labels.astype(np.int32).reshape(-1)
        self.tp += int(((preds == 1) & (labels == 1)).sum())
        self.fn += int(((preds == 0) & (labels == 1)).sum())

    def accumulate(self):
        return self.tp / max(self.tp + self.fn, 1)

    def name(self):
        return [self._name]


class Auc(Metric):
    def __init__(self, curve="ROC", num_thresholds=4095, name=None, *args, **kwargs):
        super().__init__()
        self._name = name or "auc"
        self.num_thresholds = num_thresholds
        self.reset()

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds + 1)
        self._stat_neg = np.zeros(self.num_thresholds + 1)

    def update(self, preds, labels):
        preds, labels = _host(preds, labels)
        labels = labels.reshape(-1)
        if preds.ndim == 2:
            preds = preds[:, 1]
        preds = preds.reshape(-1)
        bins = np.minimum((preds * self.num_thresholds).astype(np.int64), self.num_thresholds)
        pos = labels.astype(bool)
        np.add.at(self._stat_pos, bins[pos], 1)
        np.add.at(self._stat_neg, bins[~pos], 1)

    def accumulate(self):
        tot_pos = self._stat_pos.sum()
        tot_neg = self._stat_neg.sum()
        if tot_pos == 0 or tot_neg == 0:
            return 0.0
        # trapezoid over thresholds, descending
        pos_cum = np.cumsum(self._stat_pos[::-1])
        neg_cum = np.cumsum(self._stat_neg[::-1])
        tpr = pos_cum / tot_pos
        fpr = neg_cum / tot_neg
        return float(np.trapz(tpr, fpr))

    def name(self):
        return [self._name]


def accuracy(input, label, k=1, correct=None, total=None, name=None):
    pred, lab = _host(input, label)
    lab = lab.reshape(-1)
    idx = np.argsort(-pred, axis=-1)[..., :k]
    hit = (idx == lab[:, None]).any(axis=-1)
    return Tensor(np.asarray(hit.mean(), dtype=np.float32))
