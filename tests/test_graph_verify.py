"""Lazy-graph IR verifier (analysis/verify_graph.py, FLAGS_lazy_verify).

Seeded-corruption coverage: a hand-built pending graph with a cycle, a
dangling leaf, a donated-but-still-referenced buffer, and a tampered
signature each produce a structured GraphInvariantError naming the
offending node — plus the clean-path pins (bit-for-bit parity with the
verifier on, verify-per-flush counter) and the zero-cost tripwire for the
disabled path.
"""
import numpy as np
import pytest

import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.analysis import verify_graph as vg
from paddle_tpu.core import lazy
from paddle_tpu.framework import flags


@pytest.fixture
def fresh_graph():
    """A two-node pending graph (add -> mul) plus its live handles; the
    epoch is discarded on exit so a corrupted graph never leaks into the
    next test's flush."""
    lazy.flush()
    a = jnp.asarray(np.arange(8.0, dtype=np.float32))
    (x,), _ = lazy.record("vadd", jnp.add, [a, a])
    (y,), _ = lazy.record("vmul", jnp.multiply, [x, a])
    g = lazy._state.graph
    yield g, a, x, y
    lazy._state.graph = None


def _flag(name):
    return bool(flags.flag(name))


class TestSeededCorruptions:
    def test_clean_graph_verifies(self, fresh_graph):
        g, a, x, y = fresh_graph
        vg.verify_before_dispatch(g, (), None)  # no raise

    def test_cycle_detected_and_named(self, fresh_graph):
        g, a, x, y = fresh_graph
        # node 0 rewired to read node 1's output: a forward reference, i.e.
        # a cycle in the supposedly append-only order
        g.descs[0] = (("n", 1, 0), ("n", 1, 0))
        with pytest.raises(vg.GraphInvariantError) as ei:
            vg.verify_before_dispatch(g, (), None)
        assert ei.value.rule == "acyclicity"
        assert ei.value.node_index == 0
        assert "vadd" in str(ei.value) and "node 0" in str(ei.value)

    def test_out_of_range_output_index(self, fresh_graph):
        g, a, x, y = fresh_graph
        g.descs[1] = (("n", 0, 5), ("l", 0))  # vadd has n_out == 1
        with pytest.raises(vg.GraphInvariantError) as ei:
            vg.verify_before_dispatch(g, (), None)
        assert ei.value.rule == "wiring"
        assert ei.value.node_index == 1 and "vmul" in str(ei.value)

    def test_dangling_leaf_detected(self, fresh_graph):
        g, a, x, y = fresh_graph
        g.descs[1] = (("n", 0, 0), ("l", 7))  # only 1 leaf exists
        with pytest.raises(vg.GraphInvariantError) as ei:
            vg.verify_before_dispatch(g, (), None)
        assert ei.value.rule == "leaf-table"
        assert "dangling leaf" in str(ei.value) and "vmul" in str(ei.value)

    def test_leaf_position_corruption(self, fresh_graph):
        g, a, x, y = fresh_graph
        g.leaf_pos[id(a)] = 3
        with pytest.raises(vg.GraphInvariantError) as ei:
            vg.verify_before_dispatch(g, (), None)
        assert ei.value.rule == "leaf-table"

    def test_direct_uses_miscount(self, fresh_graph):
        g, a, x, y = fresh_graph
        # the donation refcount budget is built from direct_uses — an
        # overcount would let a live buffer pass the deadness test
        g.direct_uses[id(a)] += 1
        with pytest.raises(vg.GraphInvariantError) as ei:
            vg.verify_before_dispatch(g, (), None)
        assert ei.value.rule == "leaf-table"
        assert "donation refcount budget" in str(ei.value)

    def test_donated_but_user_referenced_leaf(self, fresh_graph):
        g, a, x, y = fresh_graph
        # leaf 0 is `a` — held right here by the test (and by the fixture):
        # donating it would destroy a live alias
        with pytest.raises(vg.GraphInvariantError) as ei:
            vg.verify_before_dispatch(g, (0,), None)
        assert ei.value.rule == "donation"
        assert "still references" in str(ei.value)

    def test_donation_index_out_of_range(self, fresh_graph):
        g, a, x, y = fresh_graph
        with pytest.raises(vg.GraphInvariantError) as ei:
            vg.verify_before_dispatch(g, (12,), None)
        assert ei.value.rule == "donation"

    def test_signature_mismatch_detected(self, fresh_graph):
        g, a, x, y = fresh_graph
        # memoized signature part no longer matches the wired graph: the
        # flush cache would key (and later serve) the wrong executable
        g.keyparts[1] = (("evil", None), g.descs[1])
        with pytest.raises(vg.GraphInvariantError) as ei:
            vg.verify_before_dispatch(g, (), None)
        assert ei.value.rule == "signature"
        assert ei.value.node_index == 1

    def test_leaf_aval_drift_detected(self, fresh_graph):
        g, a, x, y = fresh_graph
        g.leaf_avals[0] = ((4,), np.dtype(np.float64))
        with pytest.raises(vg.GraphInvariantError) as ei:
            vg.verify_before_dispatch(g, (), None)
        assert ei.value.rule == "signature"

    def test_deferred_bookkeeping_checked(self, fresh_graph):
        g, a, x, y = fresh_graph
        with pytest.raises(vg.GraphInvariantError) as ei:
            vg.verify_before_dispatch(g, (), [("not", "a", "4-tuple")])
        assert ei.value.rule == "deferred"
        # census-only and well-formed scan entries pass
        vg.verify_before_dispatch(
            g, (), [(None, None, True, None)]
        )

    def test_corrupted_graph_fails_the_flush_itself(self):
        """End to end: with FLAGS_lazy_verify on (suite default), a corrupted
        pending graph turns the next flush into a structured error instead
        of dispatching a wrong program."""
        assert _flag("FLAGS_lazy_verify")
        lazy.flush()
        a = jnp.asarray(np.ones(4, np.float32))
        (x,), _ = lazy.record("vcorrupt", jnp.negative, [a])
        g = lazy._state.graph
        g.descs[0] = (("l", 9),)
        try:
            with pytest.raises(vg.GraphInvariantError):
                lazy.flush()
        finally:
            lazy._state.graph = None
        del x


class TestCleanPath:
    def test_training_parity_and_counter(self):
        """A real donating train loop verifies on every flush and produces
        bit-identical losses with the verifier on and off."""
        from paddle_tpu import profiler
        from paddle_tpu.vision.models import LeNet

        def run():
            paddle.seed(7)
            model = LeNet()
            opt = paddle.optimizer.Adam(
                learning_rate=1e-3, parameters=model.parameters()
            )
            lossf = paddle.nn.CrossEntropyLoss()
            rng = np.random.RandomState(7)
            x = paddle.to_tensor(rng.randn(8, 1, 28, 28).astype(np.float32))
            y = paddle.to_tensor(rng.randint(0, 10, (8,)))
            out = []
            for _ in range(3):
                loss = lossf(model(x), y)
                loss.backward()
                opt.step()
                opt.clear_grad()
                out.append(loss.numpy().tobytes())
            return out

        before = profiler.counters().get("lazy_verify_passes", 0)
        on = run()
        assert profiler.counters().get("lazy_verify_passes", 0) > before
        flags.set_flags({"FLAGS_lazy_verify": False})
        try:
            off = run()
        finally:
            flags.set_flags({"FLAGS_lazy_verify": True})
        assert on == off  # bit-for-bit

    def test_disabled_path_does_zero_verify_work(self, monkeypatch):
        """FLAGS_lazy_verify=0 must cost one flag probe and nothing else:
        the verifier entry point is never reached (it is patched to explode)
        and the pass counter stays flat."""
        from paddle_tpu import profiler

        flags.set_flags({"FLAGS_lazy_verify": False})
        try:
            def boom(*a, **k):  # pragma: no cover - reaching this IS the bug
                raise AssertionError("verifier entered with the flag off")

            monkeypatch.setattr(vg, "verify_before_dispatch", boom)
            before = profiler.counters().get("lazy_verify_passes", 0)
            t = paddle.to_tensor(np.ones((4, 4), np.float32))
            r = (t * 2 + 1).numpy()
            assert r.shape == (4, 4)
            assert profiler.counters().get("lazy_verify_passes", 0) == before
        finally:
            flags.set_flags({"FLAGS_lazy_verify": True})

    def test_flag_registered(self):
        # typo-guard coverage: both new flags are registry members
        assert flags.get_flags("FLAGS_lazy_verify")["FLAGS_lazy_verify"] in (
            True, False,
        )
        assert "FLAGS_thread_checks" in flags._FLAGS
