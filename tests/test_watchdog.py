"""Distributed watchdog + coordination substrate + sample-exact resume.

Single-process tests for the supervision layer (multi-rank interleavings are
simulated with threads over a FileStore; the REAL multi-process worlds live
in test_chaos_recovery.py under the ``chaos`` marker):

* FileStore / CommitBarrier — the coordination substrate;
* watchdog progress table, suspect attribution, deadline guards, and the
  tier-1 inert tripwire (FLAGS_collective_timeout_s=0 → zero threads, zero
  store traffic, no syncs added to the step path);
* rank.slow / rank.hang / rank.kill / collective.drop chaos plumbing
  (in-process only where safe: rank.slow delay, should_fire filters);
* DataLoader / DevicePrefetcher state_dict — sample-exact resume — and the
  program RNG checkpoint round-trip.
"""
import json
import os
import threading
import time

import numpy as np
import pytest

import paddle_tpu
from paddle_tpu.distributed import coord, watchdog
from paddle_tpu.distributed.coord import CommitBarrier, DeadlineExceeded, FileStore
from paddle_tpu.fault import inject
from paddle_tpu.framework import flags as fw_flags
from paddle_tpu.io import DataLoader, Dataset
from paddle_tpu.profiler import flight

pytestmark = pytest.mark.faults


@pytest.fixture(autouse=True)
def _clean_watchdog():
    watchdog.reset()
    fw_flags.set_flags({"FLAGS_collective_timeout_s": 0.0})
    inject.disarm()
    yield
    watchdog.set_abort_fn(None)
    watchdog.reset()
    fw_flags.set_flags({"FLAGS_collective_timeout_s": 0.0})
    inject.disarm()


# ---------------------------------------------------------------- FileStore
class TestFileStore:
    def test_set_get_roundtrip(self, tmp_path):
        st = FileStore(str(tmp_path))
        st.set("a/b", "hello")
        assert st.get("a/b") == b"hello"
        assert st.get("missing") is None
        st.delete_key("a/b")
        assert st.get("a/b") is None

    def test_keys_escape_slashes(self, tmp_path):
        st = FileStore(str(tmp_path))
        st.set("wd/progress/3", "x")
        st.set("plain", "y")
        assert sorted(st.keys()) == ["plain", "wd/progress/3"]

    def test_add_serializes_concurrent_increments(self, tmp_path):
        st = FileStore(str(tmp_path))
        n_threads, per_thread = 8, 25
        errs = []

        def bump():
            try:
                for _ in range(per_thread):
                    st.add("ctr", 1)
            except Exception as e:  # pragma: no cover - failure path
                errs.append(e)

        ts = [threading.Thread(target=bump) for _ in range(n_threads)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert not errs
        assert int(st.get("ctr")) == n_threads * per_thread

    def test_wait_for_deadline(self):
        with pytest.raises(DeadlineExceeded) as ei:
            coord.wait_for(lambda: False, "nothing", 0.15, interval_s=0.02)
        assert "nothing" in str(ei.value)
        # timeout<=0 means no deadline: poll until truthy
        hits = []
        coord.wait_for(lambda: hits.append(1) or len(hits) > 2, "counts", 0.0,
                       interval_s=0.001)


# ------------------------------------------------------------ CommitBarrier
class TestCommitBarrier:
    def test_two_phase_commit_world2(self, tmp_path):
        st = FileStore(str(tmp_path))
        b0 = CommitBarrier(st, 2, 0)
        b1 = CommitBarrier(st, 2, 1)
        out = {}

        def rank1():
            b1.ack("s10")
            out[1] = b1.commit("s10", timeout_s=5.0)

        t = threading.Thread(target=rank1)
        t.start()
        b0.ack("s10")
        out[0] = b0.commit("s10", timeout_s=5.0)
        t.join()
        assert out[0]["tag"] == out[1]["tag"] == "s10"
        assert b0.committed("s10") and b1.committed("s10")

    def test_missing_rank_leaves_uncommitted(self, tmp_path):
        st = FileStore(str(tmp_path))
        b0 = CommitBarrier(st, 2, 0)
        b0.ack("s20")  # rank 1 never arrives
        with pytest.raises(DeadlineExceeded):
            b0.commit("s20", timeout_s=0.2)
        assert not b0.committed("s20")

    def test_distinct_tags_independent(self, tmp_path):
        st = FileStore(str(tmp_path))
        b = CommitBarrier(st, 1, 0)
        b.ack("old")  # litter from a crashed attempt
        b.ack("new")
        b.commit("new", timeout_s=1.0)
        assert b.committed("new") and not b.committed("old")


# ----------------------------------------------------------------- watchdog
class TestWatchdogProgress:
    def test_publish_writes_progress_file(self, tmp_path):
        watchdog.configure(rank=0, world_size=2, store=None,
                           progress_dir=str(tmp_path))
        watchdog.publish(step=7, phase="train_step", force=True)
        rec = json.loads((tmp_path / "rank_0.json").read_text())
        assert rec["step"] == 7 and rec["phase"] == "train_step"
        assert watchdog.local_progress()["step"] == 7

    def test_progress_table_merges_store_over_files(self, tmp_path):
        store = FileStore(str(tmp_path / "store"))
        pdir = tmp_path / "progress"
        pdir.mkdir()
        (pdir / "rank_1.json").write_text(json.dumps({"rank": 1, "step": 3}))
        store.set("wd/progress/1", json.dumps({"rank": 1, "step": 9}))
        watchdog.configure(rank=0, world_size=2, store=store,
                           progress_dir=str(pdir))
        table = watchdog.progress_table()
        assert table[1]["step"] == 9  # store record wins (fresher path)

    def test_suspect_names_silent_rank(self, tmp_path):
        watchdog.configure(rank=0, world_size=3, store=None,
                           progress_dir=str(tmp_path))
        watchdog.publish(step=5, force=True)
        (tmp_path / "rank_1.json").write_text(
            json.dumps({"rank": 1, "step": 5, "phase": "train_step",
                        "ts": time.time()}))
        sus, why = watchdog.suspect()
        assert sus == 2 and "no progress record" in why

    def test_suspect_names_straggler(self, tmp_path):
        watchdog.configure(rank=0, world_size=3, store=None,
                           progress_dir=str(tmp_path))
        now = time.time()
        for r, step in ((0, 10), (1, 10), (2, 4)):
            (tmp_path / f"rank_{r}.json").write_text(
                json.dumps({"rank": r, "step": step, "phase": "train_step",
                            "ts": now}))
        sus, why = watchdog.suspect()
        assert sus == 2 and "step 4" in why

    def test_suspect_never_names_the_reporting_rank(self, tmp_path):
        # early-startup hang: NO rank has published yet. The reporter is
        # alive enough to be asking — it must blame a peer, not itself
        watchdog.configure(rank=0, world_size=3, store=None,
                           progress_dir=str(tmp_path))
        sus, why = watchdog.suspect()
        assert sus == 1 and "no progress record" in why

    def test_publish_without_session_is_noop(self):
        assert not watchdog.configured()
        watchdog.publish(step=1)  # must not raise, must not create state
        assert watchdog.local_progress() == {}


class TestWatchdogGuard:
    def test_guard_trips_and_names_suspect(self, tmp_path):
        codes = []
        watchdog.set_abort_fn(codes.append)
        watchdog.configure(rank=0, world_size=2, store=None,
                           progress_dir=str(tmp_path))
        watchdog.publish(step=9, phase="train_step", force=True)
        (tmp_path / "rank_1.json").write_text(
            json.dumps({"rank": 1, "step": 2, "phase": "train_step",
                        "ts": time.time() - 30}))
        fw_flags.set_flags({"FLAGS_collective_timeout_s": 0.25})
        with watchdog.guard("allreduce:test"):
            deadline = time.time() + 5
            while not codes and time.time() < deadline:
                time.sleep(0.02)  # the wedged collective that never returns
        assert codes == [75]
        path = flight.last_dump()
        assert path is not None
        doc = json.loads(open(path).read())
        assert doc["reason"] == "collective_timeout"
        assert doc["extra"]["suspect_rank"] == 1
        assert doc["extra"]["what"] == "allreduce:test"
        # the registered context provider puts the cross-rank table in EVERY
        # dump, with the same verdict
        assert doc["context"]["watchdog"]["suspect_rank"] == 1

    def test_guard_disarms_on_normal_exit(self):
        codes = []
        watchdog.set_abort_fn(codes.append)
        watchdog.configure(rank=0, world_size=1, store=None, progress_dir=None)
        fw_flags.set_flags({"FLAGS_collective_timeout_s": 0.2})
        with watchdog.guard("fast-op"):
            pass  # returns well before the deadline
        time.sleep(0.35)
        assert codes == []

    def test_guarded_wait_trips(self, tmp_path):
        codes = []
        watchdog.set_abort_fn(codes.append)
        watchdog.configure(rank=0, world_size=1, store=None,
                           progress_dir=str(tmp_path))
        watchdog.guarded_wait(lambda: False, "peer ack", timeout=0.15,
                              interval_s=0.02)
        assert codes == [75]

    def test_guarded_wait_passes_when_ready(self):
        codes = []
        watchdog.set_abort_fn(codes.append)
        watchdog.guarded_wait(lambda: True, "instant", timeout=0.5)
        assert codes == []


class TestWatchdogInertTripwire:
    """Tier-1 tripwire: FLAGS_collective_timeout_s=0 (default) must add ZERO
    overhead — no monitor thread, no store/file traffic, no host syncs."""

    def test_disabled_guard_spawns_no_threads(self):
        assert not watchdog.enabled()
        before = {t.name for t in threading.enumerate()}
        for _ in range(100):
            with watchdog.guard("hot-path"):
                pass
        after = {t.name for t in threading.enumerate()}
        assert "paddle-tpu-watchdog" not in after
        assert after == before

    def test_disabled_step_path_adds_no_syncs_or_trips(self):
        from paddle_tpu import profiler

        from paddle_tpu.core import lazy

        watchdog.configure(rank=0, world_size=1, store=None, progress_dir=None)
        c0 = dict(profiler.counters())
        x = paddle_tpu.to_tensor(np.ones((4, 4), np.float32))
        with lazy.lazy_guard(True):
            y = (x * 2 + 1).sum()
        val = float(y.numpy())  # one sanctioned readback
        assert val == 48.0
        c1 = profiler.counters()
        assert c1.get("watchdog_trips", 0) == c0.get("watchdog_trips", 0)
        # exactly the sanctioned block — the guard wrapped it but added none
        assert "paddle-tpu-watchdog" not in {t.name for t in threading.enumerate()}

    def test_flag_registered_and_default_zero(self):
        assert fw_flags.flag("FLAGS_collective_timeout_s") == 0.0
        assert watchdog.timeout_s() == 0.0


class TestChaosPlumbing:
    def test_rank_slow_delays_publish(self, tmp_path):
        watchdog.configure(rank=0, world_size=1, store=None,
                           progress_dir=str(tmp_path))
        inject.arm({"rank.slow": {"ms": 80, "rank": 0}})
        t0 = time.monotonic()
        watchdog.publish(step=1, force=True)
        assert time.monotonic() - t0 >= 0.08
        assert "rank.slow" in inject.exercised()

    def test_rank_filter_targets_one_rank(self):
        inject.arm({"rank.kill": {"rank": 1}})
        assert not inject.should_fire("rank.kill", step=0, rank=0)
        assert inject.should_fire("rank.kill", step=0, rank=1)

    def test_chaos_points_registered(self):
        for point in ("rank.kill", "rank.hang", "rank.slow", "collective.drop"):
            assert point in inject.POINTS

    def test_kill_payload_default(self):
        inject.arm({"rank.kill": {"exit": 99}})
        assert inject.point_cfg("rank.kill")["exit"] == 99
        inject.disarm()
        assert inject.point_cfg("rank.kill") == {}


# ------------------------------------------------------- sample-exact resume
class _ArangeDS(Dataset):
    def __init__(self, n=24):
        self.n = n

    def __getitem__(self, i):
        return np.float32([i])

    def __len__(self):
        return self.n


def _drain(it, n=None):
    out = []
    for b in it:
        out.append(np.asarray(b._data).ravel().tolist())
        if n is not None and len(out) >= n:
            break
    return out


class TestSampleExactResume:
    def test_loader_state_roundtrip_bit_exact(self):
        ref = DataLoader(_ArangeDS(), batch_size=3, shuffle=True, seed=11)
        ref_seq = []
        for _ in range(2):
            ref_seq += _drain(iter(ref))

        a = DataLoader(_ArangeDS(), batch_size=3, shuffle=True, seed=11)
        it = iter(a)
        head = _drain(it, n=3)
        sd = a.state_dict()
        assert sd == {"epoch": 0, "batch_idx": 3, "seed": 11}

        b = DataLoader(_ArangeDS(), batch_size=3, shuffle=True, seed=11)
        b.load_state_dict(sd)
        tail = []
        while len(head) + len(tail) < len(ref_seq):
            tail += _drain(iter(b))
        assert head + tail == ref_seq

    def test_epochs_reshuffle_but_are_reproducible(self):
        a = DataLoader(_ArangeDS(), batch_size=3, shuffle=True, seed=5)
        e0 = _drain(iter(a))
        e1 = _drain(iter(a))
        assert e0 != e1  # per-epoch reshuffle
        b = DataLoader(_ArangeDS(), batch_size=3, shuffle=True, seed=5)
        assert _drain(iter(b)) == e0 and _drain(iter(b)) == e1

    def test_resume_skip_never_loads_skipped_samples(self):
        loads = []

        class TrackingDS(_ArangeDS):
            def __getitem__(self, i):
                loads.append(i)
                return np.float32([i])

        dl = DataLoader(TrackingDS(12), batch_size=2, shuffle=True, seed=3)
        dl.load_state_dict({"epoch": 0, "batch_idx": 4, "seed": 3})
        got = _drain(iter(dl))
        assert len(got) == 2  # 6 batches/epoch, 4 skipped
        assert len(loads) == 4  # only the two remaining batches were loaded

    def test_seed_mismatch_adopts_checkpoint_seed(self):
        dl = DataLoader(_ArangeDS(), batch_size=3, shuffle=True, seed=1)
        with pytest.warns(UserWarning, match="adopting the checkpoint"):
            dl.load_state_dict({"epoch": 0, "batch_idx": 0, "seed": 2})
        ref = DataLoader(_ArangeDS(), batch_size=3, shuffle=True, seed=2)
        assert _drain(iter(dl)) == _drain(iter(ref))

    def test_seedless_loader_adopts_checkpoint_seed_exactly(self):
        # loader built WITHOUT a seed (global-RNG shuffle): adopting the
        # checkpoint's seed must also install the seeded sampler, or the
        # replayed order silently stays irreproducible
        dl = DataLoader(_ArangeDS(), batch_size=3, shuffle=True)
        with pytest.warns(UserWarning, match="adopting the checkpoint"):
            dl.load_state_dict({"epoch": 0, "batch_idx": 2, "seed": 7})
        ref = DataLoader(_ArangeDS(), batch_size=3, shuffle=True, seed=7)
        ref.load_state_dict({"epoch": 0, "batch_idx": 2, "seed": 7})
        assert _drain(iter(dl)) == _drain(iter(ref))

    def test_prefetcher_state_counts_consumed_not_staged(self):
        dl = DataLoader(_ArangeDS(), batch_size=3, shuffle=True, seed=11,
                        device_prefetch=3)
        it = iter(dl)
        head = []
        for _ in range(2):
            head.append(np.asarray(next(it)._data).ravel().tolist())
        time.sleep(0.2)  # let the read-ahead run PAST the consumed position
        sd = it.state_dict()
        it.close()
        assert sd["epoch"] == 0 and sd["batch_idx"] == 2

        rest = DataLoader(_ArangeDS(), batch_size=3, shuffle=True, seed=11)
        rest.load_state_dict(sd)
        ref = DataLoader(_ArangeDS(), batch_size=3, shuffle=True, seed=11)
        assert head + _drain(iter(rest)) == _drain(iter(ref))

    def test_prefetcher_load_state_dict_rebinds(self):
        dl = DataLoader(_ArangeDS(), batch_size=3, shuffle=True, seed=11)
        pf = paddle_tpu.io.device_prefetch(dl, buffer_size=2)
        pf.load_state_dict({"epoch": 0, "batch_idx": 4, "seed": 11})
        got = _drain(pf)
        ref = DataLoader(_ArangeDS(), batch_size=3, shuffle=True, seed=11)
        assert got == _drain(iter(ref))[4:]

    def test_prefetcher_rebind_on_prefetching_loader_drops_no_batches(self):
        # the loader ITSELF prefetches (device_prefetch>0): rebinding must
        # not spin up a nested prefetcher whose staged read-ahead is then
        # thrown away — every post-restore batch must reach the trainer
        dl = DataLoader(_ArangeDS(), batch_size=3, shuffle=True, seed=11,
                        device_prefetch=2)
        pf = iter(dl)
        head = _drain(pf, n=2)
        sd = pf.state_dict()
        time.sleep(0.2)  # let the read-ahead run past the consumed position
        pf.load_state_dict(sd)
        got = head + _drain(pf, n=6)
        ref = DataLoader(_ArangeDS(), batch_size=3, shuffle=True, seed=11)
        assert got == _drain(iter(ref))
        pf.close()

    def test_program_rng_checkpoint_roundtrip(self, tmp_path):
        from paddle_tpu.core import random as prandom
        from paddle_tpu.distributed.checkpoint import (
            load_state_dict, save_state_dict)

        paddle_tpu.seed(123)
        prandom.next_key()  # advance the stream past the seed point
        tree = {"rng": paddle_tpu.program_rng,
                "w": paddle_tpu.to_tensor(np.zeros(2, np.float32))}
        save_state_dict(tree, str(tmp_path / "ck"), step=1)
        expect = [np.asarray(prandom.next_key()).tolist() for _ in range(3)]

        paddle_tpu.seed(999)  # clobber the stream
        load_state_dict(tree, str(tmp_path / "ck"))
        got = [np.asarray(prandom.next_key()).tolist() for _ in range(3)]
        assert got == expect
