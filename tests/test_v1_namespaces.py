"""v1 compatibility namespaces: paddle.reader, paddle.dataset,
paddle.tensor, paddle.cost_model (reference python/paddle/{reader,dataset,
tensor,cost_model}/)."""
import itertools

import numpy as np

import paddle_tpu as paddle


class TestReaderDecorators:
    def test_cache_replays(self):
        from paddle_tpu import reader

        calls = []

        def creator():
            calls.append(1)
            return iter(range(4))

        cached = reader.cache(creator)
        assert list(cached()) == [0, 1, 2, 3]
        assert list(cached()) == [0, 1, 2, 3]
        assert len(calls) == 1

    def test_shuffle_chain_compose_firstn(self):
        from paddle_tpu import reader

        assert sorted(reader.shuffle(lambda: iter(range(10)), 4)()) == list(range(10))
        assert list(reader.chain(lambda: iter([1]), lambda: iter([2, 3]))()) == [1, 2, 3]
        out = list(reader.compose(lambda: iter([1, 2]),
                                  lambda: iter([(3, 4), (5, 6)]))())
        assert out == [(1, 3, 4), (2, 5, 6)]
        assert list(reader.firstn(lambda: iter(range(100)), 2)()) == [0, 1]

    def test_compose_misaligned_raises(self):
        from paddle_tpu import reader

        import pytest
        with pytest.raises(reader.ComposeNotAligned):
            list(reader.compose(lambda: iter([1]), lambda: iter([1, 2]))())

    def test_xmap_ordered(self):
        from paddle_tpu import reader

        out = list(reader.xmap_readers(lambda x: x * x, lambda: iter(range(9)),
                                       3, 4, order=True)())
        assert out == [i * i for i in range(9)]

    def test_map_readers_and_buffered(self):
        from paddle_tpu import reader

        m = reader.map_readers(lambda a, b: a + b,
                               lambda: iter([1, 2]), lambda: iter([10, 20]))
        assert list(m()) == [11, 22]
        assert list(reader.buffered(lambda: iter(range(6)), 2)()) == list(range(6))


class TestDatasetNamespace:
    def test_mnist_generator(self):
        from paddle_tpu import dataset

        sample = next(iter(dataset.mnist.train()()))
        img, label = sample
        assert img.shape == (784,)
        assert 0 <= int(label) < 10

    def test_text_generators(self):
        from paddle_tpu import dataset

        row = next(iter(dataset.uci_housing.train()()))
        assert len(row) == 2
        first = next(iter(dataset.imikolov.train()()))
        assert first is not None

    def test_download_refuses_egress(self):
        from paddle_tpu.dataset import common

        import pytest
        with pytest.raises(RuntimeError, match="egress"):
            common.download("http://x", "m", "0")


class TestTensorNamespace:
    def test_functions_reachable(self):
        import paddle_tpu.tensor as T

        x = paddle.to_tensor(np.array([1.0, 2.0], np.float32))
        assert float(T.sum(x).item()) == 3.0
        assert float(T.add(x, x).numpy()[1]) == 4.0
        assert T.math is not None and T.creation is not None


class TestCostModel:
    def test_profile_measure_and_op_time(self):
        import jax.numpy as jnp
        from paddle_tpu.cost_model import CostModel

        cm = CostModel()
        c = cm.profile_measure(
            fn=lambda a, b: (a @ b).sum(),
            args=(jnp.ones((64, 64), jnp.float32), jnp.ones((64, 64), jnp.float32)),
            iters=3)
        assert c["time"] > 0
        assert c.get("flops", 1) > 0
        t = cm.get_static_op_time("relu", shape=(32, 32))
        assert t["op_time"] > 0
        assert len(cm.static_cost_data()) == 1
        # cache hit returns the same record
        assert cm.get_static_op_time("relu", shape=(32, 32)) is t


class TestTopLevelStaples:
    def test_batch_decorator(self):
        assert list(paddle.batch(lambda: iter(range(7)), 3)()) == [
            [0, 1, 2], [3, 4, 5], [6]]
        assert list(paddle.batch(lambda: iter(range(7)), 3, drop_last=True)()) == [
            [0, 1, 2], [3, 4, 5]]
        import pytest
        with pytest.raises(ValueError):
            paddle.batch(lambda: iter([]), 0)

    def test_dataparallel_and_callbacks_reachable(self):
        assert paddle.DataParallel is not None
        assert paddle.callbacks.Callback is not None

    def test_batch_feeds_dataloader_free_training(self):
        """v1 end-to-end: dataset -> reader.shuffle -> paddle.batch -> train."""
        import paddle_tpu.nn as nn
        from paddle_tpu import dataset, reader

        data = reader.firstn(dataset.uci_housing.train(), 64)
        paddle.seed(0)
        m = nn.Linear(13, 1)
        opt = paddle.optimizer.SGD(learning_rate=0.01, parameters=m.parameters())
        losses = []
        for b in paddle.batch(data, 16)():
            x = np.stack([np.asarray(f, np.float32).reshape(-1) for f, _ in b])
            y = np.asarray([t for _, t in b], np.float32).reshape(-1, 1)
            loss = ((m(paddle.to_tensor(x)) - paddle.to_tensor(y)) ** 2).mean()
            loss.backward(); opt.step(); opt.clear_grad()
            losses.append(float(loss.item()))
        assert len(losses) == 4 and np.isfinite(losses).all()

    def test_places_and_misc_staples(self):
        assert paddle.CUDAPinnedPlace() is not None
        import pytest
        for P in (paddle.NPUPlace, paddle.XPUPlace, paddle.IPUPlace,
                  paddle.MLUPlace, paddle.CustomPlace):
            with pytest.raises(RuntimeError, match="not available"):
                P(0)
        assert paddle.is_grad_enabled() in (True, False)
        assert paddle.get_cudnn_version() is None
        assert float(paddle.floor_mod(paddle.to_tensor(7), paddle.to_tensor(3)).item()) == 1
        x = paddle.to_tensor(np.array([2.0], np.float32))
        paddle.tanh_(x)
        np.testing.assert_allclose(x.numpy()[0], np.tanh(2.0), rtol=1e-4)
        assert isinstance(np.zeros(1).dtype, paddle.dtype)
        assert paddle.ParamAttr is not None
