"""HBM exhaustion resilience suite (ISSUE 14): the OOM classifier, preflight
memory admission, the recovery ladder (lazy flush retry, engine microbatch
degrade, serving pool shrink), the ``hbm.*`` chaos points, and the tier-1
inert tripwire pinning the zero-cost disabled path.
"""
import json
import threading

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu import nn, profiler
from paddle_tpu.core import lazy
from paddle_tpu.fault import inject, memory
from paddle_tpu.framework import flags
from paddle_tpu.serving.pool import PagePool

pytestmark = pytest.mark.faults


@pytest.fixture(autouse=True)
def _clean_state():
    prev = flags.get_flags([
        "FLAGS_hbm_admission", "FLAGS_hbm_budget_bytes", "FLAGS_lazy_donate",
    ])
    yield
    inject.disarm()
    flags.set_flags(prev)


def _oom_exc():
    return inject.hbm_oom_error("test")


def _train_steps(w, n, start=0, lr=0.1):
    """Simple lazy-mode training loop: rebinds w through the pending graph
    (donation candidate), one flush + one readback per step."""
    losses = []
    for i in range(start, start + n):
        x = paddle.to_tensor(
            np.random.RandomState(40 + i).randn(8, 4).astype(np.float32))
        loss = (paddle.matmul(x, w) ** 2).mean()
        loss.backward()
        w._set_data((w - lr * w.grad)._data)
        w.clear_grad()
        losses.append(float(loss.item()))
    return losses


# -- classifier ---------------------------------------------------------------
class TestClassifier:
    def test_resource_exhausted_classified(self):
        e = _oom_exc()
        assert memory.is_oom(e)
        info = memory.classify(e)
        assert info["kind"] == "hbm_oom"
        assert "RESOURCE_EXHAUSTED" in info["message"]

    def test_chained_cause_classified(self):
        try:
            try:
                raise _oom_exc()
            except Exception as inner:
                raise RuntimeError("step failed") from inner
        except RuntimeError as outer:
            assert memory.is_oom(outer)

    def test_non_oom_not_classified(self):
        assert not memory.is_oom(ValueError("nope"))
        assert not memory.is_oom(RuntimeError("some other runtime error"))
        # ambiguous allocation prose on a PLAIN type is not a device OOM
        assert not memory.is_oom(
            RuntimeError("Failed to allocate thread-local storage"))
        assert not memory.is_oom(OSError("Failed to allocate inode"))

    def test_memoryerror_classified(self):
        assert memory.is_oom(MemoryError("host allocation failed"))

    def test_budget_exceeded_carries_numbers(self):
        e = memory.HbmBudgetExceeded("lazy_flush", 1000, 600, 800, 400)
        assert e.predicted_bytes == 1000 and e.budget_bytes == 800
        assert "1000" in str(e) and "800" in str(e)


# -- PagePool park/unpark -----------------------------------------------------
class TestPagePoolPressure:
    def test_park_shrinks_headroom_and_conserves(self):
        pool = PagePool(16)  # 15 usable
        got = pool.alloc(4)
        parked = pool.park(6)
        assert parked == 6
        assert pool.free_blocks == 15 - 4 - 6
        assert pool.parked_blocks == 6
        pool.check()
        assert pool.alloc(pool.free_blocks + 1) is None  # parked invisible
        back = pool.unpark()
        assert back == 6 and pool.parked_blocks == 0
        pool.check()
        pool.free(got)
        pool.check()

    def test_park_never_drains_free_list(self):
        pool = PagePool(8)
        assert pool.park(100) == pool.num_blocks - 2  # one headroom block stays
        assert pool.free_blocks == 1
        pool.check()

    def test_double_free_still_raises_with_parked(self):
        pool = PagePool(8)
        ids = pool.alloc(2)
        pool.park(2)
        pool.free(ids)
        with pytest.raises(RuntimeError):
            pool.free(ids)


# -- preflight admission ------------------------------------------------------
class TestAdmission:
    def test_enforce_rejects_over_budget_then_recovers(self):
        # reference run with admission off — the reject/retry arm must
        # reproduce it bitwise
        w1 = paddle.to_tensor(np.full((4, 1), 0.5, np.float32))
        w1.stop_gradient = False
        ref = _train_steps(w1, 2)

        flags.set_flags({"FLAGS_hbm_admission": "enforce",
                         "FLAGS_hbm_budget_bytes": 10})
        w = paddle.to_tensor(np.full((4, 1), 0.5, np.float32))
        w.stop_gradient = False
        x = paddle.to_tensor(
            np.random.RandomState(40).randn(8, 4).astype(np.float32))
        loss = (paddle.matmul(x, w) ** 2).mean()
        loss.backward()
        w._set_data((w - 0.1 * w.grad)._data)
        w.clear_grad()
        rejects0 = profiler.counters().get("hbm_admission_rejects", 0)
        with pytest.raises(memory.HbmBudgetExceeded,
                           match=r"predicted \d+ bytes .* exceeds budget 10"):
            float(loss.item())
        assert profiler.counters().get("hbm_admission_rejects", 0) == rejects0 + 1
        # nothing was dispatched, the pending epoch was reinstated AND the
        # donation intent restored: raising the budget and re-reading the
        # SAME pending loss retries the SAME flush as a cache hit on the
        # already-compiled DONATING executable (a retry without donation
        # would re-key, recompile, and dispatch with a BIGGER footprint
        # exactly when memory is tightest)
        c0 = profiler.counters()
        flags.set_flags({"FLAGS_hbm_budget_bytes": 1 << 60})
        got = [float(loss.item())] + _train_steps(w, 1, start=1)
        assert got == ref
        np.testing.assert_array_equal(
            np.asarray(lazy.concrete(w1._data)),
            np.asarray(lazy.concrete(w._data)))
        c1 = profiler.counters()
        assert c1.get("lazy_donated_buffers", 0) > c0.get("lazy_donated_buffers", 0)

    def test_enforce_matches_unadmitted_run_bitwise(self):
        paddle.seed(0)
        w1 = paddle.to_tensor(np.full((4, 1), 0.5, np.float32))
        w1.stop_gradient = False
        l1 = _train_steps(w1, 4)
        flags.set_flags({"FLAGS_hbm_admission": "enforce",
                         "FLAGS_hbm_budget_bytes": 1 << 60})
        w2 = paddle.to_tensor(np.full((4, 1), 0.5, np.float32))
        w2.stop_gradient = False
        l2 = _train_steps(w2, 4)
        assert l1 == l2
        np.testing.assert_array_equal(
            np.asarray(lazy.concrete(w1._data)), np.asarray(lazy.concrete(w2._data)))

    def test_warn_mode_warns_and_dispatches(self):
        flags.set_flags({"FLAGS_hbm_admission": "warn",
                         "FLAGS_hbm_budget_bytes": 10})
        w = paddle.to_tensor(np.full((4, 2), 0.5, np.float32))
        w.stop_gradient = False
        with pytest.warns(RuntimeWarning, match="exceeds budget"):
            (loss,) = _train_steps(w, 1)
        assert np.isfinite(loss)

    def test_prediction_attached_to_flush_spans(self):
        flags.set_flags({"FLAGS_hbm_admission": "warn",
                         "FLAGS_hbm_budget_bytes": 1 << 60})
        w = paddle.to_tensor(np.full((4, 3), 0.5, np.float32))
        w.stop_gradient = False
        with profiler.profiler_guard(timer_only=True):
            _train_steps(w, 2)
            spans = profiler.span_events()
        flushes = [s for s in spans if s["name"] == "lazy_flush"]
        assert flushes
        assert any("hbm_predicted_peak_bytes" in (s.get("attrs") or {})
                   for s in flushes)
        # the compile-time capture rides a compile span too
        compiles = [s for s in spans if s["name"] == "compile"
                    and "hbm_exec_peak_bytes" in (s.get("attrs") or {})]
        assert compiles
        pred = memory.last_prediction()
        assert pred["hbm_predicted_peak_bytes"] >= pred["hbm_extra_bytes"] > 0

    def test_chaos_pressure_inflates_estimate(self):
        flags.set_flags({"FLAGS_hbm_admission": "enforce",
                         "FLAGS_hbm_budget_bytes": 1 << 40})
        w = paddle.to_tensor(np.full((4, 1), 0.5, np.float32))
        w.stop_gradient = False
        _train_steps(w, 1)  # fits comfortably
        inject.arm({"hbm.pressure": {"bytes": 1 << 41}})
        with pytest.raises(memory.HbmBudgetExceeded):
            _train_steps(w, 1, start=1)
        inject.disarm()
        (loss,) = _train_steps(w, 1, start=1)
        assert np.isfinite(loss)

    def test_donated_buffers_not_double_counted(self):
        """Memory-census correctness under donation: buffers the flush
        donates (dead-after-flush rebound params) are subtracted from the
        admission estimate — whether the backend reports the aliasing
        (alias_bytes) or silently declines (CPU: the donation mask's own
        byte count is the correction)."""
        flags.set_flags({"FLAGS_hbm_admission": "warn",
                         "FLAGS_hbm_budget_bytes": 1 << 60})

        def extra_for(donate):
            flags.set_flags({"FLAGS_lazy_donate": donate})
            d0 = profiler.counters().get("lazy_donated_buffers", 0)
            w = paddle.to_tensor(np.full((4, 64), 0.5, np.float32))
            w.stop_gradient = False
            _train_steps(w, 3)  # steady state: step 3 replays the cached exec
            donated = profiler.counters().get("lazy_donated_buffers", 0) - d0
            return memory.last_prediction()["hbm_extra_bytes"], donated

        extra_on, donated_on = extra_for(True)
        extra_off, donated_off = extra_for(False)
        assert donated_on > 0 and donated_off == 0
        # w is 4*64*4 = 1KiB; the donating arm's estimate must be smaller
        # by at least that one donated-then-freed buffer
        assert extra_on <= extra_off - 4 * 64 * 4


# -- lazy-flush recovery ladder ----------------------------------------------
class TestLazyLadder:
    def test_transient_oom_retried_bit_identical(self):
        paddle.seed(0)
        w1 = paddle.to_tensor(np.full((4, 1), 0.5, np.float32))
        w1.stop_gradient = False
        l1 = _train_steps(w1, 4)

        inject.arm("hbm.oom:op=lazy_flush,at=3,times=1")
        t0 = profiler.counters().get("hbm_oom_trips", 0)
        w2 = paddle.to_tensor(np.full((4, 1), 0.5, np.float32))
        w2.stop_gradient = False
        l2 = _train_steps(w2, 4)
        c = profiler.counters()
        assert c.get("hbm_oom_trips", 0) == t0 + 1
        assert c.get("hbm_oom_recoveries", 0) >= 1
        assert l1 == l2
        np.testing.assert_array_equal(
            np.asarray(lazy.concrete(w1._data)), np.asarray(lazy.concrete(w2._data)))

    def test_persistent_oom_exhausts_with_post_mortem(self, tmp_path,
                                                      monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_FLIGHT_DIR", str(tmp_path))
        inject.arm("hbm.oom:op=lazy_flush,from=1")
        w = paddle.to_tensor(np.full((4, 1), 0.5, np.float32))
        w.stop_gradient = False
        with pytest.raises(memory.HbmExhausted) as ei:
            _train_steps(w, 1)
        err = ei.value
        assert memory.is_oom(err.__cause__)
        actions = [a["action"] for a in err.attempts]
        assert actions == ["classify", "free_pressure", "retry"]
        assert err.dump_path is not None
        doc = json.loads(open(err.dump_path).read())
        assert doc["reason"] == "hbm_exhausted"
        assert doc["extra"]["where"] == "lazy_flush"
        assert "live_bytes" in doc["extra"]["census"]
        assert doc["extra"]["attempts"]
        # the flight context provider rides every dump from now on
        assert "hbm" in doc["context"]

    def test_free_pressure_evicts_cold_executables(self):
        # populate distinct flush signatures
        for k in range(6):
            w = paddle.to_tensor(np.ones((4, k + 1), np.float32))
            w.stop_gradient = False
            loss = (paddle.matmul(paddle.to_tensor(np.ones((8, 4), np.float32)), w) ** 2).mean()
            loss.backward()
            float(loss.item())
        before = len(lazy._flush_cache)
        assert before > 4
        summary = memory.free_pressure("test")
        assert summary["evicted_executables"] == before - 4
        assert len(lazy._flush_cache) == 4


# -- engine recovery ladder ---------------------------------------------------
class TestEngineLadder:
    def _run(self, spec=None, accum=1, steps=4, wus=False):
        from paddle_tpu.distributed.engine import HybridParallelEngine

        flags.set_flags({"FLAGS_shard_weight_update": wus})
        inject.disarm()
        if spec:
            inject.arm(spec)
        paddle.seed(5)
        m = nn.Linear(8, 4)
        opt = paddle.optimizer.Adam(learning_rate=0.05,
                                    parameters=m.parameters())
        eng = HybridParallelEngine(
            m, opt, lambda mm, x, y: F.mse_loss(mm(x), y),
            grad_accumulate=accum)
        losses = []
        for s in range(steps):
            rng = np.random.RandomState(300 + s)
            x = rng.randn(8, 8).astype(np.float32)
            y = rng.randn(8, 4).astype(np.float32)
            losses.append(float(np.asarray(lazy.concrete(
                eng.train_step(x, y)._data))))
        inject.disarm()
        ws = [np.asarray(lazy.concrete(p._data)).copy()
              for p in m.parameters()]
        return losses, ws, eng

    def test_transient_oom_retry_bit_identical(self):
        l1, w1, e1 = self._run("hbm.oom:op=engine.step,at=2,times=1")
        l2, w2, e2 = self._run(None)
        assert e1.grad_accumulate == 1  # retry recovered; no degrade
        assert l1 == l2
        for a, b in zip(w1, w2):
            np.testing.assert_array_equal(a, b)

    def test_degrade_bit_identical_to_accumulate_from_start(self):
        """The acceptance pin: OOM on every full-batch dispatch → the ladder
        re-runs each step through the grad-accumulate scan path at 2× —
        weights bit-identical to a run CONFIGURED with grad_accumulate=2
        from the start (sticky degrade: after the first incident the engine
        stays on the accumulate executable)."""
        l1, w1, e1 = self._run("hbm.oom:op=engine.step,from=1")
        l2, w2, e2 = self._run(None, accum=2)
        assert e1.grad_accumulate == 2
        assert e1._dispatch_op == "engine.accum"
        assert l1 == l2
        for a, b in zip(w1, w2):
            np.testing.assert_array_equal(a, b)
        assert profiler.counters().get("hbm_degraded_steps", 0) >= 1

    def test_ladder_exhaustion_halts_structured(self, tmp_path, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_FLIGHT_DIR", str(tmp_path))
        # no op filter: the synthesized OOM fires at EVERY consult site, so
        # retry AND both degrade rungs fail → structured halt
        with pytest.raises(memory.HbmExhausted) as ei:
            self._run("hbm.oom:from=1")
        actions = [a["action"] for a in ei.value.attempts]
        assert "free_pressure" in actions
        assert "degrade_x2" in actions and "degrade_x4" in actions
        assert ei.value.dump_path is not None

    def test_wus_engine_degrades_to_replicated_accum(self):
        """A sharded-weight-update engine that OOMs degrades onto the
        replicated accumulate path (wus has no accumulation, PR 3) — the
        same executable a from-start accumulate config builds."""
        l1, w1, e1 = self._run("hbm.oom:op=engine.step,from=1", wus=True)
        l2, w2, e2 = self._run(None, accum=2, wus=True)
        assert e1._wus is None and e1.grad_accumulate == 2
        assert l1 == l2
        for a, b in zip(w1, w2):
            np.testing.assert_array_equal(a, b)


# -- serving under memory pressure -------------------------------------------
class TestServingPressure:
    def test_oom_shrinks_pool_and_completes_all_streams(self):
        from serving_util import ENGINE_KW, make_prompts, tiny_gpt
        from paddle_tpu.serving import Engine

        m = tiny_gpt()
        rng = np.random.RandomState(0)
        prompts = make_prompts(12, rng)
        ref = Engine(m, **ENGINE_KW)
        try:
            expect = [ref.generate(p, max_new_tokens=8) for p in prompts]
        finally:
            ref.close()

        inject.arm("hbm.oom:op=serve.step,at=2,times=1;"
                   "hbm.pressure:blocks=8,at=1,times=1")
        eng = Engine(m, **ENGINE_KW)
        try:
            hs = [eng.submit(p, max_new_tokens=8) for p in prompts]
            outs = [h.result(timeout=120) for h in hs]
            st = eng.stats()
            eng._pool.check()  # conservation incl. parked blocks
            assert outs == expect  # every stream completed, bit-identical
            assert st["pages_parked"] > 0
            assert st["pages_used"] == 0
            c = profiler.counters()
            assert c.get("serve_pool_shrunk", 0) > 0
            assert eng.health()["ok"]  # backpressure, never a crash
        finally:
            eng.close()
            inject.disarm()

    def test_parked_blocks_return_after_pressure_clears(self, monkeypatch):
        """A transient OOM must not ratchet serving capacity down forever:
        after a clean-step window the scheduler unparks blocks half at a
        time until the pool is whole again."""
        from serving_util import ENGINE_KW, make_prompts, tiny_gpt
        from paddle_tpu.serving import Engine

        monkeypatch.setattr(Engine, "_UNPARK_AFTER", 2)
        inject.arm("hbm.oom:op=serve.step,at=1,times=1")
        eng = Engine(tiny_gpt(), **ENGINE_KW)
        try:
            rng = np.random.RandomState(1)
            for p in make_prompts(6, rng):
                eng.generate(p, max_new_tokens=12)
            inject.disarm()
            assert eng._pool.parked_blocks == 0
            assert eng._pool.free_blocks == eng._pool.num_blocks - 1
            eng._pool.check()
            assert profiler.counters().get("serve_pages_unparked", 0) > 0
        finally:
            eng.close()
            inject.disarm()

    def test_training_free_pressure_reaches_live_engines(self):
        from serving_util import ENGINE_KW, tiny_gpt
        from paddle_tpu.serving import Engine

        eng = Engine(tiny_gpt(), **ENGINE_KW)
        try:
            free0 = eng._pool.free_blocks
            summary = memory.free_pressure("test")
            assert eng._provider in summary["handlers"]
            # the scheduler applies the shrink at its next step boundary
            eng.generate([1, 2, 3], max_new_tokens=2)
            assert eng._pool.parked_blocks > 0
            assert eng._pool.free_blocks < free0
            eng._pool.check()
        finally:
            eng.close()


# -- cross-rank verdict barrier (satellite: PR 13 follow-up) ------------------
class TestVerdictBarrier:
    def _verdict(self, step=7, action="rollback"):
        from paddle_tpu.fault.sentinel import StabilityVerdict

        return StabilityVerdict(action, step, (0, step), "loss", 9e9, 120.0,
                                True, {"loss": 9e9})

    def test_single_rank_world_returns_local(self, tmp_path):
        from paddle_tpu.distributed.coord import FileStore
        from paddle_tpu.fault.sentinel import VerdictBarrier

        vb = VerdictBarrier(FileStore(str(tmp_path)), 1, 0)
        v = self._verdict()
        assert vb.exchange(v) is v
        assert vb.exchange(None) is None

    def test_rank_local_verdict_adopted_world_wide(self, tmp_path):
        """Rank 1 trips; rank 0 exchanges None and must come back with rank
        1's verdict folded into its own sentinel (quarantine + ladder)."""
        from paddle_tpu.distributed.coord import FileStore
        from paddle_tpu.fault.sentinel import StabilitySentinel, VerdictBarrier

        store = FileStore(str(tmp_path))
        sentinels = [StabilitySentinel(window=8, warmup=2, zmax=50),
                     StabilitySentinel(window=8, warmup=2, zmax=50)]
        barriers = [VerdictBarrier(store, 2, r, sentinel=sentinels[r])
                    for r in range(2)]
        v = self._verdict()
        results = [None, None]

        def run(rank):
            results[rank] = barriers[rank].exchange(v if rank == 1 else None)

        ts = [threading.Thread(target=run, args=(r,)) for r in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=60)
        try:
            assert results[1] is v  # the originator keeps its own verdict
            adopted = results[0]
            assert adopted.action == "rollback" and adopted.step == v.step
            assert adopted.origin_rank == 1
            # rank 0's sentinel quarantined the batch and consumed the rung
            assert sentinels[0].is_quarantined(pos=(0, 7))
            assert sentinels[0]._rollbacks_used == 1
            assert sentinels[1]._rollbacks_used == 0  # _judge counted its own
            assert profiler.counters().get("stability_coordinated_trips", 0) >= 1
        finally:
            for s in sentinels:
                s.close()


    def test_both_ranks_tripping_count_one_rung_each(self, tmp_path):
        """A rank whose own verdict was merely OUTRANKED by a remote one
        already consumed its ladder rung in _judge — exchange must not
        adopt on top (double-counting would desync the ladders and make
        one rank escalate early: the exact divergence the barrier
        prevents)."""
        from paddle_tpu.distributed.coord import FileStore
        from paddle_tpu.fault.sentinel import StabilitySentinel, VerdictBarrier

        store = FileStore(str(tmp_path))
        sents = [StabilitySentinel(window=8, warmup=2, zmax=50)
                 for _ in range(2)]
        barriers = [VerdictBarrier(store, 2, r, sentinel=sents[r])
                    for r in range(2)]
        # rank 1's verdict outranks rank 0's (higher z)
        vs = [self._verdict(), self._verdict()]
        vs[1].zscore = 500.0
        # simulate observe() having consumed a rung locally on BOTH ranks
        for s in sents:
            s._rollbacks_used = 1
        results = [None, None]

        def run(rank):
            results[rank] = barriers[rank].exchange(vs[rank])

        ts = [threading.Thread(target=run, args=(r,)) for r in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=60)
        try:
            assert results[0].origin_rank == 1  # rank 0 adopted the winner
            assert results[1] is vs[1]
            # neither rank double-counted
            assert sents[0]._rollbacks_used == 1
            assert sents[1]._rollbacks_used == 1
        finally:
            for s in sents:
                s.close()

    def test_equal_verdicts_resolve_to_one_world_choice(self, tmp_path):
        """Two rank-local trips with EQUAL (severity, z) — e.g. both
        nonfinite, z=inf — must resolve identically on every rank (lowest
        origin rank wins), or the world quarantines different batches and
        rolls back to different anchors."""
        from paddle_tpu.distributed.coord import FileStore
        from paddle_tpu.fault.sentinel import VerdictBarrier

        store = FileStore(str(tmp_path))
        barriers = [VerdictBarrier(store, 2, r) for r in range(2)]
        vs = [self._verdict(), self._verdict()]
        vs[0].pos, vs[1].pos = (0, 7), (1, 7)  # different condemned batches
        results = [None, None]

        def run(rank):
            results[rank] = barriers[rank].exchange(vs[rank])

        ts = [threading.Thread(target=run, args=(r,)) for r in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=60)
        assert results[0] is vs[0]  # rank 0 keeps its own (it won the tie)
        assert results[1].origin_rank == 0  # rank 1 adopted rank 0's choice
        assert tuple(results[1].pos) == (0, 7)

    def test_store_footprint_stays_bounded_across_rounds(self, tmp_path):
        """One live round of barrier/verdict keys, not one pair per step —
        a week-long run must not fill the store with round litter."""
        from paddle_tpu.distributed.coord import FileStore
        from paddle_tpu.fault.sentinel import VerdictBarrier

        store = FileStore(str(tmp_path))
        vb = VerdictBarrier(store, 1, 0)
        for i in range(12):
            vb.exchange(self._verdict(step=i) if i % 3 == 0 else None)
        # at most the live round's keys survive (ack + commit + verdict)
        assert len(store.keys()) <= 3

# -- tier-1 inert tripwire ----------------------------------------------------
class TestInertTripwire:
    def test_unconfigured_loop_never_touches_classifier_or_preflight(
            self, monkeypatch):
        """FLAGS_hbm_admission=off (default) + nothing armed → the
        classifier and the preflight are NEVER called (exploded here), no
        per-step census runs, and no hbm counters move — the whole disabled
        path is one flag probe per flush and one module-attribute probe per
        dispatch site."""
        assert flags.flag("FLAGS_hbm_admission") == "off"

        def boom(*a, **k):
            raise AssertionError("fault.memory touched without admission/OOM")

        monkeypatch.setattr(memory, "preflight", boom)
        monkeypatch.setattr(memory, "classify", boom)
        monkeypatch.setattr(memory, "is_oom", boom)
        monkeypatch.setattr(memory, "free_pressure", boom)
        censuses0 = profiler.memory_stats().get("censuses", 0)
        c0 = profiler.counters()

        # lazy train loop
        w = paddle.to_tensor(np.full((4, 1), 0.5, np.float32))
        w.stop_gradient = False
        _train_steps(w, 3)
        # eager per-op loop
        with lazy.lazy_guard(False):
            t = paddle.to_tensor(np.ones((16,), np.float32))
            for _ in range(3):
                t = t + 1.0
            float(t.numpy()[0])
        # engine step
        from paddle_tpu.distributed.engine import HybridParallelEngine

        paddle.seed(0)
        m = nn.Linear(4, 2)
        opt = paddle.optimizer.SGD(learning_rate=0.01,
                                   parameters=m.parameters())
        eng = HybridParallelEngine(
            m, opt, lambda mm, x, y: F.mse_loss(mm(x), y))
        eng.train_step(np.ones((8, 4), np.float32), np.ones((8, 2), np.float32))

        assert profiler.memory_stats().get("censuses", 0) == censuses0
        c1 = profiler.counters()
        for k in ("hbm_admission_checks", "hbm_admission_rejects",
                  "hbm_oom_trips", "hbm_oom_recoveries"):
            assert c1.get(k, 0) == c0.get(k, 0)
