"""Serving chaos suite — the ``serve.*`` injection points under load.

Drives the round-12 resilience layer the way production fails: engine
crashes mid-decode under a multi-stream load (supervisor restart must keep
every greedy stream bit-identical to an uninterrupted run), wedge detection
inside the watchdog deadline, pool corruption contained to a restart, a
straggling scheduler missing deadlines, and a 4x-overload storm that the
engine must SHED (bounded admitted-latency, conserved pool) instead of
stalling. The round-17 durability drives ride along: repeated crashes with
snapshot re-attach armed, the prefix-chain restore under active sharing, a
rolling engine→engine→engine handoff chain, and a crash racing the handoff
quiesce — every interleaving completes or falls back whole, bit-identical.
Marked ``chaos`` like the PR 8 recovery suite: heavier multi-round
drives, opt-in via PADDLE_TPU_CHAOS=1 on the CPU tier; the single-shot
tier-1 pins live in tests/test_serving_resilience.py.
"""
import time

import numpy as np
import pytest

from paddle_tpu import profiler
from paddle_tpu.fault import inject
from paddle_tpu.serving import (
    DeadlineExceeded, Engine, Overloaded, ServeError, ServingSupervisor,
)
from serving_util import ENGINE_KW, make_prompts as _prompts, tiny_gpt

pytestmark = pytest.mark.chaos

# a deeper pool than the base config: the storm/restart drives need headroom
_KW = dict(ENGINE_KW, num_blocks=128)


@pytest.fixture(scope="module")
def model():
    return tiny_gpt()


@pytest.fixture(autouse=True)
def _disarm():
    yield
    inject.disarm()


class TestCrashRecovery:
    def test_repeated_crashes_under_load_stay_bit_identical(self, model):
        """Sixteen greedy streams, the engine loop crashes TWICE mid-drive
        (steps 5 and 12): the supervisor restarts both times and every
        stream's output is bit-identical to an uninterrupted run — the
        accumulated-tokens re-prefill continuation changes nothing."""
        rng = np.random.RandomState(0)
        prompts = _prompts(16, rng)
        with Engine(model, **_KW) as eng:
            baseline = [eng.submit(p, max_new_tokens=10).result(timeout=600)
                        for p in prompts]
        inject.arm({"serve.crash": {"at": 5}})
        with ServingSupervisor(model, watchdog_s=5.0, **_KW) as sup:
            hs = [sup.submit(p, max_new_tokens=10) for p in prompts]
            # re-arm mid-drive: a second crash against the restarted engine
            deadline = time.monotonic() + 60
            while not inject.fired_counts().get("serve.crash") \
                    and time.monotonic() < deadline:
                time.sleep(0.01)
            inject.arm({"serve.crash": {"at": 7}})
            outs = [h.result(timeout=600) for h in hs]
            assert sup.restarts == 2
        assert outs == baseline

    def test_pool_corruption_contained_by_restart(self, model):
        """serve.pool_corrupt breaks block conservation; the resulting
        double-free crashes the loop, the supervisor restarts with a FRESH
        pool, harvested sequences requeue (their dead-pool blocks dropped),
        and greedy outputs still match the uninterrupted run."""
        rng = np.random.RandomState(1)
        prompts = _prompts(6, rng)
        with Engine(model, **_KW) as eng:
            baseline = [eng.submit(p, max_new_tokens=8).result(timeout=600)
                        for p in prompts]
        c0 = profiler.counters().get("serve_pool_damaged", 0)
        inject.arm("serve.pool_corrupt:at=3")
        with ServingSupervisor(model, watchdog_s=5.0, **_KW) as sup:
            hs = [sup.submit(p, max_new_tokens=8) for p in prompts]
            outs = [h.result(timeout=600) for h in hs]
            assert sup.restarts >= 1
            # the restarted engine's pool conserves
            st = sup.stats()
            assert st["pages_used"] == 0
        assert outs == baseline
        assert profiler.counters().get("serve_pool_damaged", 0) > c0


class TestWedgeDetection:
    def test_wedge_detected_within_watchdog_deadline(self, model):
        """From the moment the heartbeat goes stale, the supervisor must
        declare the wedge within FLAGS_serve_watchdog_s — the in-flight
        handle fails structurally (never hangs) inside that bound."""
        rng = np.random.RandomState(2)
        watchdog_s = 3.0
        with ServingSupervisor(model, watchdog_s=watchdog_s, **_KW) as sup:
            sup.generate(rng.randint(0, 211, (5,)).tolist(), max_new_tokens=3)
            inject.arm("serve.wedge:at=2,ms=120000")
            t0 = time.monotonic()
            h = sup.submit(rng.randint(0, 211, (5,)).tolist(),
                           max_new_tokens=60)
            with pytest.raises(ServeError, match="wedged"):
                h.result(timeout=60)
            elapsed = time.monotonic() - t0
            assert elapsed < watchdog_s + 1.0, \
                f"wedge took {elapsed:.2f}s to surface (watchdog {watchdog_s}s)"
            inject.disarm()
            # restarted engine serves
            assert len(sup.generate(rng.randint(0, 211, (4,)).tolist(),
                                    max_new_tokens=3)) == 7


class TestStraggler:
    def test_slow_step_drives_deadline_misses_not_hangs(self, model):
        """serve.slow_step makes every scheduler step a straggler; deadlined
        requests miss and fail structurally while deadline-free traffic
        still completes — bounded-latency degradation, not a stall."""
        rng = np.random.RandomState(3)
        inject.arm("serve.slow_step:from=1,ms=80")
        with Engine(model, **_KW) as eng:
            free = eng.submit(rng.randint(0, 211, (5,)).tolist(),
                              max_new_tokens=6)
            timed = [eng.submit(p, max_new_tokens=60, deadline_s=0.5)
                     for p in _prompts(4, rng)]
            misses = 0
            for h in timed:
                try:
                    h.result(timeout=120)
                except DeadlineExceeded:
                    misses += 1
            assert misses == len(timed)
            assert len(free.result(timeout=120)) == 11
            assert eng.stats()["pages_used"] == 0


class TestSharingUnderChaos:
    def test_crash_mid_share_restart_bit_identical_with_cache_armed(self, model):
        """serve.crash fires while streams are actively sharing cached
        prefix blocks: the dying engine's containment sweep must release
        the index's references without double-freeing the sharers' (one
        pool, many refs per block), and the supervisor's restart — fresh
        pool, fresh cache — re-prefills and stays bit-identical."""
        rng = np.random.RandomState(10)
        shared = rng.randint(0, 211, (40,)).tolist()
        prompts = [shared + rng.randint(0, 211, (int(rng.randint(3, 10)),)).tolist()
                   for _ in range(12)]
        with Engine(model, **_KW) as eng:
            baseline = [eng.submit(p, max_new_tokens=10).result(timeout=600)
                        for p in prompts]
        inject.arm({"serve.crash": {"at": 5}})
        with ServingSupervisor(model, watchdog_s=5.0, prefix_cache=True,
                               **_KW) as sup:
            hs = [sup.submit(p, max_new_tokens=10) for p in prompts]
            deadline = time.monotonic() + 60
            while not inject.fired_counts().get("serve.crash") \
                    and time.monotonic() < deadline:
                time.sleep(0.01)
            inject.arm({"serve.crash": {"at": 7}})
            outs = [h.result(timeout=600) for h in hs]
            assert sup.restarts == 2
            # restarted engine's pool conserves with the cache re-armed:
            # the only residents are the index's own references
            st = sup.stats()
            assert st["pages_used"] == st["pages_cached"]
        assert outs == baseline

    def test_preemption_of_sharers_over_rounds_never_corrupts_peers(self, model):
        """Multi-round sharer-preemption drive: a pool sized so concurrent
        growth past the shared prefix must preempt sharers repeatedly.
        Victims re-match the cache on resume, peers keep decoding off the
        same physical blocks, and every round is bit-identical with the
        pool conserving (no double-free of a shared block, ever)."""
        rng = np.random.RandomState(11)
        shared = rng.randint(0, 211, (40,)).tolist()
        prompts = [shared + rng.randint(0, 211, (6,)).tolist()
                   for _ in range(4)]
        with Engine(model, **_KW) as eng:
            baseline = [eng.submit(p, max_new_tokens=24).result(timeout=600)
                        for p in prompts]
        kw = dict(ENGINE_KW, num_blocks=20)
        preempted = profiler.counters().get("serve_preempted", 0)
        with Engine(model, prefix_cache=True, **kw) as eng:
            for _ in range(4):
                hs = [eng.submit(p, max_new_tokens=24) for p in prompts]
                outs = [h.result(timeout=600) for h in hs]
                assert outs == baseline
                eng._pool.check()
            st = eng.stats()
            assert st["pages_used"] == st["pages_cached"]
        assert profiler.counters().get("serve_preempted", 0) > preempted


class TestDurabilityChaos:
    def test_repeated_crashes_with_snapshot_stay_bit_identical(self, model):
        """Sixteen greedy streams, the loop crashes TWICE with snapshot
        recovery armed: both restarts RE-ATTACH (zero tokens re-prefilled
        across the whole drive) and every stream is bit-identical to an
        uninterrupted run — durability composes across repeated failures."""
        rng = np.random.RandomState(20)
        prompts = _prompts(16, rng)
        with Engine(model, **_KW) as eng:
            baseline = [eng.submit(p, max_new_tokens=10).result(timeout=600)
                        for p in prompts]
        c0 = profiler.counters().get("serve_reprefill_tokens", 0)
        inject.arm({"serve.crash": {"at": 5}})
        with ServingSupervisor(model, watchdog_s=5.0, snapshot=True,
                               **_KW) as sup:
            hs = [sup.submit(p, max_new_tokens=10) for p in prompts]
            deadline = time.monotonic() + 60
            while not inject.fired_counts().get("serve.crash") \
                    and time.monotonic() < deadline:
                time.sleep(0.01)
            inject.arm({"serve.crash": {"at": 7}})
            outs = [h.result(timeout=600) for h in hs]
            assert sup.restarts == 2
            assert sup.health()["last_recovery"]["mode"] == "reattach"
            assert sup.stats()["pages_used"] == 0
        assert outs == baseline
        assert profiler.counters().get("serve_reprefill_tokens", 0) == c0

    def test_crash_mid_share_snapshot_restores_prefix_chain(self, model):
        """The PR 16 + PR 17 composition under chaos: streams actively
        sharing cached prefix blocks when the loop dies. The snapshot
        carries the index's references and CoW refcounts; the restored pool
        conserves with the chain intact and every stream stays
        bit-identical with zero re-prefill."""
        rng = np.random.RandomState(21)
        shared = rng.randint(0, 211, (40,)).tolist()
        prompts = [shared + rng.randint(0, 211,
                                        (int(rng.randint(3, 10)),)).tolist()
                   for _ in range(12)]
        with Engine(model, **_KW) as eng:
            baseline = [eng.submit(p, max_new_tokens=10).result(timeout=600)
                        for p in prompts]
        c0 = profiler.counters().get("serve_reprefill_tokens", 0)
        inject.arm({"serve.crash": {"at": 5}})
        with ServingSupervisor(model, watchdog_s=5.0, snapshot=True,
                               prefix_cache=True, **_KW) as sup:
            hs = [sup.submit(p, max_new_tokens=10) for p in prompts]
            outs = [h.result(timeout=600) for h in hs]
            assert sup.restarts == 1
            st = sup.stats()
            assert st["pages_used"] == st["pages_cached"]
        assert outs == baseline
        assert profiler.counters().get("serve_reprefill_tokens", 0) == c0

    def test_handoff_chain_under_load(self, model):
        """Rolling-upgrade drive: twelve live streams handed off engine →
        engine → engine mid-decode. Each hop quiesces, adopts, and resumes
        without re-prefill; the third engine finishes everything
        bit-identical."""
        rng = np.random.RandomState(22)
        prompts = _prompts(12, rng)
        with Engine(model, **_KW) as eng:
            baseline = [eng.submit(p, max_new_tokens=12).result(timeout=600)
                        for p in prompts]
        c0 = profiler.counters().get("serve_reprefill_tokens", 0)
        eng = Engine(model, **_KW)
        hs = [eng.submit(p, max_new_tokens=12) for p in prompts]
        try:
            for _hop in range(2):
                deadline = time.monotonic() + 60
                while eng.stats()["decode_steps"] < 2 \
                        and time.monotonic() < deadline:
                    time.sleep(0.005)
                snap = eng.handoff()
                succ = Engine(model, **_KW)
                info = succ.adopt(snap)
                assert info["mode"] == "reattach"
                eng.close()
                eng = succ
            outs = [h.result(timeout=600) for h in hs]
            assert eng.stats()["pages_used"] == 0
        finally:
            eng.close()
        assert outs == baseline
        assert profiler.counters().get("serve_reprefill_tokens", 0) == c0

    def test_crash_during_handoff_falls_back_whole(self, model):
        """serve.crash lands between the handoff request and the quiesce:
        handoff() must fail structurally (never a torn half-export), the
        dying engine's handles fail or recover through the crash path, and
        a fresh engine serves the same traffic bit-identical."""
        rng = np.random.RandomState(23)
        prompts = _prompts(6, rng)
        with Engine(model, **_KW) as eng:
            baseline = [eng.submit(p, max_new_tokens=8).result(timeout=600)
                        for p in prompts]
        old = Engine(model, **_KW)
        try:
            inject.arm("serve.crash:at=2")
            hs = [old.submit(p, max_new_tokens=8) for p in prompts]
            deadline = time.monotonic() + 60
            while not inject.fired_counts().get("serve.crash") \
                    and time.monotonic() < deadline:
                time.sleep(0.005)
            with pytest.raises(ServeError):
                old.handoff(timeout=10.0)
            inject.disarm()
            for h in hs:
                with pytest.raises(ServeError):
                    h.result(timeout=10)
        finally:
            old.close()
        with Engine(model, **_KW) as new:
            outs = [new.submit(p, max_new_tokens=8).result(timeout=600)
                    for p in prompts]
        assert outs == baseline


class TestOverloadStorm:
    def test_shed_keeps_engine_healthy_and_latency_bounded(self, model):
        """A 4x-style open-loop storm against a shed-armed engine: some
        requests shed (Overloaded), admitted ones complete with pool
        conservation intact, and the engine remains healthy and ready
        afterwards — overload is a first-class, recoverable state."""
        rng = np.random.RandomState(4)
        kw = dict(_KW, max_batch=4, max_queue=4, shed=True)
        shed = completed = missed = 0
        with Engine(model, **kw) as eng:
            # unloaded reference latency
            ref = [eng.submit(p, max_new_tokens=6) for p in _prompts(4, rng)]
            [h.result(timeout=600) for h in ref]
            p99_ref = max(h.latency_s for h in ref)
            handles = []
            for p in _prompts(120, rng, lo=3, hi=12):
                try:
                    handles.append(eng.submit(p, max_new_tokens=6,
                                              deadline_s=max(2.0, 4 * p99_ref)))
                except Overloaded as e:
                    assert e.retry_after_s > 0
                    shed += 1
            for h in handles:
                try:
                    h.result(timeout=600)
                    completed += 1
                except DeadlineExceeded:
                    missed += 1
            assert shed > 0, "storm never tripped the shed policy"
            assert completed > 0
            lat = sorted(h.latency_s for h in handles if h.latency_s and h.done
                         and h._req.error is None)
            # bounded p99 for admitted work: within the deadline we offered
            assert lat[-1] <= max(2.0, 4 * p99_ref) + 1.0
            eng._pool.check()
            assert eng.stats()["pages_used"] == 0
            assert eng.health()["ok"] and eng.ready()
            # and it still serves a clean request afterwards
            out = eng.submit(rng.randint(0, 211, (4,)).tolist(),
                             max_new_tokens=3).result(timeout=300)
            assert len(out) == 7
