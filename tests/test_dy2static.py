"""@to_static control-flow conversion (jit/dy2static.py).

Acceptance patterns modeled on the reference's
``unittests/dygraph_to_static/`` suite (test_ifelse.py, test_loop.py,
test_logical.py): tensor-dependent if/while/for compile under to_static and
match eager execution exactly.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F


def _t(a):
    return paddle.to_tensor(np.asarray(a, dtype=np.float32))


class TestIfElse:
    def test_tensor_if_both_paths(self):
        def fn(x):
            if x.mean() > 0:
                y = x * 2.0
            else:
                y = x - 1.0
            return y

        st = paddle.jit.to_static(fn)
        xp = _t([1.0, 2.0, 3.0])
        xn = _t([-1.0, -2.0, -3.0])
        np.testing.assert_allclose(st(xp).numpy(), fn(xp).numpy())
        np.testing.assert_allclose(st(xn).numpy(), fn(xn).numpy())

    def test_if_updates_existing_var(self):
        def fn(x):
            y = x + 1.0
            if x.sum() > 100.0:
                y = y * 10.0
            else:
                y = y / 2.0
            return y

        st = paddle.jit.to_static(fn)
        x = _t([1.0, 2.0])
        np.testing.assert_allclose(st(x).numpy(), fn(x).numpy())

    def test_nested_if(self):
        def fn(x):
            if x.mean() > 0:
                if x.max() > 2.0:
                    y = x * 3.0
                else:
                    y = x * 2.0
            else:
                y = -x
            return y

        st = paddle.jit.to_static(fn)
        for a in ([1.0, 5.0], [1.0, 1.5], [-1.0, -2.0]):
            x = _t(a)
            np.testing.assert_allclose(st(x).numpy(), fn(x).numpy())

    def test_python_if_still_works(self):
        def fn(x, flag=True):
            if flag:  # plain python predicate
                return x + 1.0
            return x - 1.0

        st = paddle.jit.to_static(fn)
        x = _t([1.0])
        np.testing.assert_allclose(st(x).numpy(), [2.0])


class TestWhile:
    def test_tensor_bounded_while(self):
        def fn(x):
            s = x * 0.0
            i = paddle.to_tensor(np.float32(0.0))
            while i < 5.0:
                s = s + x
                i = i + 1.0
            return s

        st = paddle.jit.to_static(fn)
        x = _t([1.0, 2.0])
        np.testing.assert_allclose(st(x).numpy(), fn(x).numpy())
        np.testing.assert_allclose(st(x).numpy(), [5.0, 10.0])

    def test_while_data_dependent_condition(self):
        def fn(x):
            # double until the sum crosses a data-dependent threshold
            while x.sum() < 100.0:
                x = x * 2.0
            return x

        st = paddle.jit.to_static(fn)
        x = _t([1.0, 2.0])
        np.testing.assert_allclose(st(x).numpy(), fn(x).numpy())


class TestLogical:
    def test_and_or_not_on_tensors(self):
        def fn(x):
            if (x.mean() > 0.0) and (x.max() < 10.0):
                y = x + 1.0
            else:
                y = x - 1.0
            return y

        st = paddle.jit.to_static(fn)
        for a in ([1.0, 2.0], [1.0, 20.0], [-1.0, -2.0]):
            x = _t(a)
            np.testing.assert_allclose(st(x).numpy(), fn(x).numpy())

    def test_return_in_tensor_branch_raises_clearly(self):
        def fn(x):
            if x.mean() > 0.0:
                return x + 1.0
            return x - 1.0

        st = paddle.jit.to_static(fn)
        with pytest.raises(TypeError, match="traced Tensor"):
            st(_t([1.0, 2.0]))


class TestLayerForward:
    def test_layer_with_tensor_if(self):
        class Gate(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc = nn.Linear(4, 4)

            def forward(self, x):
                h = self.fc(x)
                if h.mean() > 0:
                    out = F.relu(h)
                else:
                    out = h * 0.1
                return out

        paddle.seed(0)
        g = Gate()
        x = _t(np.random.RandomState(0).randn(2, 4))
        eager = g(x).numpy()
        st = paddle.jit.to_static(g)
        np.testing.assert_allclose(st(x).numpy(), eager, rtol=1e-6)

    def test_grads_flow_through_converted_if(self):
        class Gate(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc = nn.Linear(4, 4)

            def forward(self, x):
                h = self.fc(x)
                if h.sum() > 0:
                    y = h * 2.0
                else:
                    y = h * 3.0
                return y.sum()

        paddle.seed(1)
        g1, g2 = Gate(), Gate()
        g2.set_state_dict(g1.state_dict())
        x = _t(np.random.RandomState(1).randn(2, 4))
        g1(x).backward()
        st = paddle.jit.to_static(g2)
        st(x).backward()
        np.testing.assert_allclose(
            g1.fc.weight.grad.numpy(), g2.fc.weight.grad.numpy(), rtol=1e-5
        )


class TestForRange:
    def test_for_over_tensor_range(self):
        def fn(x, n):
            s = x * 0.0
            for i in range(n):
                s = s + x
            return s

        st = paddle.jit.to_static(fn)
        x = _t([1.0, 2.0])
        n = paddle.to_tensor(np.int64(4))
        np.testing.assert_allclose(st(x, n).numpy(), [4.0, 8.0])

    def test_for_uses_index(self):
        def fn(x, n):
            s = x * 0.0
            for i in range(n):
                s = s + x * float(1.0) + i
            return s

        st = paddle.jit.to_static(fn)
        x = _t([0.0, 0.0])
        n = paddle.to_tensor(np.int64(3))
        # s = sum_{i<3} (x + i) = 0+1+2 = 3
        np.testing.assert_allclose(st(x, n).numpy(), [3.0, 3.0])

    def test_negative_step_range_stays_python(self):
        def make(n):
            def fn(x):
                s = x * 0.0
                for i in range(n, 0, -1):
                    s = s + i
                return s

            return fn

        st = paddle.jit.to_static(make(3))
        np.testing.assert_allclose(st(_t([0.0])).numpy(), [6.0])  # 3+2+1

    def test_loop_var_after_loop_matches_python(self):
        def fn(x, n):
            s = x * 0.0
            for i in range(n):
                s = s + x
            return s + i

        st = paddle.jit.to_static(fn)
        x = _t([1.0, 1.0])
        n = paddle.to_tensor(np.int64(3))
        # python: i ends at 2 → 3 + 2 = 5
        np.testing.assert_allclose(st(x, n).numpy(), [5.0, 5.0])


class TestTransformScope:
    def test_closure_overrides_global(self):
        def make(thresh):
            def fn(x):
                if x.mean() > thresh:
                    y = x * 2.0
                else:
                    y = x * 0.0
                return y
            return fn

        st = paddle.jit.to_static(make(100.0))
        np.testing.assert_allclose(st(_t([1.0, 1.0])).numpy(), [0.0, 0.0])

    def test_no_control_flow_keeps_live_globals(self):
        import types
        mod = types.ModuleType("m_live")
        exec("SCALE = 1.0\ndef f(x):\n    return x * SCALE\n", mod.__dict__)
        st = paddle.jit.to_static(mod.f)
        mod.SCALE = 3.0
        np.testing.assert_allclose(st(_t([1.0])).numpy(), [3.0])
