"""Serving engine — continuous batching + paged KV cache over compiled decode.

Pins the ISSUE-11 acceptance surface: continuous-batched greedy outputs
bit-identical to sequential per-request decode (and to the dense
``generate()`` path), page-pool alloc/free invariants (no leak, no
double-free, OOM → backpressure/preemption not crash), mid-stream cancel,
compile-count ≤ bucket count on a warm cache, the int8 serving path, and the
batched-decode EOS satellite in ``models/generation.py``.
"""
import threading
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import profiler
from paddle_tpu.serving import (
    Engine, PagePool, RequestCancelled, ServeError,
)
from serving_util import ENGINE_KW as _ENGINE_KW
from serving_util import make_prompts as _prompts, tiny_gpt as _tiny_gpt


@pytest.fixture(scope="module")
def model():
    return _tiny_gpt()


class TestContinuousBatching:
    def test_batched_bit_identical_to_sequential(self, model):
        rng = np.random.RandomState(0)
        prompts = _prompts(6, rng)
        with Engine(model, **_ENGINE_KW) as eng:
            handles = [eng.submit(p, max_new_tokens=8) for p in prompts]
            batched = [h.result(timeout=300) for h in handles]
            assert eng.stats()["running"] == 0
        with Engine(model, **_ENGINE_KW) as eng:
            sequential = [
                eng.submit(p, max_new_tokens=8).result(timeout=300)
                for p in prompts
            ]
        # THE acceptance pin: continuous batching must not change a single
        # token vs serving each request alone (greedy)
        assert batched == sequential
        for p, out in zip(prompts, batched):
            assert out[:len(p)] == p and len(out) == len(p) + 8

    def test_matches_dense_generate_greedy(self, model):
        rng = np.random.RandomState(1)
        p = rng.randint(0, 211, (11,)).tolist()
        with Engine(model, **_ENGINE_KW) as eng:
            got = eng.submit(p, max_new_tokens=6).result(timeout=300)
        ref = model.generate(
            paddle.to_tensor(np.asarray([p], np.int64)),
            max_new_tokens=6, do_sample=False,
        )
        assert got == np.asarray(ref._data)[0].tolist()

    def test_eos_retires_early_and_is_respected(self, model):
        rng = np.random.RandomState(2)
        p = rng.randint(0, 211, (7,)).tolist()
        with Engine(model, **_ENGINE_KW) as eng:
            full = eng.submit(p, max_new_tokens=8).result(timeout=300)
            eos = full[len(p) + 2]  # third generated token
            out = eng.submit(p, max_new_tokens=8, eos_token_id=eos).result(
                timeout=300)
        # stops AT the eos token's FIRST occurrence, no tail beyond it
        first = full.index(eos, len(p))
        assert out == full[:first + 1]

    def test_sixty_four_concurrent_streams(self, model):
        """The load-shape acceptance floor: >= 64 in-flight streams through
        one engine, all correct prefixes, batch occupancy accounted."""
        rng = np.random.RandomState(3)
        prompts = _prompts(64, rng, lo=3, hi=16)
        with Engine(model, block_size=8, num_blocks=512, max_batch=64,
                    max_seq_len=128) as eng:
            handles = [eng.submit(p, max_new_tokens=6) for p in prompts]
            outs = [h.result(timeout=600) for h in handles]
            st = eng.stats()
        for p, out in zip(prompts, outs):
            assert out[:len(p)] == p and len(out) == len(p) + 6
        assert st["batch_occupancy_mean"] > 0.3
        assert st["pages_used"] == 0

    def test_streaming_and_cancel_midstream(self, model):
        rng = np.random.RandomState(4)
        with Engine(model, **_ENGINE_KW) as eng:
            h = eng.submit(rng.randint(0, 211, (5,)).tolist(),
                           max_new_tokens=100, stream=True)
            got = []
            for tok in h:  # ends cleanly when the cancel lands
                got.append(tok)
                if len(got) == 3:
                    h.cancel()
            assert 3 <= len(got) < 100
            with pytest.raises(RequestCancelled):
                h.result(timeout=60)
            deadline = time.monotonic() + 30
            while eng.stats()["pages_used"] and time.monotonic() < deadline:
                time.sleep(0.01)
            assert eng.stats()["pages_used"] == 0  # blocks came back
            # the engine is still healthy after the cancel
            p = rng.randint(0, 211, (4,)).tolist()
            out = eng.submit(p, max_new_tokens=3).result(timeout=300)
            assert out[:4] == p

    def test_compile_count_bounded_by_buckets_and_warm(self, model):
        rng = np.random.RandomState(5)
        # lengths spanning exactly two prefill buckets (<=8 and <=16)
        prompts = [rng.randint(0, 211, (L,)).tolist()
                   for L in (3, 5, 7, 9, 12, 15, 4, 11)]
        with Engine(model, **_ENGINE_KW) as eng:
            outs = [eng.submit(p, max_new_tokens=5) for p in prompts]
            [h.result(timeout=300) for h in outs]
            compiles = eng.stats()["compiles"]
            t_buckets = {8, 16}
            # decode buckets possibly touched: every width <= max_batch
            max_decode_buckets = len(eng.config.decode_buckets)
            assert compiles <= len(t_buckets) + max_decode_buckets
            # warm cache: a second identical wave must compile NOTHING new
            outs = [eng.submit(p, max_new_tokens=5) for p in prompts]
            [h.result(timeout=300) for h in outs]
            assert eng.stats()["compiles"] == compiles

    def test_submit_validation(self, model):
        with Engine(model, **_ENGINE_KW) as eng:
            with pytest.raises(ValueError, match="empty"):
                eng.submit([], max_new_tokens=4)
            with pytest.raises(ValueError, match="max_seq_len"):
                eng.submit([1] * 100, max_new_tokens=100)
            with pytest.raises(ValueError, match="max_new_tokens"):
                eng.submit([1, 2], max_new_tokens=0)
        with pytest.raises(ServeError):
            eng.submit([1, 2], max_new_tokens=2)  # closed engine

    def test_cancel_while_queued_unblocks_immediately(self, model):
        """A cancel must not wait for a batch slot: with the engine
        saturated by long streams, a queued request's cancel resolves at the
        next scheduler step, not when admission reaches it."""
        rng = np.random.RandomState(15)
        with Engine(model, block_size=8, num_blocks=64, max_batch=2,
                    max_seq_len=128) as eng:
            hogs = [eng.submit(rng.randint(0, 211, (4,)).tolist(),
                               max_new_tokens=100) for _ in range(2)]
            queued = eng.submit(rng.randint(0, 211, (4,)).tolist(),
                                max_new_tokens=100)
            queued.cancel()
            with pytest.raises(RequestCancelled):
                queued.result(timeout=30)  # well before any hog finishes
            [h.result(timeout=600) for h in hogs]

    def test_config_object_not_mutated_and_buckets_clamped(self, model):
        from paddle_tpu.serving import EngineConfig

        cfg = EngineConfig(block_size=8, num_blocks=64, max_batch=4,
                           max_seq_len=128, decode_buckets=(128,))
        with Engine(model, config=cfg) as eng:
            # oversized bucket clamped away; ceiling always present
            assert eng.config.decode_buckets == (4,)
            out = eng.submit([1, 2, 3], max_new_tokens=3).result(timeout=300)
            assert len(out) == 6
        # the caller's config object is untouched (reusable across engines)
        assert cfg.decode_buckets == (128,) and cfg.num_blocks == 64
        with pytest.raises(ValueError, match="not both"):
            Engine(model, config=cfg, block_size=16)


class TestPagedPool:
    def test_alloc_free_invariants(self):
        pool = PagePool(8)
        ids = pool.alloc(3)
        assert len(ids) == 3 and pool.used_blocks == 3
        assert 0 not in ids  # trash block never circulates
        assert pool.alloc(5) is None  # 4 free: backpressure, not partial
        pool.free(ids)
        assert pool.free_blocks == 7
        with pytest.raises(RuntimeError, match="double-free"):
            pool.free([ids[0]])
        pool.check()

    def test_oom_is_backpressure_then_completes(self, model):
        rng = np.random.RandomState(6)
        c0 = profiler.counters().get("serve_backpressure", 0)
        # 11 usable blocks of 8 = 88 cache slots; 6 requests of 16+24=40
        # slots each can never fit together → queueing + preemption
        with Engine(model, block_size=8, num_blocks=12, max_batch=8,
                    max_seq_len=88) as eng:
            hs = [eng.submit(rng.randint(0, 211, (16,)).tolist(),
                             max_new_tokens=24) for _ in range(6)]
            outs = [h.result(timeout=600) for h in hs]
            eng._pool.check()
            assert eng.stats()["pages_used"] == 0
        assert all(len(o) == 40 for o in outs)
        assert profiler.counters().get("serve_backpressure", 0) > c0

    def test_preempted_sequence_completes_full_length(self, model):
        """Eviction requeues accumulated state for re-prefill — the stream
        survives preemption end to end."""
        rng = np.random.RandomState(7)
        c0 = profiler.counters().get("serve_preempted", 0)
        with Engine(model, block_size=8, num_blocks=10, max_batch=4,
                    max_seq_len=72) as eng:
            hs = [eng.submit(rng.randint(0, 211, (8,)).tolist(),
                             max_new_tokens=24) for _ in range(4)]
            outs = [h.result(timeout=600) for h in hs]
        assert all(len(o) == 32 for o in outs)
        assert profiler.counters().get("serve_preempted", 0) >= c0


class TestInt8Serving:
    def test_int8_batched_bit_identical_to_sequential(self, model):
        rng = np.random.RandomState(8)
        prompts = _prompts(4, rng)
        kw = dict(_ENGINE_KW, int8=True)
        with Engine(model, **kw) as eng:
            batched = [h.result(timeout=300) for h in
                       [eng.submit(p, max_new_tokens=6) for p in prompts]]
        with Engine(model, **kw) as eng:
            sequential = [eng.submit(p, max_new_tokens=6).result(timeout=300)
                          for p in prompts]
        assert batched == sequential

    def test_int8_logits_within_ptq_tolerance(self, model):
        rng = np.random.RandomState(9)
        p = rng.randint(0, 211, (9,)).tolist()
        with Engine(model, **dict(_ENGINE_KW, int8=True)) as eng:
            l8 = eng._debug_prefill_logits(p)
        with Engine(model, **_ENGINE_KW) as eng:
            lf = eng._debug_prefill_logits(p)
        rel = float(np.abs(l8 - lf).max() / (np.abs(lf).max() + 1e-6))
        assert rel < 0.12, f"int8 serving drift {rel:.3f}"


class TestServingTelemetry:
    def test_spans_and_counters(self, model):
        rng = np.random.RandomState(10)
        c0 = profiler.counters()
        with profiler.Profiler() as prof:
            with Engine(model, **_ENGINE_KW) as eng:
                hs = [eng.submit(p, max_new_tokens=4)
                      for p in _prompts(3, rng)]
                [h.result(timeout=300) for h in hs]
            names = {s["name"] for s in profiler.span_events()}
        del prof
        assert {"schedule", "admit", "prefill", "decode_step"} <= names
        c1 = profiler.counters()
        for k in ("serve_requests", "serve_admitted", "serve_retired",
                  "serve_prefills", "serve_decode_steps", "serve_tokens",
                  "serve_compiles", "serve_pages_allocated",
                  "serve_pages_freed", "serve_occupancy_live",
                  "serve_occupancy_slots"):
            assert c1.get(k, 0) > c0.get(k, 0), k
        assert c1.get("serve_pages_allocated") is not None

    def test_flight_context_provider_carries_request_table(self, model):
        from paddle_tpu.profiler import flight

        rng = np.random.RandomState(11)
        with Engine(model, **_ENGINE_KW) as eng:
            h = eng.submit(rng.randint(0, 211, (5,)).tolist(),
                           max_new_tokens=64)
            path = flight.dump("serving_test_probe")
            h.result(timeout=300)
        assert path is not None
        import json

        doc = json.load(open(path))
        serving = [v for k, v in doc["context"].items()
                   if k.startswith("serving_")]
        assert serving, "no serving context provider in the dump"
        assert "queue_depth" in serving[0] and "pages" in serving[0]
        # provider unregistered at close: a fresh dump carries no live table
        path2 = flight.dump("serving_test_probe2")
        doc2 = json.load(open(path2))
        assert all(not k.startswith(f"serving_{eng._provider}")
                   for k in doc2["context"])


class TestLlamaServing:
    def test_llama_paged_matches_sequential_and_generate(self):
        from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny

        paddle.seed(0)
        cfg = llama_tiny(num_kv_heads=2)  # GQA through the paged read
        m = LlamaForCausalLM(cfg)
        m.eval()
        rng = np.random.RandomState(12)
        prompts = [rng.randint(0, cfg.vocab_size, (L,)).tolist()
                   for L in (4, 9, 6)]
        kw = dict(block_size=8, num_blocks=64, max_batch=4,
                  max_seq_len=min(64, cfg.max_position_embeddings))
        with Engine(m, **kw) as eng:
            batched = [h.result(timeout=300) for h in
                       [eng.submit(p, max_new_tokens=4) for p in prompts]]
        with Engine(m, **kw) as eng:
            sequential = [eng.submit(p, max_new_tokens=4).result(timeout=300)
                          for p in prompts]
        assert batched == sequential
        from paddle_tpu.models.generation import generate_llama

        ref = generate_llama(
            m, paddle.to_tensor(np.asarray([prompts[1]], np.int64)),
            max_new_tokens=4, do_sample=False,
        )
        assert batched[1] == np.asarray(ref._data)[0].tolist()


class TestGenerateEosSatellite:
    """models/generation.py satellite: per-sequence EOS handling in batched
    decode — frozen finished rows, eos-padded tails, early loop exit —
    pinned bit-for-bit against single-sequence decode."""

    def _model(self):
        return _tiny_gpt(seed=3)

    def test_batched_rows_bitwise_equal_single_sequence(self):
        from paddle_tpu.models import generation as G

        m = self._model()
        rng = np.random.RandomState(13)
        prompt = rng.randint(0, 211, (3, 6))
        # an eos one row actually emits, so the batch mixes finished+live
        probe = m.generate(paddle.to_tensor(prompt[:1]), max_new_tokens=6,
                           do_sample=False)
        eos = int(np.asarray(probe._data)[0, 8])
        batched = m.generate(paddle.to_tensor(prompt), max_new_tokens=6,
                             do_sample=False, eos_token_id=eos)
        for r in range(3):
            single = m.generate(paddle.to_tensor(prompt[r:r + 1]),
                                max_new_tokens=6, do_sample=False,
                                eos_token_id=eos)
            np.testing.assert_array_equal(
                np.asarray(batched._data)[r], np.asarray(single._data)[0],
            )
        assert G.last_decode_steps() <= 6

    def test_early_exit_stops_burning_steps(self):
        from paddle_tpu.models import generation as G

        m = self._model()
        rng = np.random.RandomState(14)
        prompt = paddle.to_tensor(rng.randint(0, 211, (1, 6)))
        probe = m.generate(prompt, max_new_tokens=40, do_sample=False)
        first = int(np.asarray(probe._data)[0, 6])
        assert G.last_decode_steps() == 40  # no eos: full budget
        out = m.generate(prompt, max_new_tokens=40, do_sample=False,
                         eos_token_id=first)
        # the very first generated token is eos → ONE step, not 40
        assert G.last_decode_steps() == 1
        row = np.asarray(out._data)[0]
        assert (row[6:] == first).all()  # tail is eos-padded, never garbage
