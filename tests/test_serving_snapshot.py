"""Serving state durability — snapshot/restore, crash re-attach, handoff.

Pins the ISSUE-17 acceptance surface: ``PagePool.snapshot()/restore()`` is
a validated O(blocks) capture (CRC torn-detection + the conservation
``check()`` — a tampered capture is a structured ``SnapshotError``, never a
wrong pool); a supervised crash with ``snapshot=True`` RE-ATTACHES the
survivors' live KV blocks so they resume mid-decode with ZERO re-prefilled
tokens, bit-identical to an uninterrupted run (GPT and Llama/GQA, prefix
cache armed and not); a torn/corrupt capture (``serve.snapshot_corrupt``)
falls back whole to the PR 12 re-prefill path with the same bit-identity;
``Engine.handoff()`` quiesces at a step boundary and a successor adopts
queue + in-flight handles with zero downtime; and the whole layer is INERT
when unconfigured — snapshot/restore/adopt monkeypatch-exploded and never
called on the default path. Chaos-grade multi-round drives live in
tests/test_serving_chaos.py.
"""
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import profiler
from paddle_tpu.fault import inject
from paddle_tpu.serving import (
    Engine, PagePool, ServeError, ServingSupervisor, SnapshotError,
    TRASH_BLOCK,
)
from serving_util import ENGINE_KW, make_prompts as _prompts, tiny_gpt

_KW = dict(ENGINE_KW)


@pytest.fixture(scope="module")
def model():
    return tiny_gpt()


@pytest.fixture(autouse=True)
def _disarm():
    yield
    inject.disarm()


def _delta(c0, name):
    return profiler.counters().get(name, 0) - c0.get(name, 0)


# ---------------------------------------------------------------- pool unit
class TestPoolSnapshot:
    def _busy_pool(self):
        pool = PagePool(16)
        a = pool.alloc(3)
        b = pool.alloc(2)
        pool.share(b)          # refcount 2: a shared prefix block pattern
        pool.park(4)
        return pool, a, b

    def test_roundtrip_preserves_every_field(self):
        pool, a, b = self._busy_pool()
        snap = pool.snapshot()
        clone = PagePool.restore(snap)
        clone.check()
        assert clone.num_blocks == pool.num_blocks
        assert clone.free_blocks == pool.free_blocks
        assert clone.parked_blocks == pool.parked_blocks
        for bid in a:
            assert clone.refcount(bid) == 1
        for bid in b:
            assert clone.refcount(bid) == 2
        # the clone is live: the shared blocks need BOTH frees
        clone.free(b)
        for bid in b:
            assert clone.refcount(bid) == 1

    def test_snapshot_is_a_capture_not_a_view(self):
        pool, a, _b = self._busy_pool()
        snap = pool.snapshot()
        pool.free(a)  # mutate the source after the capture
        clone = PagePool.restore(snap)
        for bid in a:
            assert clone.refcount(bid) == 1  # capture kept the old truth

    def test_torn_capture_rejected_by_crc(self):
        pool, _a, _b = self._busy_pool()
        snap = pool.snapshot()
        snap["free"].pop()  # tear: a field mutated after the CRC was taken
        with pytest.raises(SnapshotError, match="torn"):
            PagePool.restore(snap)

    def test_consistent_tamper_rejected_by_conservation(self):
        """A tamper that RECOMPUTES the CRC still cannot pass: the restored
        pool must satisfy the conservation check()."""
        from paddle_tpu.serving.pool import _pool_crc

        pool, a, _b = self._busy_pool()
        snap = pool.snapshot()
        snap["free"].append(a[0])  # block now both free and owned
        snap["crc"] = _pool_crc(snap["num_blocks"], snap["free"],
                                snap["ref"], snap["parked"])
        with pytest.raises(SnapshotError):
            PagePool.restore(snap)

    def test_zero_refcount_and_bad_ids_rejected(self):
        from paddle_tpu.serving.pool import _pool_crc

        pool, a, _b = self._busy_pool()
        for mutate in (
            lambda s: s["ref"].__setitem__(a[0], 0),
            lambda s: s["ref"].__setitem__(TRASH_BLOCK, 1),
            lambda s: s["ref"].__setitem__(s["num_blocks"] + 3, 1),
        ):
            snap = pool.snapshot()
            mutate(snap)
            snap["crc"] = _pool_crc(snap["num_blocks"], snap["free"],
                                    snap["ref"], snap["parked"])
            with pytest.raises(SnapshotError):
                PagePool.restore(snap)

    def test_version_and_malformed_rejected(self):
        pool, _a, _b = self._busy_pool()
        snap = pool.snapshot()
        bad = dict(snap, version=99)
        with pytest.raises(SnapshotError, match="version"):
            PagePool.restore(bad)
        with pytest.raises(SnapshotError, match="malformed"):
            PagePool.restore({"version": snap["version"], "free": object()})


# ------------------------------------------------------- crash → re-attach
class TestCrashReattach:
    def test_reattach_zero_reprefill_bit_identical(self, model):
        """THE acceptance pin: supervised crash mid-decode with snapshot
        armed — every survivor RE-ATTACHES its live KV blocks (zero tokens
        re-prefilled, zero requeues) and every greedy stream completes
        bit-identical to an uninterrupted run."""
        rng = np.random.RandomState(20)
        prompts = _prompts(6, rng)
        with Engine(model, **_KW) as eng:
            baseline = [eng.submit(p, max_new_tokens=10).result(timeout=300)
                        for p in prompts]
        c0 = dict(profiler.counters())
        inject.arm("serve.crash:at=4")
        with ServingSupervisor(model, watchdog_s=4.0, snapshot=True,
                               **_KW) as sup:
            hs = [sup.submit(p, max_new_tokens=10) for p in prompts]
            outs = [h.result(timeout=600) for h in hs]
            assert sup.restarts == 1
            last = sup.health()["last_recovery"]
            assert last["mode"] == "reattach"
            assert last["reattached"] == len(prompts)
            assert last["blocks_reattached"] > 0
            assert last["requeued"] == 0
            assert last["duration_s"] > 0.0
            assert sup.health()["ok"] and sup.ready()
            assert sup.stats()["pages_used"] == 0  # restored pool drained
        assert outs == baseline
        assert _delta(c0, "serve_reprefill_tokens") == 0, \
            "re-attach must re-prefill ZERO tokens"
        assert _delta(c0, "serve_requeued") == 0
        assert _delta(c0, "serve_reattached") == len(prompts)
        assert _delta(c0, "serve_reattached_blocks") > 0
        assert _delta(c0, "serve_reprefill_tokens_saved") > 0
        assert _delta(c0, "serve_snapshots") == 1
        assert _delta(c0, "serve_pool_restores") >= 1
        assert _delta(c0, "serve_restart_mttr_ms") > 0

    def test_reattach_llama_gqa_bit_identical(self):
        """Same pin over the Llama/GQA paged path — grouped KV heads change
        the pool geometry and the decode program, not the durability
        contract."""
        from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM

        paddle.seed(3)
        cfg = LlamaConfig(vocab_size=193, hidden_size=32, num_layers=2,
                          num_heads=4, num_kv_heads=2, intermediate_size=64,
                          max_position_embeddings=128)
        m = LlamaForCausalLM(cfg)
        m.eval()
        rng = np.random.RandomState(21)
        prompts = [rng.randint(0, 193, (int(rng.randint(3, 20)),)).tolist()
                   for _ in range(6)]
        kw = dict(block_size=8, num_blocks=64, max_batch=8, max_seq_len=128)
        with Engine(m, **kw) as eng:
            baseline = [eng.submit(p, max_new_tokens=10).result(timeout=600)
                        for p in prompts]
        c0 = dict(profiler.counters())
        inject.arm("serve.crash:at=4")
        with ServingSupervisor(m, watchdog_s=4.0, snapshot=True,
                               **kw) as sup:
            hs = [sup.submit(p, max_new_tokens=10) for p in prompts]
            outs = [h.result(timeout=600) for h in hs]
            assert sup.restarts == 1
            assert sup.health()["last_recovery"]["mode"] == "reattach"
        assert outs == baseline
        assert _delta(c0, "serve_reprefill_tokens") == 0
        assert _delta(c0, "serve_reattached_blocks") > 0

    def test_reattach_with_prefix_cache_armed(self, model):
        """Crash while streams share cached prefix blocks: the restored
        pool carries the index's own references, CoW guards, and LRU order
        — conservation holds post-restore (pages_used == pages_cached once
        drained) and the successor still serves cache hits."""
        rng = np.random.RandomState(22)
        shared = rng.randint(0, 211, (40,)).tolist()
        prompts = [shared + rng.randint(0, 211,
                                        (int(rng.randint(3, 10)),)).tolist()
                   for _ in range(8)]
        kw = dict(_KW, num_blocks=128)
        with Engine(model, **kw) as eng:
            baseline = [eng.submit(p, max_new_tokens=10).result(timeout=600)
                        for p in prompts]
        c0 = dict(profiler.counters())
        inject.arm("serve.crash:at=5")
        with ServingSupervisor(model, watchdog_s=4.0, snapshot=True,
                               prefix_cache=True, **kw) as sup:
            hs = [sup.submit(p, max_new_tokens=10) for p in prompts]
            outs = [h.result(timeout=600) for h in hs]
            assert sup.restarts == 1
            assert sup.health()["last_recovery"]["mode"] == "reattach"
            # restored index holds its own refs; nothing else is resident
            st = sup.stats()
            assert st["pages_used"] == st["pages_cached"] > 0
            with sup._lock:
                sup._engine._pool.check()  # conservation post-restore
            # the restored chain still SERVES: a fresh wave hits the cache
            h0 = profiler.counters().get("serve_prefix_hits", 0)
            hs2 = [sup.submit(p, max_new_tokens=10) for p in prompts]
            outs2 = [h.result(timeout=600) for h in hs2]
            assert profiler.counters().get("serve_prefix_hits", 0) > h0
        assert outs == baseline and outs2 == baseline
        assert _delta(c0, "serve_reprefill_tokens") == 0

    def test_corrupt_snapshot_falls_back_whole_bit_identical(self, model):
        """serve.snapshot_corrupt tears the capture mid-write: adopt's
        validation rejects it (SnapshotError, serve_snapshot_rejected) and
        the supervisor falls back WHOLE to the PR 12 requeue/re-prefill
        path — same bit-identity, nothing half-adopted."""
        rng = np.random.RandomState(23)
        prompts = _prompts(6, rng)
        with Engine(model, **_KW) as eng:
            baseline = [eng.submit(p, max_new_tokens=10).result(timeout=300)
                        for p in prompts]
        c0 = dict(profiler.counters())
        inject.arm("serve.crash:at=4;serve.snapshot_corrupt")
        with ServingSupervisor(model, watchdog_s=4.0, snapshot=True,
                               **_KW) as sup:
            hs = [sup.submit(p, max_new_tokens=10) for p in prompts]
            outs = [h.result(timeout=600) for h in hs]
            assert sup.restarts == 1
            last = sup.health()["last_recovery"]
            assert last["mode"] == "reprefill"
            assert last["requeued"] == len(prompts)
            assert last["blocks_reattached"] == 0
        assert outs == baseline
        assert _delta(c0, "serve_snapshot_rejected") == 1
        assert _delta(c0, "serve_requeued") == len(prompts)
        assert _delta(c0, "serve_reattached_blocks") == 0
        assert _delta(c0, "serve_reprefill_tokens") > 0

    def test_mixed_running_and_queued_all_complete(self, model):
        """max_batch smaller than the load: at crash time some requests are
        mid-decode (re-attached) and some still queued (requeued fresh by
        the harvest). Every stream completes bit-identical either way."""
        rng = np.random.RandomState(24)
        prompts = _prompts(6, rng)
        kw = dict(_KW, max_batch=2)
        with Engine(model, **kw) as eng:
            baseline = [eng.submit(p, max_new_tokens=10).result(timeout=300)
                        for p in prompts]
        c0 = dict(profiler.counters())
        inject.arm("serve.crash:at=4")
        with ServingSupervisor(model, watchdog_s=4.0, snapshot=True,
                               **kw) as sup:
            hs = [sup.submit(p, max_new_tokens=10) for p in prompts]
            outs = [h.result(timeout=600) for h in hs]
            assert sup.restarts == 1
            last = sup.health()["last_recovery"]
            assert last["mode"] == "reattach"
            assert last["reattached"] + last["requeued"] == len(prompts)
            assert last["reattached"] > 0 and last["requeued"] > 0
        assert outs == baseline
        # queued requests had no prefill yet — still zero re-prefill
        assert _delta(c0, "serve_reprefill_tokens") == 0

    def test_streamed_request_reattaches_contiguously(self, model):
        """A streamed survivor keeps its ORIGINAL handle across the
        re-attach — no relay, no gap, the stream equals the uninterrupted
        generation."""
        rng = np.random.RandomState(25)
        p = rng.randint(0, 211, (6,)).tolist()
        with Engine(model, **_KW) as eng:
            ref = eng.submit(p, max_new_tokens=10).result(timeout=300)
        c0 = dict(profiler.counters())
        inject.arm("serve.crash:at=5")
        with ServingSupervisor(model, watchdog_s=4.0, snapshot=True,
                               **_KW) as sup:
            h = sup.submit(p, max_new_tokens=10, stream=True)
            got = list(h)
            assert sup.restarts == 1
        assert p + got == ref
        assert _delta(c0, "serve_relayed") == 0  # original handle, no relay
        assert _delta(c0, "serve_reprefill_tokens") == 0

    def test_wedge_never_snapshots(self, model):
        """Snapshot is CRASH-only: a wedged scheduler thread may still be
        mutating state, so the supervisor must not capture it — the wedge
        path keeps its PR 12 semantics (structural failure + requeue)."""
        rng = np.random.RandomState(26)
        c0 = dict(profiler.counters())
        with ServingSupervisor(model, watchdog_s=3.0, snapshot=True,
                               **_KW) as sup:
            sup.generate(rng.randint(0, 211, (5,)).tolist(), max_new_tokens=3)
            inject.arm("serve.wedge:at=2,ms=60000")
            h = sup.submit(rng.randint(0, 211, (5,)).tolist(),
                           max_new_tokens=50)
            with pytest.raises(ServeError, match="wedged"):
                h.result(timeout=30)
            inject.disarm()
            assert sup.restarts == 1
            assert sup.health()["last_recovery"]["mode"] != "reattach"
            assert len(sup.generate(rng.randint(0, 211, (4,)).tolist(),
                                    max_new_tokens=3)) == 7
        assert _delta(c0, "serve_snapshots") == 0


# ----------------------------------------------------------------- handoff
class TestHandoff:
    def test_handoff_mid_decode_bit_identical(self, model):
        """Zero-downtime handoff: quiesce at a step boundary, successor
        adopts snapshot + handles, survivors resume mid-decode on their
        ORIGINAL handles with zero re-prefill, outputs bit-identical."""
        rng = np.random.RandomState(30)
        prompts = _prompts(6, rng)
        with Engine(model, **_KW) as eng:
            baseline = [eng.submit(p, max_new_tokens=10).result(timeout=300)
                        for p in prompts]
        c0 = dict(profiler.counters())
        old = Engine(model, **_KW)
        try:
            hs = [old.submit(p, max_new_tokens=10) for p in prompts]
            # let decode get going so the handoff is genuinely mid-flight
            deadline = time.monotonic() + 30
            while old.stats()["decode_steps"] < 2 \
                    and time.monotonic() < deadline:
                time.sleep(0.005)
            snap = old.handoff()
            with pytest.raises(ServeError):
                old.submit([1, 2], max_new_tokens=2)  # terminally stopped
            with Engine(model, **_KW) as new:
                info = new.adopt(snap)
                assert info["mode"] == "reattach"
                assert info["reattached"] > 0
                assert info["reprefill_tokens"] == 0
                outs = [h.result(timeout=600) for h in hs]
                assert new.health()["last_recovery"]["mode"] == "reattach"
                assert new.stats()["pages_used"] == 0
        finally:
            old.close()
        assert outs == baseline
        assert _delta(c0, "serve_handoffs") == 1
        assert _delta(c0, "serve_adoptions") == 1
        assert _delta(c0, "serve_reprefill_tokens") == 0

    def test_handoff_transfers_queue(self, model):
        """Queued-but-unadmitted requests ride the handoff too: the
        successor admits them from the adopted queue."""
        rng = np.random.RandomState(31)
        prompts = _prompts(4, rng)
        kw = dict(_KW, max_batch=1)
        with Engine(model, **kw) as eng:
            baseline = [eng.submit(p, max_new_tokens=8).result(timeout=300)
                        for p in prompts]
        old = Engine(model, **kw)
        try:
            hs = [old.submit(p, max_new_tokens=8) for p in prompts]
            deadline = time.monotonic() + 30
            while old.stats()["decode_steps"] < 2 \
                    and time.monotonic() < deadline:
                time.sleep(0.005)
            snap = old.handoff()
            assert snap["queue"], "nothing was queued at handoff time"
            with Engine(model, **kw) as new:
                info = new.adopt(snap)
                assert info["queued"] == len(snap["queue"])
                outs = [h.result(timeout=600) for h in hs]
        finally:
            old.close()
        assert outs == baseline

    def test_handoff_prefix_chain_survives(self, model):
        """A prefix-cache-armed handoff carries the chain: the successor's
        index serves hits immediately, and conservation holds."""
        rng = np.random.RandomState(32)
        shared = rng.randint(0, 211, (40,)).tolist()
        prompts = [shared + rng.randint(0, 211, (5,)).tolist()
                   for _ in range(6)]
        kw = dict(_KW, num_blocks=128)
        with Engine(model, **kw) as eng:
            baseline = [eng.submit(p, max_new_tokens=8).result(timeout=600)
                        for p in prompts]
        old = Engine(model, prefix_cache=True, **kw)
        try:
            first = [old.submit(p, max_new_tokens=8) for p in prompts]
            outs1 = [h.result(timeout=600) for h in first]
            snap = old.handoff()
            with Engine(model, prefix_cache=True, **kw) as new:
                new.adopt(snap)
                h0 = profiler.counters().get("serve_prefix_hits", 0)
                hs = [new.submit(p, max_new_tokens=8) for p in prompts]
                outs2 = [h.result(timeout=600) for h in hs]
                assert profiler.counters().get("serve_prefix_hits", 0) > h0
                st = new.stats()
                assert st["pages_used"] == st["pages_cached"] > 0
                new._pool.check()
        finally:
            old.close()
        assert outs1 == baseline and outs2 == baseline

    def test_handoff_to_unarmed_successor_releases_index(self, model):
        """Prefix-armed predecessor, cache-OFF successor: the adopted
        chain's index references are RELEASED (not leaked) — conservation
        holds with pages_cached == 0."""
        rng = np.random.RandomState(33)
        shared = rng.randint(0, 211, (24,)).tolist()
        prompts = [shared + rng.randint(0, 211, (4,)).tolist()
                   for _ in range(4)]
        kw = dict(_KW, num_blocks=128)
        with Engine(model, **kw) as eng:
            baseline = [eng.submit(p, max_new_tokens=6).result(timeout=600)
                        for p in prompts]
        old = Engine(model, prefix_cache=True, **kw)
        try:
            [old.submit(p, max_new_tokens=6).result(timeout=600)
             for p in prompts]
            snap = old.handoff()
            with Engine(model, **kw) as new:  # cache off
                new.adopt(snap)
                outs = [new.submit(p, max_new_tokens=6).result(timeout=600)
                        for p in prompts]
                st = new.stats()
                assert st["pages_cached"] == 0 and st["pages_used"] == 0
                new._pool.check()
        finally:
            old.close()
        assert outs == baseline

    def test_handoff_crash_before_quiesce_fails_whole(self, model):
        """The engine dies before the quiesce lands: handoff() raises
        ServeError, the crash path owns the handles (structural failure,
        never a hang), and a separately-built successor is untouched."""
        rng = np.random.RandomState(34)
        old = Engine(model, **_KW)
        try:
            inject.arm("serve.crash:at=2")
            h = old.submit(rng.randint(0, 211, (5,)).tolist(),
                           max_new_tokens=50)
            deadline = time.monotonic() + 30
            while not inject.fired_counts().get("serve.crash") \
                    and time.monotonic() < deadline:
                time.sleep(0.005)
            with pytest.raises(ServeError):
                old.handoff(timeout=10.0)
            inject.disarm()
            with pytest.raises(ServeError):
                h.result(timeout=10)  # failed structurally, not stranded
            with Engine(model, **_KW) as new:
                out = new.submit(rng.randint(0, 211, (4,)).tolist(),
                                 max_new_tokens=3).result(timeout=300)
                assert len(out) == 7
        finally:
            old.close()

    def test_handoff_corrupt_snapshot_reprefill_fallback(self, model):
        """serve.snapshot_corrupt during the handoff capture: adopt's
        default fallback re-prefills every survivor whole — the handoff
        still completes bit-identical, just without the re-attach win."""
        rng = np.random.RandomState(35)
        prompts = _prompts(4, rng)
        with Engine(model, **_KW) as eng:
            baseline = [eng.submit(p, max_new_tokens=8).result(timeout=300)
                        for p in prompts]
        c0 = dict(profiler.counters())
        old = Engine(model, **_KW)
        try:
            hs = [old.submit(p, max_new_tokens=8) for p in prompts]
            deadline = time.monotonic() + 30
            while old.stats()["decode_steps"] < 2 \
                    and time.monotonic() < deadline:
                time.sleep(0.005)
            inject.arm("serve.snapshot_corrupt")
            snap = old.handoff()
            inject.disarm()
            with Engine(model, **_KW) as new:
                info = new.adopt(snap)
                assert info["mode"] == "reprefill"
                assert "reject_reason" in info
                outs = [h.result(timeout=600) for h in hs]
                assert new.health()["last_recovery"]["mode"] == "reprefill"
        finally:
            old.close()
        assert outs == baseline
        assert _delta(c0, "serve_snapshot_rejected") == 1

    def test_kv_content_tamper_rejected(self, model):
        """Never a wrong-KV serve: a snapshot whose KV bytes diverge from
        the captured fingerprints is rejected outright with
        fallback='raise', and falls back whole by default."""
        import jax.numpy as jnp

        rng = np.random.RandomState(36)
        prompts = _prompts(3, rng)
        with Engine(model, **_KW) as eng:
            baseline = [eng.submit(p, max_new_tokens=8).result(timeout=300)
                        for p in prompts]
        old = Engine(model, **_KW)
        try:
            hs = [old.submit(p, max_new_tokens=8) for p in prompts]
            deadline = time.monotonic() + 30
            while old.stats()["decode_steps"] < 2 \
                    and time.monotonic() < deadline:
                time.sleep(0.005)
            snap = old.handoff()
            snap["kpool"] = jnp.zeros_like(snap["kpool"])  # wrong KV bytes
            with Engine(model, **_KW) as new:
                with pytest.raises(SnapshotError, match="fingerprint"):
                    new.adopt(snap, fallback="raise")
                info = new.adopt(snap)  # default: whole-state re-prefill
                assert info["mode"] == "reprefill"
                outs = [h.result(timeout=600) for h in hs]
        finally:
            old.close()
        assert outs == baseline

    def test_adopt_refuses_geometry_mismatch_and_traffic(self, model):
        """Cross-config adoption is refused (compat key), and adopt into an
        engine that already served traffic is a hard error — never a merge
        of two pools."""
        rng = np.random.RandomState(37)
        old = Engine(model, **_KW)
        try:
            old.submit(rng.randint(0, 211, (5,)).tolist(),
                       max_new_tokens=4).result(timeout=300)
            snap = old.handoff()
            with Engine(model, **dict(_KW, num_blocks=32)) as other:
                with pytest.raises(SnapshotError, match="geometry"):
                    other.adopt(snap, fallback="raise")
            with Engine(model, **_KW) as busy:
                busy.submit(rng.randint(0, 211, (4,)).tolist(),
                            max_new_tokens=2).result(timeout=300)
                with pytest.raises(ServeError, match="fresh"):
                    busy.adopt(snap)
        finally:
            old.close()


# ------------------------------------------------------------ inert tripwire
class TestInertTripwire:
    def test_unconfigured_path_never_touches_durability(self, model,
                                                        monkeypatch):
        """With FLAGS_serve_snapshot off (the default) the durability layer
        must cost NOTHING: snapshot/restore/adopt are monkeypatch-exploded
        and a full supervised crash recovery (the PR 12 path) plus plain
        traffic never call them — byte-identical behaviour, zero per-step
        overhead."""
        import paddle_tpu.serving.engine as E
        import paddle_tpu.serving.pool as P

        def boom(*a, **k):
            raise AssertionError(
                "durability machinery ran on the unconfigured path")

        monkeypatch.setattr(P.PagePool, "snapshot", boom)
        monkeypatch.setattr(P.PagePool, "restore", boom)
        monkeypatch.setattr(E.Engine, "snapshot", boom)
        monkeypatch.setattr(E.Engine, "adopt", boom)
        monkeypatch.setattr(E.Engine, "handoff", boom)
        rng = np.random.RandomState(40)
        prompts = _prompts(4, rng)
        with Engine(model, **_KW) as eng:
            baseline = [eng.submit(p, max_new_tokens=8).result(timeout=300)
                        for p in prompts]
        inject.arm("serve.crash:at=3")
        with ServingSupervisor(model, watchdog_s=4.0, **_KW) as sup:
            hs = [sup.submit(p, max_new_tokens=8) for p in prompts]
            outs = [h.result(timeout=600) for h in hs]
            assert sup.restarts == 1
            assert sup.health()["last_recovery"]["mode"] == "reprefill"
        assert outs == baseline
