"""Generated-op sweep — the OpTest battery over the yaml op table.

Methodology per reference ``unittests/op_test.py:282``: every registered
generated op gets (1) an fp32 forward execution with finite outputs, (2) a
bf16 forward smoke for float ops, (3) a central finite-difference gradient
check against the autograd tape for differentiable ops. Op-specific input
domains/shapes come from the spec metadata (ops.yaml), so newly added yaml
entries are tested automatically.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.ops.generated import GENERATED, SPECS

_SHAPES = {"sq": (4, 4), "vec": (6,), None: (2, 3)}


def _shape_of(val):
    if val is None:
        return _SHAPES[None]
    if isinstance(val, str):
        return _SHAPES.get(val, _SHAPES[None])
    return tuple(val)


def _sample(domain, shape, rng):
    if domain == "pos":
        return (rng.rand(*shape) + 0.5).astype(np.float32)
    if domain == "unit":
        return (rng.rand(*shape) * 0.8 + 0.1).astype(np.float32)
    if domain == "smallint":
        return rng.randint(0, 3, shape).astype(np.int32)
    if domain == "index":
        return rng.randint(0, 2, shape).astype(np.int32)
    return rng.randn(*shape).astype(np.float32)


# bespoke inputs where the generic sampler can't satisfy op preconditions
def _custom_inputs(name, rng):
    if name == "bucketize":
        return [rng.randn(2, 3).astype(np.float32), np.sort(rng.randn(5).astype(np.float32))]
    if name == "isin":
        return [rng.randint(0, 4, (2, 3)).astype(np.int32), rng.randint(0, 4, (4,)).astype(np.int32)]
    if name == "argwhere":
        return [(rng.rand(2, 3) > 0.5).astype(np.float32)]
    if name == "matrix_exp":
        return [(rng.randn(3, 3) * 0.1).astype(np.float32)]
    if name in ("matrix_norm", "lu_unpack"):
        return [rng.randn(3, 3).astype(np.float32)]
    return None


def _inputs_for(spec, rng):
    custom = _custom_inputs(spec["name"], rng)
    if custom is not None:
        return custom
    args = spec.get("args", ["x"])
    if spec.get("variadic"):
        sh = _shape_of(spec.get("shape"))
        return [_sample(spec.get("domain"), sh, rng) for _ in range(2)]
    inputs = []
    for i in range(len(args)):
        sh = _shape_of(spec.get("shape" if i == 0 else f"shape{i + 1}", spec.get("shape")))
        dom = spec.get("domain" if i == 0 else f"domain{i + 1}", spec.get("domain"))
        inputs.append(_sample(dom, sh, rng))
    return inputs


def _runnable_specs():
    out = []
    for name, spec in sorted(SPECS.items()):
        if spec.get("skip_test") or spec.get("alias_of"):
            continue
        out.append(name)
    return out


@pytest.mark.parametrize("name", _runnable_specs())
def test_forward_fp32(name):
    spec = SPECS[name]
    rng = np.random.RandomState(7)
    inputs = _inputs_for(spec, rng)
    op = GENERATED[name]
    if spec.get("variadic"):
        out = op(inputs)
    else:
        out = op(*[paddle.to_tensor(a) for a in inputs])
    outs = out if isinstance(out, (list, tuple)) else [out]
    for o in outs:
        a = np.asarray(o.numpy())
        if np.issubdtype(a.dtype, np.floating):
            assert np.isfinite(a).all(), f"{name} produced non-finite fp32 output"


@pytest.mark.parametrize(
    "name",
    [n for n in _runnable_specs()
     if SPECS[n].get("grad", True) and SPECS[n].get("bf16", True)
     and not SPECS[n].get("variadic")
     and SPECS[n].get("args", ["x"]) and SPECS[n].get("domain") not in ("smallint", "index")],
)
def test_forward_bf16(name):
    """Float ops must run in bf16 (the MXU-native dtype)."""
    import jax.numpy as jnp

    spec = SPECS[name]
    rng = np.random.RandomState(8)
    inputs = _inputs_for(spec, rng)
    if any(np.issubdtype(np.asarray(a).dtype, np.integer) for a in inputs):
        pytest.skip("integer-input op")
    tensors = [paddle.to_tensor(a).astype("bfloat16") for a in inputs]
    out = GENERATED[name](*tensors)
    outs = out if isinstance(out, (list, tuple)) else [out]
    assert all(o.numpy() is not None for o in outs)


_GRAD_EXCLUDE = {
    # piecewise-constant or argsort-coupled outputs: analytic grad is 0/ok but
    # finite differences step across discontinuities
    "fix", "msort", "unwrap", "renorm", "nanmedian", "nanquantile", "diff",
}


@pytest.mark.parametrize(
    "name",
    [n for n in _runnable_specs()
     if SPECS[n].get("grad", True) and not SPECS[n].get("variadic")
     and SPECS[n].get("args", ["x"]) and n not in _GRAD_EXCLUDE
     and SPECS[n].get("domain") not in ("smallint", "index")
     and not any(SPECS[n].get(f"domain{i}") in ("smallint", "index") for i in (2, 3))],
)
def test_grad_check(name):
    """Central finite difference vs the autograd tape (op_test.check_grad)."""
    from op_test import check_grad

    spec = SPECS[name]
    rng = np.random.RandomState(9)
    inputs = _inputs_for(spec, rng)
    n_tensor = len(spec.get("args", ["x"]))
    out_index = 0 if spec.get("n_outs") in (2, "list") else None
    nondiff = set(spec.get("nondiff", ()))
    if out_index == 0 and 0 in nondiff:
        pytest.skip("first output non-differentiable")
    check_grad(
        GENERATED[name], inputs[:n_tensor],
        grad_inputs=[i for i in range(n_tensor)
                     if not np.issubdtype(np.asarray(inputs[i]).dtype, np.integer)],
        out_index=out_index, atol=5e-2, rtol=5e-2,
    )


def test_registry_count():
    """SURVEY §2.2 coverage gate: the registered forward-op surface keeps
    growing toward the reference's (913 registrations incl. grad kernels;
    grads are implicit here)."""
    from paddle_tpu.ops.registry import op_count

    assert op_count() >= 500, op_count()


# ---------------------------------------------------------------------------
# Value checks: every yaml op with a `ref` numpy expression is compared
# AGAINST that independent implementation (reference OpTest.check_output
# semantics, unittests/op_test.py:282) — a typo'd jnp expr now FAILS instead
# of passing a finiteness scan.
# ---------------------------------------------------------------------------
import scipy.integrate as scipy_integrate
import scipy.linalg as scipy_linalg
import scipy.special as scipy_special


def np_index_update(x, index, src, axis):
    out = np.array(x)
    sl = [slice(None)] * out.ndim
    sl[axis] = index[0]
    out[tuple(sl)] = src
    return out


def np_slice_update(x, src, start, axis):
    out = np.array(x)
    sl = [slice(None)] * out.ndim
    sl[axis] = slice(start, start + src.shape[axis])
    out[tuple(sl)] = src
    return out


def np_fill_rows(x, idx, value):
    out = np.array(x)
    out[idx] = value
    return out


def np_diag_embed(x):
    out = np.zeros(x.shape + (x.shape[-1],), x.dtype)
    r = np.arange(x.shape[-1])
    out[..., r, r] = x
    return out


def np_fill_diagonal(x, value):
    out = np.array(x)
    n = min(out.shape[-2], out.shape[-1])
    out[..., np.arange(n), np.arange(n)] = value
    return out


def np_padded_argwhere(x):
    idx = np.argwhere(x)
    pad = x.size - idx.shape[0]
    if pad > 0:
        idx = np.concatenate([idx, np.full((pad, idx.shape[1]), -1, idx.dtype)], 0)
    return idx


_REF_ENV = {
    "np": np,
    "scipy_special": scipy_special,
    "scipy_linalg": scipy_linalg,
    "scipy_integrate": scipy_integrate,
    "np_index_update": np_index_update,
    "np_slice_update": np_slice_update,
    "np_fill_rows": np_fill_rows,
    "np_diag_embed": np_diag_embed,
    "np_fill_diagonal": np_fill_diagonal,
    "np_padded_argwhere": np_padded_argwhere,
    "hasattr": hasattr,
    "range": range,
    "tuple": tuple,
    "len": len,
    "zip": zip,
    "sum": sum,
    "slice": slice,
    "min": min,
    "max": max,
}


def _eval_ref(spec, inputs):
    env = dict(_REF_ENV)
    env.update(spec.get("attrs") or {})
    if spec.get("variadic"):
        env["xs"] = [np.asarray(a) for a in inputs]
    else:
        for aname, val in zip(spec.get("args", ["x"]), inputs):
            env[aname] = np.asarray(val)
    # env goes in GLOBALS: names inside lambda/genexp bodies resolve against
    # eval's globals, not its locals. numpy keepdims reductions lazily
    # __import__ internally, so that one builtin must be present.
    return eval(  # noqa: S307
        spec["ref"], {"__builtins__": {"__import__": __import__}, **env})


_VALUE_SPECS = [n for n in sorted(SPECS) if SPECS[n].get("ref") and not SPECS[n].get("skip_test")]


@pytest.mark.parametrize("name", _VALUE_SPECS)
def test_values_vs_numpy_reference(name):
    spec = SPECS[name]
    rng = np.random.RandomState(7)
    inputs = _inputs_for(spec, rng)
    op = GENERATED[name]
    out = op(inputs) if spec.get("variadic") else op(*[paddle.to_tensor(a) for a in inputs])
    outs = out if isinstance(out, (list, tuple)) else [out]
    ref = _eval_ref(spec, inputs)
    refs = list(ref) if isinstance(ref, (list, tuple)) else [ref]
    assert len(outs) == len(refs), f"{name}: arity {len(outs)} vs ref {len(refs)}"
    for o, r in zip(outs, refs):
        got = np.asarray(o.numpy())
        want = np.asarray(r)
        if np.issubdtype(want.dtype, np.floating) or np.issubdtype(want.dtype, np.complexfloating):
            np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-6, err_msg=name)
        else:
            np.testing.assert_array_equal(got, want, err_msg=name)


def test_value_sweep_coverage_report(capsys):
    """Coverage accounting (VERDICT r2 weak #3): how much of the generated
    surface is VALUE-checked, not just finiteness-checked."""
    total = [n for n in SPECS if not SPECS[n].get("alias_of")]
    with_ref = [n for n in total if SPECS[n].get("ref")]
    skipped = [n for n in total if SPECS[n].get("skip_test") and not SPECS[n].get("ref")]
    pct = 100.0 * len(with_ref) / len(total)
    print(f"\nvalue-checked: {len(with_ref)}/{len(total)} generated ops ({pct:.0f}%); "
          f"bespoke-only: {sorted(skipped)}")
    assert pct >= 90.0


def test_mutation_is_caught():
    """Prove the sweep fails when an op's math is wrong: evaluate a MUTATED
    expr (cosh-for-sinh-style) against the ref and require a mismatch."""
    from paddle_tpu.ops.generated import _compile_impl

    spec = dict(SPECS["exp2"])
    spec["expr"] = "jnp.exp(x)"  # the classic typo
    bad = _compile_impl(spec)
    rng = np.random.RandomState(7)
    (x,) = _inputs_for(spec, rng)
    got = np.asarray(bad(paddle.to_tensor(x)._data))
    want = np.asarray(_eval_ref(spec, [x]))
    assert not np.allclose(got, want, rtol=2e-5), "mutated op not caught"
