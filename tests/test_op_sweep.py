"""Generated-op sweep — the OpTest battery over the yaml op table.

Methodology per reference ``unittests/op_test.py:282``: every registered
generated op gets (1) an fp32 forward execution with finite outputs, (2) a
bf16 forward smoke for float ops, (3) a central finite-difference gradient
check against the autograd tape for differentiable ops. Op-specific input
domains/shapes come from the spec metadata (ops.yaml), so newly added yaml
entries are tested automatically.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.ops.generated import GENERATED, SPECS

_SHAPES = {"sq": (4, 4), "vec": (6,), None: (2, 3)}


def _shape_of(val):
    if val is None:
        return _SHAPES[None]
    if isinstance(val, str):
        return _SHAPES.get(val, _SHAPES[None])
    return tuple(val)


def _sample(domain, shape, rng):
    if domain == "pos":
        return (rng.rand(*shape) + 0.5).astype(np.float32)
    if domain == "unit":
        return (rng.rand(*shape) * 0.8 + 0.1).astype(np.float32)
    if domain == "smallint":
        return rng.randint(0, 3, shape).astype(np.int32)
    if domain == "index":
        return rng.randint(0, 2, shape).astype(np.int32)
    return rng.randn(*shape).astype(np.float32)


# bespoke inputs where the generic sampler can't satisfy op preconditions
def _custom_inputs(name, rng):
    if name == "bucketize":
        return [rng.randn(2, 3).astype(np.float32), np.sort(rng.randn(5).astype(np.float32))]
    if name == "isin":
        return [rng.randint(0, 4, (2, 3)).astype(np.int32), rng.randint(0, 4, (4,)).astype(np.int32)]
    if name == "argwhere":
        return [(rng.rand(2, 3) > 0.5).astype(np.float32)]
    if name == "matrix_exp":
        return [(rng.randn(3, 3) * 0.1).astype(np.float32)]
    if name in ("matrix_norm", "lu_unpack"):
        return [rng.randn(3, 3).astype(np.float32)]
    return None


def _inputs_for(spec, rng):
    custom = _custom_inputs(spec["name"], rng)
    if custom is not None:
        return custom
    args = spec.get("args", ["x"])
    if spec.get("variadic"):
        sh = _shape_of(spec.get("shape"))
        return [_sample(spec.get("domain"), sh, rng) for _ in range(2)]
    inputs = []
    for i in range(len(args)):
        sh = _shape_of(spec.get("shape" if i == 0 else f"shape{i + 1}", spec.get("shape")))
        dom = spec.get("domain" if i == 0 else f"domain{i + 1}", spec.get("domain"))
        inputs.append(_sample(dom, sh, rng))
    return inputs


def _runnable_specs():
    out = []
    for name, spec in sorted(SPECS.items()):
        if spec.get("skip_test") or spec.get("alias_of"):
            continue
        out.append(name)
    return out


@pytest.mark.parametrize("name", _runnable_specs())
def test_forward_fp32(name):
    spec = SPECS[name]
    rng = np.random.RandomState(7)
    inputs = _inputs_for(spec, rng)
    op = GENERATED[name]
    if spec.get("variadic"):
        out = op(inputs)
    else:
        out = op(*[paddle.to_tensor(a) for a in inputs])
    outs = out if isinstance(out, (list, tuple)) else [out]
    for o in outs:
        a = np.asarray(o.numpy())
        if np.issubdtype(a.dtype, np.floating):
            assert np.isfinite(a).all(), f"{name} produced non-finite fp32 output"


@pytest.mark.parametrize(
    "name",
    [n for n in _runnable_specs()
     if SPECS[n].get("grad", True) and SPECS[n].get("bf16", True)
     and not SPECS[n].get("variadic")
     and SPECS[n].get("args", ["x"]) and SPECS[n].get("domain") not in ("smallint", "index")],
)
def test_forward_bf16(name):
    """Float ops must run in bf16 (the MXU-native dtype)."""
    import jax.numpy as jnp

    spec = SPECS[name]
    rng = np.random.RandomState(8)
    inputs = _inputs_for(spec, rng)
    if any(np.issubdtype(np.asarray(a).dtype, np.integer) for a in inputs):
        pytest.skip("integer-input op")
    tensors = [paddle.to_tensor(a).astype("bfloat16") for a in inputs]
    out = GENERATED[name](*tensors)
    outs = out if isinstance(out, (list, tuple)) else [out]
    assert all(o.numpy() is not None for o in outs)


_GRAD_EXCLUDE = {
    # piecewise-constant or argsort-coupled outputs: analytic grad is 0/ok but
    # finite differences step across discontinuities
    "fix", "msort", "unwrap", "renorm", "nanmedian", "nanquantile", "diff",
}


@pytest.mark.parametrize(
    "name",
    [n for n in _runnable_specs()
     if SPECS[n].get("grad", True) and not SPECS[n].get("variadic")
     and SPECS[n].get("args", ["x"]) and n not in _GRAD_EXCLUDE
     and SPECS[n].get("domain") not in ("smallint", "index")
     and not any(SPECS[n].get(f"domain{i}") in ("smallint", "index") for i in (2, 3))],
)
def test_grad_check(name):
    """Central finite difference vs the autograd tape (op_test.check_grad)."""
    from op_test import check_grad

    spec = SPECS[name]
    rng = np.random.RandomState(9)
    inputs = _inputs_for(spec, rng)
    n_tensor = len(spec.get("args", ["x"]))
    out_index = 0 if spec.get("n_outs") in (2, "list") else None
    nondiff = set(spec.get("nondiff", ()))
    if out_index == 0 and 0 in nondiff:
        pytest.skip("first output non-differentiable")
    check_grad(
        GENERATED[name], inputs[:n_tensor],
        grad_inputs=[i for i in range(n_tensor)
                     if not np.issubdtype(np.asarray(inputs[i]).dtype, np.integer)],
        out_index=out_index, atol=5e-2, rtol=5e-2,
    )


def test_registry_count():
    """SURVEY §2.2 coverage gate: the registered forward-op surface keeps
    growing toward the reference's (913 registrations incl. grad kernels;
    grads are implicit here)."""
    from paddle_tpu.ops.registry import op_count

    assert op_count() >= 500, op_count()
