"""Portable-artifact story: (1) paddle.onnx.export writes the portable
StableHLO interchange artifact and a CPU-ONLY subprocess (no TPU visible)
loads and runs it — the deployment property the reference gets from
paddle2onnx; (2) a standalone C++ binary (runtime_cpp/capi_demo.cc, the
goapi-role second-language consumer) drives the C ABI end to end."""
import json
import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.static import InputSpec

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _model():
    paddle.seed(11)
    return nn.Sequential(nn.Linear(6, 12), nn.Tanh(), nn.Linear(12, 3))


class TestPortableExport:
    def test_onnx_export_writes_portable_artifact(self, tmp_path):
        m = _model()
        m.eval()
        prefix = paddle.onnx.export(
            m, str(tmp_path / "net.onnx"), input_spec=[InputSpec([2, 6], "float32")]
        )
        assert os.path.exists(prefix + ".pdmodel")
        assert os.path.exists(prefix + ".pdiparams")

    def test_onnx_format_raises_with_guidance(self, tmp_path):
        with pytest.raises(NotImplementedError, match="paddle2onnx|StableHLO"):
            paddle.onnx.export(
                _model(), str(tmp_path / "x"),
                input_spec=[InputSpec([2, 6], "float32")], format="onnx",
            )

    def test_cpu_only_subprocess_loads_and_matches(self, tmp_path):
        m = _model()
        m.eval()
        x = np.random.RandomState(0).randn(2, 6).astype(np.float32)
        want = m(paddle.to_tensor(x)).numpy()
        prefix = paddle.onnx.export(
            m, str(tmp_path / "net"), input_spec=[InputSpec([2, 6], "float32")]
        )
        np.save(tmp_path / "x.npy", x)
        np.save(tmp_path / "want.npy", want)

        script = textwrap.dedent(
            f"""
            import os
            os.environ["JAX_PLATFORMS"] = "cpu"  # no TPU in this process
            import numpy as np
            import paddle_tpu as paddle
            layer = paddle.jit.load({prefix!r})
            x = np.load({str(tmp_path / 'x.npy')!r})
            out = layer(paddle.to_tensor(x))
            out = out[0] if isinstance(out, (list, tuple)) else out
            np.testing.assert_allclose(
                out.numpy(), np.load({str(tmp_path / 'want.npy')!r}),
                rtol=1e-4, atol=1e-5,
            )
            print("PORTABLE_OK")
            """
        )
        env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
        env.update({"PYTHONPATH": ROOT, "JAX_PLATFORMS": "cpu"})
        r = subprocess.run(
            [sys.executable, "-c", script], env=env,
            capture_output=True, text=True, timeout=240,
        )
        assert r.returncode == 0, r.stderr[-2000:]
        assert "PORTABLE_OK" in r.stdout


class TestCppConsumer:
    def test_capi_demo_binary_runs_artifact(self, tmp_path):
        demo = os.path.join(ROOT, "runtime_cpp", "capi_demo")
        if not os.path.exists(demo):
            r = subprocess.run(
                ["make", "-C", os.path.join(ROOT, "runtime_cpp"), "capi_demo"],
                capture_output=True,
            )
            if r.returncode != 0:
                pytest.skip(f"capi_demo build unavailable: {r.stderr[-300:]}")

        m = _model()
        m.eval()
        prefix = str(tmp_path / "net")
        paddle.static.save_inference_model(
            prefix, [InputSpec([2, 6], "float32")], m
        )
        # same deterministic ramp the C++ host feeds
        n = 12
        x = (np.arange(n) % 17).astype(np.float32) * 0.25 - 2.0
        want = m(paddle.to_tensor(x.reshape(2, 6))).numpy()

        env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
        env.update({"JAX_PLATFORMS": "cpu", "PYTHONPATH": ROOT})
        r = subprocess.run(
            [demo, prefix, ROOT, "2", "6"], env=env,
            capture_output=True, text=True, timeout=240,
        )
        assert r.returncode == 0, (r.stdout, r.stderr[-2000:])
        got = json.loads(r.stdout.strip().splitlines()[-1])
        assert got["numel"] == want.size
        np.testing.assert_allclose(got["sum"], float(want.sum()), rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(
            got["head"], want.ravel()[:4], rtol=1e-4, atol=1e-5
        )
