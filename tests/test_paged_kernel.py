"""Paged-attention decode kernel + int8 head kernel — serving bit-identity.

The ISSUE-18 acceptance surface for the two new serving kernels:

- ``ops/kernels/paged_attention`` reads K/V straight from PagePool blocks
  through the block table (no gather-then-dense-attend) and must be
  **bit-identical** to the existing gather path — at the kernel level
  against the same ``_grouped_attention`` math, at the builder level
  (``build_paged_decode_kernel`` vs ``build_paged_decode``, GPT and
  Llama/GQA), and engine end-to-end behind ``FLAGS_serve_paged_kernel``
  (prefix cache on and off). CPU runs the kernel in Pallas interpret mode.
- ``ops/kernels/int8_matmul`` (weight-only int8 head matmul behind
  ``FLAGS_serve_int8_kernel``) must match the dequantize-then-matmul it
  replaces bitwise, and the engine's int8 path must produce identical
  tokens with the kernel on or off.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.models.generation as G
from paddle_tpu.framework import flags
from paddle_tpu.ops import kernels as K
from paddle_tpu.serving import Engine
from serving_util import ENGINE_KW, make_prompts, tiny_gpt

jnp = pytest.importorskip("jax.numpy")
import jax  # noqa: E402


def _ref_paged(q, kpool, vpool, tables, pos):
    """The existing serving read: gather context via the block table, then
    dense grouped attention over live positions."""
    B, H, D = q.shape
    NB, BS, KV, _ = kpool.shape
    T_pad = tables.shape[1] * BS
    kc = kpool[tables].reshape(B, T_pad, KV, D)
    vc = vpool[tables].reshape(B, T_pad, KV, D)
    live = jnp.arange(T_pad)[None, :] <= pos[:, None]
    o = G._grouped_attention(q[:, None], kc, vc,
                             live[:, None, None, None, :], H // KV)
    return o.reshape(B, H * D)


def _disjoint_tables(rng, B, MB, NB):
    """Per-row disjoint block ids, as PagePool guarantees (duplicate ids
    would make the fresh-KV scatter order compilation-dependent)."""
    perm = rng.permutation(np.arange(1, NB))[: B * MB]
    return jnp.asarray(perm.reshape(B, MB).astype(np.int32))


class TestPagedKernelBitIdentity:
    @pytest.mark.parametrize("heads", [(4, 4), (8, 2)],
                             ids=["mha", "gqa_rep4"])
    def test_kernel_matches_gather_reference(self, heads):
        H, KV = heads
        B, D, BS, MB, NB = 4, 16, 8, 4, 64
        rng = np.random.RandomState(1)
        kpool = jnp.asarray(rng.randn(NB, BS, KV, D), jnp.float32)
        vpool = jnp.asarray(rng.randn(NB, BS, KV, D), jnp.float32)
        tables = jnp.asarray(rng.randint(1, NB, size=(B, MB)), jnp.int32)
        pos = jnp.asarray([3, 8, 17, 31], jnp.int32)
        q = jnp.asarray(rng.randn(B, H, D), jnp.float32)
        ref = np.asarray(_ref_paged(q, kpool, vpool, tables, pos))
        for score_mode in ("live", "full"):
            for rows in (1, 2, 4):
                out = K.paged_attention_rows(
                    q, kpool, vpool, tables, pos,
                    config={"rows_per_program": rows,
                            "score_mode": score_mode})
                assert np.array_equal(np.asarray(out), ref), \
                    (score_mode, rows)

    @pytest.mark.parametrize("which", ["gpt", "llama_gqa"])
    def test_builder_bitwise_vs_gather_builder(self, which):
        if which == "gpt":
            _, arch, params, _ = G.gpt_decode_state(tiny_gpt(seed=0))
            vocab = 211
        else:
            from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny

            paddle.seed(0)
            m = LlamaForCausalLM(llama_tiny(num_kv_heads=2))
            m.eval()
            _, arch, params, _ = G.llama_decode_state(m)
            vocab = m.model.config.vocab_size
        B, BS, MB, NB = 4, 8, 4, 64
        L, KV, D = len(params["layers"]), arch["kv_heads"], arch["head_dim"]
        rng = np.random.RandomState(1)
        kpool = jnp.asarray(rng.randn(L, NB, BS, KV, D), jnp.float32)
        vpool = jnp.asarray(rng.randn(L, NB, BS, KV, D), jnp.float32)
        tables = _disjoint_tables(rng, B, MB, NB)
        pos = jnp.asarray([3, 8, 17, 30], jnp.int32)
        toks = jnp.asarray(rng.randint(0, vocab, (B,)), jnp.int32)
        temps = jnp.asarray([0.0, 0.7, 0.0, 1.1], jnp.float32)
        key = jax.random.PRNGKey(7)

        ref = jax.jit(G.build_paged_decode(arch, B, BS, MB))
        ker = jax.jit(G.build_paged_decode_kernel(arch, B, BS, MB))
        r = ref(params, kpool, vpool, tables, pos, toks, temps, key)
        k = ker(params, kpool, vpool, tables, pos, toks, temps, key)
        for a, b, name in zip(r, k, ("kpool", "vpool", "next_tokens")):
            assert np.array_equal(np.asarray(a), np.asarray(b)), name


def _run_engine(prompt_seed=3, n=4, max_new=8, **fl):
    """Token outputs of a fresh tiny-GPT engine under flag overrides."""
    old = {k: flags._FLAGS.get(k) for k in fl}
    flags._FLAGS.update(fl)
    try:
        with Engine(tiny_gpt(seed=0), **ENGINE_KW) as eng:
            prompts = make_prompts(n, np.random.RandomState(prompt_seed))
            handles = [eng.submit(p, max_new_tokens=max_new, temperature=0.0)
                       for p in prompts]
            return [h.result(timeout=300) for h in handles]
    finally:
        for k, v in old.items():
            if v is None:
                flags._FLAGS.pop(k, None)
            else:
                flags._FLAGS[k] = v


class TestEnginePagedKernel:
    @pytest.mark.parametrize("prefix_cache", [False, True],
                             ids=["plain", "prefix_cache"])
    def test_engine_tokens_identical_with_kernel(self, prefix_cache):
        base = _run_engine(FLAGS_serve_paged_kernel=False,
                           FLAGS_serve_prefix_cache=prefix_cache)
        kern = _run_engine(FLAGS_serve_paged_kernel=True,
                           FLAGS_serve_prefix_cache=prefix_cache)
        assert base == kern

    def test_engine_actually_builds_kernel_step(self, monkeypatch):
        """The flag must really swap the decode builder (not silently keep
        the gather path)."""
        called = {"n": 0}
        real = G.build_paged_decode_kernel

        def spy(*a, **k):
            called["n"] += 1
            return real(*a, **k)

        monkeypatch.setattr(G, "build_paged_decode_kernel", spy)
        out = _run_engine(FLAGS_serve_paged_kernel=True)
        assert called["n"] >= 1
        assert out == _run_engine(FLAGS_serve_paged_kernel=False)


class TestInt8Kernel:
    def test_int8_matmul_bitwise_vs_dequant_matmul(self):
        rng = np.random.RandomState(2)
        w = rng.randn(64, 32).astype(np.float32)
        scale = jnp.asarray(np.abs(w).max(), jnp.float32)
        qw = jnp.asarray(
            np.clip(np.round(w / (np.asarray(scale) / 127.0)), -127, 127),
            jnp.int8)
        wd = (qw.astype(jnp.float32) * (scale / 127.0)).astype(jnp.float32)
        x = jnp.asarray(rng.randn(3, 32), jnp.float32)
        out_t = K.int8_matmul(x, qw, scale, transpose_w=True,
                              config={"block_n": 512})
        assert np.array_equal(np.asarray(out_t), np.asarray(x @ wd.T))
        out_n = K.int8_matmul(x, qw.T, scale, transpose_w=False,
                              config={"block_n": 512})
        assert np.array_equal(np.asarray(out_n), np.asarray(x @ wd.T))

    def test_attach_int8_head_grafts_quantized_head(self):
        from paddle_tpu.serving.int8 import (
            attach_int8_head, dequantize_tree, quantize_params,
        )

        _, _, params, _ = G.gpt_decode_state(tiny_gpt(seed=0))
        tagged = quantize_params(params)
        dense = dequantize_tree(tagged, jnp.float32)
        grafted = attach_int8_head(dense, tagged)
        assert grafted["head_q"]["q"].dtype == jnp.int8
        assert "head_q" not in dense  # original tree untouched
        # un-quantized tree passes through unchanged
        assert attach_int8_head(params, params) is params

    def test_engine_int8_tokens_identical_with_kernel(self, monkeypatch):
        import paddle_tpu.ops.kernels as KM

        calls = {"n": 0}
        real = KM.int8_matmul

        def spy(*a, **k):
            calls["n"] += 1
            return real(*a, **k)

        monkeypatch.setattr(KM, "int8_matmul", spy)
        base = _run_engine(FLAGS_serve_int8=True,
                           FLAGS_serve_int8_kernel=False)
        assert calls["n"] == 0  # kernel off: head stays on the dense matmul
        kern = _run_engine(FLAGS_serve_int8=True,
                           FLAGS_serve_int8_kernel=True)
        assert calls["n"] >= 1  # kernel on: the head traced through it
        assert base == kern
        both = _run_engine(FLAGS_serve_int8=True,
                           FLAGS_serve_int8_kernel=True,
                           FLAGS_serve_paged_kernel=True)
        assert base == both
