"""Tensor op numeric tests (reference OpTest pattern, SURVEY.md §4)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from op_test import check_grad, check_output


class TestElementwise:
    def test_add(self):
        check_output(paddle.add, np.add, [np.random.rand(3, 4), np.random.rand(3, 4)])

    def test_add_broadcast(self):
        check_output(paddle.add, np.add, [np.random.rand(3, 4), np.random.rand(4)])

    def test_sub_scalar(self):
        x = paddle.to_tensor(np.ones((2, 2), np.float32))
        np.testing.assert_allclose((x - 1.5).numpy(), -0.5 * np.ones((2, 2)))
        np.testing.assert_allclose((1.5 - x).numpy(), 0.5 * np.ones((2, 2)))

    def test_mul_div(self):
        a, b = np.random.rand(5), np.random.rand(5) + 0.5
        check_output(paddle.multiply, np.multiply, [a, b])
        check_output(paddle.divide, np.divide, [a, b])

    def test_pow(self):
        check_output(lambda x: paddle.pow(x, 2.0), lambda x: x**2, [np.random.rand(4)])

    def test_maximum_minimum(self):
        a, b = np.random.randn(3, 3), np.random.randn(3, 3)
        check_output(paddle.maximum, np.maximum, [a, b])
        check_output(paddle.minimum, np.minimum, [a, b])

    def test_dtype_preserved(self):
        x = paddle.to_tensor(np.ones((2,), np.float32))
        assert (x * 2).dtype == np.dtype("float32")
        assert (x + 1).dtype == np.dtype("float32")


class TestUnary:
    @pytest.mark.parametrize(
        "name", ["exp", "log", "sqrt", "tanh", "sin", "cos", "abs", "floor", "ceil", "sigmoid"]
    )
    def test_match_numpy(self, name):
        np_map = {"sigmoid": lambda x: 1 / (1 + np.exp(-x))}
        data = np.random.rand(4, 3).astype(np.float64) + 0.1
        np_fn = np_map[name] if name in np_map else getattr(np, name)
        check_output(getattr(paddle, name), np_fn, [data], atol=1e-4, rtol=1e-3)

    def test_clip(self):
        check_output(
            lambda x: paddle.clip(x, 0.2, 0.8), lambda x: np.clip(x, 0.2, 0.8), [np.random.rand(10)]
        )


class TestReduce:
    def test_sum_axes(self):
        x = np.random.rand(2, 3, 4)
        check_output(lambda t: paddle.sum(t), lambda a: np.sum(a), [x])
        check_output(lambda t: paddle.sum(t, axis=1), lambda a: np.sum(a, axis=1), [x])
        check_output(
            lambda t: paddle.sum(t, axis=[0, 2], keepdim=True),
            lambda a: np.sum(a, axis=(0, 2), keepdims=True),
            [x],
        )

    def test_mean_max_min_prod(self):
        x = np.random.rand(3, 4)
        check_output(lambda t: paddle.mean(t, axis=0), lambda a: np.mean(a, axis=0), [x])
        check_output(lambda t: paddle.max(t, axis=1), lambda a: np.max(a, axis=1), [x])
        check_output(lambda t: paddle.min(t), lambda a: np.min(a), [x])
        check_output(lambda t: paddle.prod(t, axis=0), lambda a: np.prod(a, axis=0), [x])

    def test_argmax_int64(self):
        x = paddle.to_tensor(np.random.rand(3, 5))
        out = paddle.argmax(x, axis=1)
        assert out.dtype == np.dtype("int64")
        np.testing.assert_array_equal(out.numpy(), np.argmax(x.numpy(), axis=1))

    def test_std_var_unbiased(self):
        x = np.random.rand(10)
        check_output(lambda t: paddle.std(t), lambda a: np.std(a, ddof=1), [x])
        check_output(lambda t: paddle.var(t, unbiased=False), lambda a: np.var(a), [x])

    def test_cumsum(self):
        x = np.random.rand(3, 4)
        check_output(lambda t: paddle.cumsum(t, axis=1), lambda a: np.cumsum(a, axis=1), [x])
        check_output(lambda t: paddle.cumsum(t), lambda a: np.cumsum(a.reshape(-1)), [x])


class TestMatmul:
    def test_2d(self):
        check_output(paddle.matmul, np.matmul, [np.random.rand(3, 4), np.random.rand(4, 5)])

    def test_batched(self):
        check_output(paddle.matmul, np.matmul, [np.random.rand(2, 3, 4), np.random.rand(2, 4, 5)])

    def test_transpose_flags(self):
        a, b = np.random.rand(4, 3), np.random.rand(4, 5)
        out = paddle.matmul(paddle.to_tensor(a), paddle.to_tensor(b), transpose_x=True)
        np.testing.assert_allclose(out.numpy(), a.T @ b, rtol=1e-5)

    def test_grad(self):
        check_grad(paddle.matmul, [np.random.rand(3, 4), np.random.rand(4, 2)])


class TestManipulation:
    def test_reshape_transpose(self):
        x = np.arange(24).reshape(2, 3, 4).astype(np.float32)
        check_output(lambda t: paddle.reshape(t, [4, 6]), lambda a: a.reshape(4, 6), [x])
        check_output(lambda t: paddle.transpose(t, [2, 0, 1]), lambda a: a.transpose(2, 0, 1), [x])

    def test_concat_stack_split(self):
        a, b = np.random.rand(2, 3), np.random.rand(2, 3)
        out = paddle.concat([paddle.to_tensor(a), paddle.to_tensor(b)], axis=0)
        np.testing.assert_allclose(out.numpy(), np.concatenate([a, b], 0))
        out = paddle.stack([paddle.to_tensor(a), paddle.to_tensor(b)], axis=1)
        np.testing.assert_allclose(out.numpy(), np.stack([a, b], 1))
        parts = paddle.split(paddle.to_tensor(a), 3, axis=1)
        assert len(parts) == 3 and parts[0].shape == [2, 1]
        parts = paddle.split(paddle.to_tensor(a), [1, 2], axis=1)
        assert parts[1].shape == [2, 2]

    def test_squeeze_unsqueeze_flatten(self):
        x = np.random.rand(1, 3, 1, 4)
        check_output(lambda t: paddle.squeeze(t), lambda a: np.squeeze(a), [x])
        check_output(lambda t: paddle.unsqueeze(t, 0), lambda a: a[None], [x])
        check_output(lambda t: paddle.flatten(t, 1, 2), lambda a: a.reshape(1, 3, 4), [x])

    def test_gather_index_select(self):
        x = np.random.rand(5, 3).astype(np.float32)
        idx = np.array([0, 2, 4])
        out = paddle.gather(paddle.to_tensor(x), paddle.to_tensor(idx), axis=0)
        np.testing.assert_allclose(out.numpy(), x[idx])

    def test_getitem(self):
        x = np.random.rand(4, 5, 6).astype(np.float32)
        t = paddle.to_tensor(x)
        np.testing.assert_allclose(t[1].numpy(), x[1])
        np.testing.assert_allclose(t[1:3, ::2].numpy(), x[1:3, ::2])
        np.testing.assert_allclose(t[:, -1].numpy(), x[:, -1])
        np.testing.assert_allclose(t[..., 0].numpy(), x[..., 0])
        mask = x[:, 0, 0] > 0.5
        np.testing.assert_allclose(t[paddle.to_tensor(mask)].numpy(), x[mask])

    def test_setitem(self):
        x = np.zeros((3, 3), np.float32)
        t = paddle.to_tensor(x)
        t[1] = 5.0
        assert t.numpy()[1].sum() == 15.0
        t[0, 2] = 7.0
        assert t.numpy()[0, 2] == 7.0

    def test_topk_sort(self):
        x = np.random.rand(3, 6)
        vals, idx = paddle.topk(paddle.to_tensor(x), 2, axis=1)
        ref = np.sort(x, axis=1)[:, ::-1][:, :2]
        np.testing.assert_allclose(vals.numpy(), ref, rtol=1e-6)
        out = paddle.sort(paddle.to_tensor(x), axis=1)
        np.testing.assert_allclose(out.numpy(), np.sort(x, axis=1), rtol=1e-6)

    def test_where(self):
        c = np.array([True, False, True])
        a, b = np.ones(3, np.float32), np.zeros(3, np.float32)
        out = paddle.where(paddle.to_tensor(c), paddle.to_tensor(a), paddle.to_tensor(b))
        np.testing.assert_allclose(out.numpy(), np.where(c, a, b))

    def test_pad(self):
        x = np.random.rand(2, 3).astype(np.float32)
        out = paddle.nn.functional.pad(paddle.to_tensor(x), [1, 1], value=0.5)
        assert out.shape == [2, 5]

    def test_tile_expand(self):
        x = np.random.rand(1, 3).astype(np.float32)
        np.testing.assert_allclose(paddle.tile(paddle.to_tensor(x), [2, 2]).numpy(), np.tile(x, (2, 2)))
        np.testing.assert_allclose(
            paddle.expand(paddle.to_tensor(x), [4, 3]).numpy(), np.broadcast_to(x, (4, 3))
        )


class TestLinalg:
    def test_inv_det_solve(self):
        a = np.random.rand(4, 4) + 4 * np.eye(4)
        check_output(paddle.linalg.inv, np.linalg.inv, [a], atol=1e-4)
        check_output(paddle.linalg.det, np.linalg.det, [a], atol=1e-3, rtol=1e-3)
        b = np.random.rand(4, 2)
        check_output(paddle.linalg.solve, lambda x, y: np.linalg.solve(x, y), [a, b], atol=1e-4)

    def test_cholesky_qr_svd(self):
        a = np.random.rand(3, 3)
        spd = a @ a.T + 3 * np.eye(3)
        L = paddle.linalg.cholesky(paddle.to_tensor(spd))
        np.testing.assert_allclose(L.numpy() @ L.numpy().T, spd, atol=1e-4)
        q, r = paddle.linalg.qr(paddle.to_tensor(a))
        np.testing.assert_allclose(q.numpy() @ r.numpy(), a, atol=1e-5)
        u, s, v = paddle.linalg.svd(paddle.to_tensor(a))
        np.testing.assert_allclose(
            (u.numpy() * s.numpy()[None]) @ v.numpy().T, a, atol=1e-5
        )

    def test_norm(self):
        x = np.random.rand(3, 4)
        check_output(lambda t: paddle.linalg.norm(t), lambda a: np.linalg.norm(a), [x])
        check_output(
            lambda t: paddle.linalg.norm(t, p=1, axis=1), lambda a: np.abs(a).sum(1), [x]
        )


class TestCreation:
    def test_basic(self):
        assert paddle.zeros([2, 3]).shape == [2, 3]
        assert paddle.ones([2], dtype="int64").dtype == np.dtype("int64")
        np.testing.assert_array_equal(paddle.arange(5).numpy(), np.arange(5))
        np.testing.assert_array_equal(paddle.eye(3).numpy(), np.eye(3, dtype=np.float32))
        np.testing.assert_allclose(paddle.full([2], 3.5).numpy(), np.full(2, 3.5, np.float32))

    def test_default_dtype_float(self):
        assert paddle.to_tensor([1.0, 2.0]).dtype == np.dtype("float32")
        assert paddle.to_tensor([1, 2]).dtype == np.dtype("int64")

    def test_tril_triu(self):
        x = np.random.rand(4, 4)
        check_output(paddle.tril, np.tril, [x])
        check_output(paddle.triu, np.triu, [x])

    def test_random_shapes(self):
        assert paddle.rand([3, 3]).shape == [3, 3]
        assert paddle.randn([2, 2]).dtype == np.dtype("float32")
        r = paddle.randint(0, 10, [100])
        assert r.numpy().min() >= 0 and r.numpy().max() < 10
        p = paddle.randperm(10).numpy()
        assert sorted(p.tolist()) == list(range(10))

    def test_seed_reproducible(self):
        paddle.seed(7)
        a = paddle.rand([4]).numpy()
        paddle.seed(7)
        b = paddle.rand([4]).numpy()
        np.testing.assert_array_equal(a, b)
