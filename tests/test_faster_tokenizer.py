"""FasterTokenizer — native C++ tokenizer vs the pure-Python twin.

Reference: operators/string/faster_tokenizer_op.cc +
test_faster_tokenizer_op.py methodology (text → padded id/seg tensors,
batch + pair encoding, truncation).
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.text import FasterTokenizer
from paddle_tpu.text.faster_tokenizer import _basic_tokenize, _wordpiece


VOCAB = {t: i for i, t in enumerate([
    "[PAD]", "[UNK]", "[CLS]", "[SEP]",
    "un", "##aff", "##able", "want", "##ed", "wa", "##nt", "the", "runn",
    "##ing", "hello", "world", ",", "!", "好", "你",
])}


def make(native=True):
    tok = FasterTokenizer(VOCAB)
    if not native:
        tok._handle = None  # force the python twin
    return tok


class TestWordpiece:
    def test_greedy_longest_match(self):
        # canonical BERT wordpiece example
        assert _wordpiece("unaffable", VOCAB, 1) == [
            VOCAB["un"], VOCAB["##aff"], VOCAB["##able"]]
        assert _wordpiece("wanted", VOCAB, 1) == [VOCAB["want"], VOCAB["##ed"]]
        # "unwanted": after "un", no "##wa..."-prefixed piece exists -> UNK
        assert _wordpiece("unwanted", VOCAB, 1) == [1]
        assert _wordpiece("xyz", VOCAB, 1) == [1]  # UNK

    def test_basic_tokenize_splits(self):
        assert _basic_tokenize("Hello, World!", True) == [
            "hello", ",", "world", "!"]
        assert _basic_tokenize("你好", True) == ["你", "好"]
        assert _basic_tokenize("a\x00b\x07c", True) == ["abc"]


class TestNativeParity:
    def test_native_available(self):
        tok = make()
        if not tok.is_native:
            pytest.skip("native runtime not built")

    @pytest.mark.parametrize("text", [
        "Hello, World! unaffable wanted",
        "你好 world",
        "the running UNAFFABLE",
        "punct...everywhere!!!",
        "",
        "café unaffable",  # combining accent: non-ascii word -> UNK both sides
        "x" * 150,  # over the 100-byte wordpiece cap
        "hello\x00world",  # NUL: both backends stop at the C-string boundary
    ])
    def test_ids_match_python_twin(self, text):
        tok_n, tok_p = make(True), make(False)
        if not tok_n.is_native:
            pytest.skip("native runtime not built")
        assert tok_n._encode_one(text) == tok_p._encode_one(text), text

    def test_batch_pair_encoding(self):
        tok = make()
        ids, segs = tok(["hello world", "unaffable"],
                        text_pair=["wanted", "the running"], max_seq_len=12)
        ids, segs = np.asarray(ids._data), np.asarray(segs._data)
        assert ids.shape == (2, 12) and segs.shape == (2, 12)
        assert ids[0, 0] == VOCAB["[CLS]"]
        row = list(ids[0])
        first_sep = row.index(VOCAB["[SEP]"])
        assert segs[0, first_sep] == 0 and segs[0, first_sep + 1] == 1
        assert ids[0, -1] == VOCAB["[PAD]"] or segs[0, -1] in (0, 1)

    def test_truncation_fits_budget(self):
        tok = make()
        ids, _ = tok(["hello world " * 50], max_seq_len=16)
        assert np.asarray(ids._data).shape == (1, 16)

    def test_sparse_vocab_falls_back_to_python(self):
        sparse = {"[PAD]": 0, "[UNK]": 1, "[CLS]": 2, "[SEP]": 3, "hello": 10}
        tok = FasterTokenizer(sparse)
        assert not tok.is_native  # native loader is line-number-indexed
        ids, _ = tok(["hello"], max_seq_len=4)
        assert list(np.asarray(ids._data)[0]) == [2, 10, 3, 0]

    def test_max_seq_len_too_small_raises(self):
        tok = make()
        with pytest.raises(ValueError, match="max_seq_len"):
            tok(["hi"], text_pair=["yo"], max_seq_len=2)

    def test_single_string_and_no_pad(self):
        tok = make()
        ids, segs = tok("hello world", max_seq_len=32, pad_to_max_seq_len=False)
        row = list(np.asarray(ids._data)[0])
        assert row == [VOCAB["[CLS]"], VOCAB["hello"], VOCAB["world"], VOCAB["[SEP]"]]


class TestTokenizerToErnieServing:
    def test_text_to_prediction_pipeline(self, tmp_path):
        """The reference's faster_tokenizer_op exists to feed text into
        BERT/ERNIE serving graphs; drive that pipeline: raw strings →
        FasterTokenizer → AOT-saved ErnieModel → logits, with save/load
        output parity."""
        from paddle_tpu.models.ernie import ErnieConfig, ErnieModel
        from paddle_tpu.static import InputSpec

        vocab = {t: i for i, t in enumerate(
            ["[PAD]", "[UNK]", "[CLS]", "[SEP]", "hello", "world", "good"])}
        tok = FasterTokenizer(vocab)
        paddle.seed(0)
        cfg = ErnieConfig(vocab_size=len(vocab), hidden_size=32, num_layers=2,
                          num_heads=2, max_position_embeddings=16,
                          type_vocab_size=2)
        model = ErnieModel(cfg)
        model.eval()

        ids, segs = tok(["hello world", "good good"], max_seq_len=8)
        out = model(ids, token_type_ids=segs)
        seq_out = out[0] if isinstance(out, (tuple, list)) else out
        assert np.asarray(seq_out._data).shape[0] == 2

        prefix = str(tmp_path / "ernie")
        paddle.jit.save(
            model, prefix,
            input_spec=[InputSpec([2, 8], "int64", name="input_ids"),
                        InputSpec([2, 8], "int64", name="token_type_ids")])
        loaded = paddle.jit.load(prefix)
        out2 = loaded(ids, segs)
        b = out2[0] if isinstance(out2, (tuple, list)) else out2
        np.testing.assert_allclose(
            np.asarray(seq_out._data), np.asarray(b._data), atol=1e-4)
