"""Async lazy runtime (ISSUE 6) — non-blocking dispatch, deferred guards,
background compilation, and device-side input prefetch.

Pins:
* bit-for-bit parity of a k-step Adam train loop with ``FLAGS_lazy_async``
  on vs off (the async restructure must not change a single bit);
* the deferred NaN/Inf guard still trips (≤1 step late, at the next
  flush/materialization/sync), still writes a flight-recorder dump naming
  the PRODUCING ``lazy_flush`` span, and still suppresses donation while
  armed;
* ``FLAGS_lazy_bg_compile``: a cache-miss step completes via the un-jitted
  replay while the executable compiles off-thread, and a later step picks
  the compiled executable up (counter asserts on both sides);
* the device-prefetch input stage preserves ordering, propagates worker
  errors, and shuts its thread down;
* tier-1 tripwire: the ``FLAGS_lazy_async=0`` kill-switch restores the old
  synchronous semantics exactly, and with async ON no blocking-readback
  (``block``) span ever appears inside a ``lazy_flush`` span — a future
  accidental ``.block_until_ready()``/``np.asarray`` on the hot path makes
  this grep fail fast.
"""
import json
import time

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu import profiler
from paddle_tpu.core import lazy
from paddle_tpu.fault import inject
from paddle_tpu.profiler import flight


@pytest.fixture(autouse=True)
def _clean_flags():
    lazy.set_lazy_mode(True)
    yield
    inject.disarm()
    paddle.set_flags({
        "FLAGS_lazy_async": True,
        "FLAGS_lazy_bg_compile": False,
        "FLAGS_check_nan_inf": False,
        "FLAGS_check_nan_inf_per_op": False,
        "FLAGS_lazy_donate": True,
    })
    try:
        lazy.sync()
    except FloatingPointError:
        pass
    lazy.set_lazy_mode(True)


class MLP(nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc1 = nn.Linear(16, 32)
        self.fc2 = nn.Linear(32, 10)

    def forward(self, x):
        return self.fc2(F.relu(self.fc1(x)))


def _train(async_on, steps=4):
    paddle.set_flags({"FLAGS_lazy_async": bool(async_on)})
    paddle.seed(7)
    m = MLP()
    opt = paddle.optimizer.Adam(learning_rate=0.01, parameters=m.parameters())
    losses = []
    for i in range(steps):
        x = paddle.to_tensor(np.random.RandomState(i).randn(8, 16).astype("float32"))
        y = paddle.to_tensor(np.random.RandomState(100 + i).randint(0, 10, (8,)))
        loss = F.cross_entropy(m(x), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss.numpy()))
    weights = [np.asarray(lazy.concrete(p._data)).copy() for p in m.parameters()]
    paddle.set_flags({"FLAGS_lazy_async": True})
    return losses, weights


class TestAsyncParity:
    def test_async_vs_sync_bit_for_bit(self):
        """Acceptance: k-step Adam train loss (and final params) bit-for-bit
        identical with FLAGS_lazy_async on vs off on CPU."""
        on_l, on_w = _train(True, steps=4)
        off_l, off_w = _train(False, steps=4)
        assert on_l == off_l  # float equality — not allclose
        for a, b in zip(on_w, off_w):
            np.testing.assert_array_equal(a, b)

    def test_sync_is_a_barrier(self):
        t = paddle.to_tensor(np.ones(32, np.float32))
        u = (t * 2.0 + 1.0)._data
        assert lazy.is_lazy(u) and u._concrete is None
        before = profiler.counters().get("lazy_blocks", 0)
        lazy.sync()
        assert u._concrete is not None and u._concrete.is_ready()
        assert profiler.counters().get("lazy_blocks", 0) > before
        np.testing.assert_array_equal(np.asarray(u._concrete), np.full(32, 3.0))

    def test_flush_cache_still_stable_and_donating(self):
        """The async restructure keeps PR-1 invariants: one executable per
        iteration signature, steady-state in-place (donated) updates."""
        profiler.reset_counters()
        _train(True, steps=5)
        c = profiler.counters()
        assert c.get("lazy_cache_hits", 0) >= 3
        assert c.get("lazy_donated_buffers", 0) > 0
        assert c.get("lazy_donation_fallbacks", 0) == 0


class TestDeferredNanGuard:
    def test_trip_surfaces_at_next_flush_with_producing_span(self, tmp_path, monkeypatch):
        """The deferred guard raises ≤1 step late and the flight dump still
        names the producing lazy_flush span (ISSUE-6 acceptance)."""
        monkeypatch.setenv("PADDLE_TPU_FLIGHT_DIR", str(tmp_path))
        paddle.set_flags({"FLAGS_check_nan_inf": True})
        w = paddle.to_tensor(np.zeros(4, np.float32))
        bad = paddle.log(w - 1.0)  # NaN born lazily
        lazy.flush()  # dispatches; the scan is deferred, NO raise here
        assert profiler.counters().get("lazy_deferred_checks", 0) >= 1
        ok = w + 1.0
        with pytest.raises(FloatingPointError, match="log"):
            lazy.flush()  # next flush drains the deferred check
        doc = json.load(open(flight.last_dump()))
        assert doc["reason"] == "naninf"
        prod = doc["extra"]["producing_span"]
        assert prod["name"] == "lazy_flush"
        assert doc["extra"]["origin"] == "lazy flush (deferred)"

    def test_trip_surfaces_at_sync(self):
        paddle.set_flags({"FLAGS_check_nan_inf": True})
        w = paddle.to_tensor(np.zeros(2, np.float32))
        paddle.log(w - 1.0) * 2.0  # held by nothing: per-op path not needed
        t = paddle.log(w - 1.0)
        lazy.flush()
        with pytest.raises(FloatingPointError):
            lazy.sync()

    def test_injected_nan_deferred_attribution(self, tmp_path, monkeypatch):
        """fault/inject.py tensor.nan poisons INSIDE the fused step; the
        deferred guard must still catch it with producing-span attribution
        and per-op mode must still name the poisoned op."""
        monkeypatch.setenv("PADDLE_TPU_FLIGHT_DIR", str(tmp_path))
        paddle.set_flags({"FLAGS_check_nan_inf": True})
        inject.arm({"tensor.nan": {"op": "matmul", "call": 1}})
        a = paddle.to_tensor(np.ones((4, 4), np.float32))
        b = paddle.to_tensor(np.ones((4, 4), np.float32))
        c = paddle.matmul(a, b)
        lazy.flush()  # poison dispatched, check deferred
        with pytest.raises(FloatingPointError):
            lazy.sync()
        doc = json.load(open(flight.last_dump()))
        assert doc["extra"]["producing_span"]["name"] == "lazy_flush"
        assert doc["fault_inject"]["armed"] is True

    def test_materialization_same_step_semantics_kept(self):
        """A loop that materializes every step still sees the trip within
        the step it reads — the drain runs at every materialization point."""
        paddle.set_flags({"FLAGS_check_nan_inf": True})
        w = paddle.to_tensor(np.zeros(3, np.float32))
        t = paddle.log(w - 1.0)
        with pytest.raises(FloatingPointError, match="log"):
            t.numpy()

    def test_donation_still_suppressed_while_armed(self):
        paddle.set_flags({"FLAGS_check_nan_inf": True})
        before = profiler.counters().get("naninf_donation_suppressed", 0)
        donated = profiler.counters().get("lazy_donated_buffers", 0)
        w = paddle.to_tensor(np.ones(4, np.float32))
        w._set_data((w + 1.0)._data)  # the donation rebind pattern
        lazy.sync()
        assert profiler.counters().get("naninf_donation_suppressed", 0) > before
        assert profiler.counters().get("lazy_donated_buffers", 0) == donated


class TestBackgroundCompile:
    def test_miss_completes_via_replay_then_picks_up_compiled(self):
        """Acceptance: a cache-miss step completes through the replay
        fallback while the background compile finishes, and a later step
        picks up the compiled executable (counter asserts)."""
        paddle.set_flags({"FLAGS_lazy_bg_compile": True})
        profiler.reset_counters()

        def fn(a, b):
            return a * b + jnp.sin(a)

        vals = []
        picked = False
        for step in range(100):
            x = jnp.full((64,), float(step))
            y = jnp.full((64,), 2.0)
            (out,), _ = lazy.record("bg_pickup_test", fn, [x, y], key=("bg_pickup_test",))
            lazy.flush()
            vals.append(float(np.asarray(out._concrete)[0]))
            if profiler.counters().get("lazy_bg_pickups", 0) >= 1:
                picked = True
                break
            time.sleep(0.05)
        c = profiler.counters()
        assert c.get("lazy_bg_compiles", 0) == 1
        assert c.get("lazy_bg_replays", 0) >= 1  # the miss step ran via replay
        assert picked, f"background compile never picked up: {c}"
        expect = [s * 2.0 + np.sin(np.float64(s)) for s in range(len(vals))]
        np.testing.assert_allclose(vals, expect, rtol=1e-6)

    def test_bg_compile_off_by_default(self):
        profiler.reset_counters()
        t = paddle.to_tensor(np.ones(8, np.float32))
        ((t + 3.0) * 2.0).numpy()
        assert profiler.counters().get("lazy_bg_compiles", 0) == 0

    def test_bg_compile_respects_async_kill_switch(self):
        paddle.set_flags({"FLAGS_lazy_bg_compile": True, "FLAGS_lazy_async": False})
        profiler.reset_counters()
        t = paddle.to_tensor(np.ones(8, np.float32))
        ((t - 5.0) / 2.0).numpy()
        assert profiler.counters().get("lazy_bg_compiles", 0) == 0


class _SeqDataset(paddle.io.Dataset):
    def __init__(self, n=17, fail_at=None):
        self.n = n
        self.fail_at = fail_at

    def __len__(self):
        return self.n

    def __getitem__(self, i):
        if self.fail_at is not None and i == self.fail_at:
            raise ValueError("boom")
        return np.full((3,), i, np.float32)


class TestDevicePrefetch:
    def test_ordering_matches_unprefetched(self):
        plain = [b.numpy() for b in paddle.io.DataLoader(_SeqDataset(), batch_size=4)]
        pref = [
            b.numpy()
            for b in paddle.io.DataLoader(_SeqDataset(), batch_size=4, device_prefetch=2)
        ]
        assert len(plain) == len(pref) == 5
        for a, b in zip(plain, pref):
            np.testing.assert_array_equal(a, b)

    def test_counter_and_device_residency(self):
        before = profiler.counters().get("io_device_prefetched", 0)
        it = iter(paddle.io.DataLoader(_SeqDataset(8), batch_size=4, device_prefetch=2))
        b = next(it)
        assert isinstance(b._data, jax.Array)  # already transferred, not lazy
        it.close()
        assert profiler.counters().get("io_device_prefetched", 0) > before

    def test_shutdown_on_exhaustion_and_early_close(self):
        it = iter(paddle.io.DataLoader(_SeqDataset(8), batch_size=4, device_prefetch=2))
        assert len(list(it)) == 2
        assert not it._thread.is_alive()
        it2 = iter(paddle.io.DataLoader(_SeqDataset(100), batch_size=2, device_prefetch=2))
        next(it2)
        it2.close()
        it2._thread.join(timeout=2.0)
        assert not it2._thread.is_alive()
        with pytest.raises(StopIteration):
            next(it2)

    def test_worker_error_propagates(self):
        it = iter(
            paddle.io.DataLoader(_SeqDataset(8, fail_at=5), batch_size=4, device_prefetch=2)
        )
        next(it)
        with pytest.raises(ValueError, match="boom"):
            next(it)
        assert not it._thread.is_alive()

    def test_engine_prefetch_commits_batch_sharding(self):
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
        from paddle_tpu.distributed.engine import HybridParallelEngine

        devs = jax.devices()
        mesh = Mesh(np.asarray(devs[: min(8, len(devs))]), ("dp",))
        paddle.seed(0)
        m = nn.Linear(8, 4)
        opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=m.parameters())
        eng = HybridParallelEngine(
            m, opt, lambda mm, x, y: F.mse_loss(mm(x), y), mesh=mesh
        )

        class XY(paddle.io.Dataset):
            def __len__(self):
                return 16

            def __getitem__(self, i):
                return (
                    np.full((8,), i, np.float32),
                    np.zeros((4,), np.float32),
                )

        pf = eng.prefetch(paddle.io.DataLoader(XY(), batch_size=8), buffer_size=2)
        x, y = next(pf)
        # committed to the engine's dp batch sharding BEFORE the step ran
        assert x._data.sharding == eng._batch_sharding(0, x._data)
        loss = eng.train_step(x, y)
        assert np.isfinite(float(loss.numpy()))
        pf.close()


class _XYDataset(paddle.io.Dataset):
    def __init__(self, n=32):
        rng = np.random.RandomState(0)
        self.x = rng.randn(n, 8).astype(np.float32)
        self.y = self.x.sum(axis=1, keepdims=True).astype(np.float32)

    def __len__(self):
        return len(self.x)

    def __getitem__(self, i):
        return self.x[i], self.y[i]


class TestFitDevicePrefetch:
    """hapi satellite (ROADMAP item 2 leftover): ``Model.fit(...,
    device_prefetch=N)`` plumbs the PR 6 DevicePrefetcher double-buffering
    into the fit loop — parity pinned bit-for-bit, counter proves the
    prefetch stage actually ran."""

    def _fit(self, **fit_kw):
        paddle.seed(7)
        net = nn.Linear(8, 1)
        model = paddle.Model(net)
        model.prepare(
            optimizer=paddle.optimizer.SGD(
                learning_rate=0.05, parameters=net.parameters()),
            loss=lambda pred, y: F.mse_loss(pred, y),
        )
        model.fit(_XYDataset(), batch_size=4, epochs=2, shuffle=False,
                  verbose=0, **fit_kw)
        return [np.asarray(p.numpy()) for p in net.parameters()]

    def test_parity_and_counter(self):
        plain = self._fit()
        before = profiler.counters().get("io_device_prefetched", 0)
        prefetched = self._fit(device_prefetch=2)
        assert profiler.counters().get("io_device_prefetched", 0) > before
        for a, b in zip(plain, prefetched):
            np.testing.assert_array_equal(a, b)

    def test_wraps_an_existing_loader_without_double_buffering(self):
        plain = self._fit()
        # a caller-built loader gets wrapped per epoch...
        paddle.seed(7)
        net = nn.Linear(8, 1)
        model = paddle.Model(net)
        model.prepare(
            optimizer=paddle.optimizer.SGD(
                learning_rate=0.05, parameters=net.parameters()),
            loss=lambda pred, y: F.mse_loss(pred, y),
        )
        loader = paddle.io.DataLoader(_XYDataset(), batch_size=4,
                                      shuffle=False)
        before = profiler.counters().get("io_device_prefetched", 0)
        model.fit(loader, epochs=2, verbose=0, device_prefetch=2)
        assert profiler.counters().get("io_device_prefetched", 0) > before
        for a, b in zip(plain,
                        [np.asarray(p.numpy()) for p in net.parameters()]):
            np.testing.assert_array_equal(a, b)
        # ...but a loader that already prefetches is NOT wrapped again
        from paddle_tpu.io import DevicePrefetcher

        own = paddle.io.DataLoader(_XYDataset(), batch_size=4, shuffle=False,
                                   device_prefetch=2)
        it = iter(own)
        assert isinstance(it, DevicePrefetcher)
        it.close()
        paddle.seed(7)
        net2 = nn.Linear(8, 1)
        model2 = paddle.Model(net2)
        model2.prepare(
            optimizer=paddle.optimizer.SGD(
                learning_rate=0.05, parameters=net2.parameters()),
            loss=lambda pred, y: F.mse_loss(pred, y),
        )
        model2.fit(own, epochs=1, verbose=0, device_prefetch=2)


class TestTripwire:
    """Tier-1 tripwires for the async runtime (CI satellite)."""

    def test_disabled_path_is_old_behavior(self, tmp_path, monkeypatch):
        """FLAGS_lazy_async=0: in-flush synchronous NaN scan (active span
        stack names lazy_flush at dump time, origin has no deferred tag), no
        deferral, no block instrumentation."""
        monkeypatch.setenv("PADDLE_TPU_FLIGHT_DIR", str(tmp_path))
        paddle.set_flags({"FLAGS_lazy_async": False, "FLAGS_check_nan_inf": True})
        deferred = profiler.counters().get("lazy_deferred_checks", 0)
        blocks = profiler.counters().get("lazy_blocks", 0)
        w = paddle.to_tensor(np.zeros(4, np.float32))
        t = paddle.log(w - 1.0)
        with pytest.raises(FloatingPointError, match="log"):
            t.numpy()
        doc = json.load(open(flight.last_dump()))
        assert any(s["name"] == "lazy_flush" for s in doc["active_spans"])
        assert doc["extra"]["origin"] == "lazy flush"
        assert "producing_span" not in doc["extra"]
        assert profiler.counters().get("lazy_deferred_checks", 0) == deferred
        assert profiler.counters().get("lazy_blocks", 0) == blocks

    def test_no_block_spans_inside_lazy_flush(self):
        """Span-stream grep: with async ON, the flush must only DISPATCH —
        any blocking readback recorded inside a lazy_flush span (a future
        accidental block_until_ready/np.asarray on the hot path) fails
        here."""
        p = profiler.Profiler(timer_only=True)
        p.start()
        paddle.seed(0)
        m = MLP()
        opt = paddle.optimizer.SGD(learning_rate=0.01, parameters=m.parameters())
        x = paddle.to_tensor(np.random.RandomState(0).randn(8, 16).astype("float32"))
        y = paddle.to_tensor(np.random.RandomState(1).randint(0, 10, (8,)))
        for _ in range(4):
            loss = F.cross_entropy(m(x), y)
            loss.backward()
            opt.step()
            opt.clear_grad()
            loss.item()
        p.stop()
        spans = profiler.span_events()
        by_id = {s["span_id"]: s for s in spans}

        def inside_flush(s):
            while s["parent_id"]:
                s = by_id.get(s["parent_id"])
                if s is None:
                    return False
                if s["name"] == "lazy_flush":
                    return True
            return False

        flushes = [s for s in spans if s["name"] == "lazy_flush"]
        assert flushes, [s["name"] for s in spans][:20]
        offenders = [s for s in spans if s["name"] == "block" and inside_flush(s)]
        assert not offenders, offenders
        # the async path was actually taken: cache hits DISPATCH
        assert any(
            s["name"] == "dispatch" and s["attrs"].get("cache") == "hit"
            for s in spans
        )

    def test_lr_plateau_no_midstep_sync(self):
        """optimizer/lr.py satellite: ReduceOnPlateau.step with a Python
        float does no device readback at all; with a Tensor it flushes
        (dispatch) first and the wait is attributed."""
        sched = paddle.optimizer.lr.ReduceOnPlateau(learning_rate=0.1, patience=0)
        blocks = profiler.counters().get("lazy_blocks", 0)
        sched.step(1.0)
        sched.step(2.0)  # worse -> lr drops, pure host floats
        assert sched.last_lr < 0.1
        assert profiler.counters().get("lazy_blocks", 0) == blocks
        t = paddle.to_tensor(np.float32(3.0)) + 0.0  # lazy scalar
        sched.step(t)
        assert sched.best == pytest.approx(1.0)

    def test_metric_update_single_sync(self):
        """metric satellite: one update = one coalesced host sync (no
        per-tensor np.asarray flushes splitting the fused step)."""
        m = paddle.metric.Accuracy()
        pred = paddle.to_tensor(
            np.array([[0.1, 0.9], [0.8, 0.2]], np.float32)
        ) * 1.0  # lazy
        label = paddle.to_tensor(np.array([1, 1], np.int64))
        flushes0 = profiler.counters().get("lazy_flushes", 0)
        correct = m.compute(pred, label)
        m.update(correct)
        flushes1 = profiler.counters().get("lazy_flushes", 0)
        assert flushes1 - flushes0 <= 1  # the coalesced materialization
        assert m.accumulate() == pytest.approx(0.5)
