"""Collection-error tripwire.

Tier-1 runs with ``--continue-on-collection-errors``, so a version-drift
ImportError in one test module silently drops that whole file from the suite
(it happened: three distributed files fell out on a jax upgrade and nothing
failed loudly). This test collects the full suite in a subprocess and FAILS
if any module errors at collection time — the drop becomes a red test.
"""
import os
import subprocess
import sys


def test_full_suite_collects_cleanly():
    tests_dir = os.path.dirname(os.path.abspath(__file__))
    proc = subprocess.run(
        [
            sys.executable, "-m", "pytest", tests_dir, "-q", "--collect-only",
            "-p", "no:cacheprovider",
        ],
        capture_output=True,
        text=True,
        timeout=240,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
        cwd=os.path.dirname(tests_dir),
    )
    out = proc.stdout + proc.stderr
    assert proc.returncode == 0, f"collection failed (rc={proc.returncode}):\n{out[-4000:]}"
    assert "ERROR" not in out, f"collection errors:\n{out[-4000:]}"
