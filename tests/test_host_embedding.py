"""Host-offloaded giant embedding (incubate/host_embedding.py) — the
TPU-first stand-in for the reference brpc PS embedding tables
(memory_sparse_table.cc / ssd_sparse_table.cc / the_one_ps.py:606).

Covers the PR 15 hot-path rebuild: native gather/scatter bit-exact against
the numpy fallback, HBM hot-row cache coherence through update/evict,
pipelined prefetch ordering + abandoned-layer GC, the physical-size
fallback that replaced the filesystem skip, and the tier-1 inert tripwire
(kill-switches off ⇒ no threads, no native entry points)."""
import gc
import os
import threading

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu import profiler
from paddle_tpu.framework import flags
from paddle_tpu.incubate import host_embedding as he
from paddle_tpu.incubate.host_embedding import (
    HostEmbedding, HostEmbeddingTable, HotRowCache,
)


@pytest.fixture(autouse=True)
def _restore_flags():
    prev = flags.get_flags([
        "FLAGS_host_emb_native", "FLAGS_host_emb_cache_rows",
        "FLAGS_host_emb_async_push", "FLAGS_host_emb_cache_min_count",
    ])
    yield
    flags.set_flags(prev)


def _native_available() -> bool:
    from paddle_tpu.core import native

    return native.lib() is not None and native.HAS_EMBED


class TestParityWithInHBMEmbedding:
    def test_forward_and_sgd_step_match_dense_embedding(self):
        V, D = 50, 8
        he_l = HostEmbedding(V, D, optimizer="sgd", seed=3)
        dense = nn.Embedding(V, D)
        # same initial rows
        ids_np = np.array([[1, 4, 4], [7, 1, 9]], np.int64)
        _ = he_l(paddle.to_tensor(ids_np))  # touch → init rows
        he_l._pending = []
        full = he_l.table.gather(np.arange(V))
        dense.weight.set_value(paddle.to_tensor(full.astype(np.float32)))

        ids = paddle.to_tensor(ids_np)
        target = paddle.to_tensor(np.random.RandomState(0).randn(2, 3, 8).astype(np.float32))

        he_l.train()
        out_h = he_l(ids)
        loss_h = F.mse_loss(out_h, target)
        loss_h.backward()
        he_l.apply_gradients(lr=0.5)

        out_d = dense(ids)
        loss_d = F.mse_loss(out_d, target)
        loss_d.backward()
        opt = paddle.optimizer.SGD(learning_rate=0.5, parameters=[dense.weight])
        opt.step()

        np.testing.assert_allclose(float(loss_h.numpy()), float(loss_d.numpy()), rtol=1e-6)
        np.testing.assert_allclose(
            he_l.table.gather(np.arange(V)), dense.weight.numpy(), rtol=1e-5, atol=1e-6
        )

    def test_adagrad_rule(self):
        V, D = 10, 4
        t = HostEmbeddingTable(V, D, optimizer="adagrad", seed=0)
        rows = t.gather(np.array([2, 3]))
        g = np.ones((2, D), np.float32)
        t.apply_update(np.array([2, 3]), g, lr=1.0)
        # accum = mean(g^2) = 1 → step = 1/sqrt(1) = 1
        np.testing.assert_allclose(
            t.gather(np.array([2, 3])), rows - 1.0, rtol=1e-5, atol=1e-5
        )


class TestNativeNumpyParity:
    """Bit-exact pins: the embed.cc kernels and the numpy fallback are two
    implementations of ONE semantics — any drift is a bug, not tolerance."""

    def _skip_no_native(self):
        if not _native_available():
            pytest.skip("native embed kernels not built")

    def _tables(self, optimizer, V=300, D=24, seed=11):
        a = HostEmbeddingTable(V, D, optimizer=optimizer, seed=seed)
        b = HostEmbeddingTable(V, D, optimizer=optimizer, seed=seed)
        return a, b

    def test_gather_bit_exact(self):
        self._skip_no_native()
        a, b = self._tables("sgd")
        ids = np.random.RandomState(0).randint(0, 300, 500).astype(np.int64)
        flags.set_flags({"FLAGS_host_emb_native": True})
        ra = a.gather(ids)
        flags.set_flags({"FLAGS_host_emb_native": False})
        rb = b.gather(ids)
        assert (ra == rb).all()

    @pytest.mark.parametrize("optimizer", ["sgd", "adagrad"])
    def test_update_bit_exact(self, optimizer):
        self._skip_no_native()
        rng = np.random.RandomState(1)
        a, b = self._tables(optimizer)
        uniq = np.unique(rng.randint(0, 300, 200)).astype(np.int64)
        a.gather(uniq), b.gather(uniq)  # init rows identically
        for step in range(3):
            g = rng.randn(uniq.size, 24).astype(np.float32)
            flags.set_flags({"FLAGS_host_emb_native": True})
            a.apply_update(uniq, g, lr=0.3)
            flags.set_flags({"FLAGS_host_emb_native": False})
            b.apply_update(uniq, g, lr=0.3)
        assert (a.table == b.table).all()
        if optimizer == "adagrad":
            assert (a._accum == b._accum).all()

    def test_duplicate_id_merge_bit_exact(self):
        self._skip_no_native()
        rng = np.random.RandomState(2)
        ids = [rng.randint(0, 64, 40).astype(np.int64) for _ in range(3)]
        grads = [rng.randn(40, 8).astype(np.float32) for _ in range(3)]
        flags.set_flags({"FLAGS_host_emb_native": True})
        ua, ga = he._merge_sparse_grads(ids, grads, 8)
        flags.set_flags({"FLAGS_host_emb_native": False})
        ub, gb = he._merge_sparse_grads(ids, grads, 8)
        assert (ua == ub).all()
        # duplicates merged by in-order float32 sums on both sides
        np.testing.assert_array_equal(ga, gb)

    def test_unique_matches_numpy(self):
        self._skip_no_native()
        ids = np.random.RandomState(3).randint(0, 50, 400).astype(np.int64)
        flags.set_flags({"FLAGS_host_emb_native": True})
        ua, ia = he._unique(ids)
        un, inn = np.unique(ids, return_inverse=True)
        assert (ua == un).all() and (ia == inn.ravel()).all()

    def test_negative_id_raises_not_faults(self):
        self._skip_no_native()
        flags.set_flags({"FLAGS_host_emb_native": True})
        t = HostEmbeddingTable(10, 4)
        with pytest.raises(IndexError):
            he._unique(np.array([1, -2, 3], np.int64))
        with pytest.raises((IndexError, Exception)):
            t.gather(np.array([2, 99], np.int64))  # out of range

    def test_full_train_loop_bit_exact_native_vs_fallback(self):
        """The acceptance pin: the whole layer loop (forward, backward,
        coalesced push) lands identical tables with native on and off."""

        def run():
            emb = HostEmbedding(96, 12, seed=5)
            rng = np.random.RandomState(9)
            losses = []
            for _ in range(4):
                ids = rng.randint(0, 96, (4, 6))
                out = emb(paddle.to_tensor(ids))
                loss = paddle.sum(out * out)
                loss.backward()
                losses.append(float(loss.numpy()))
                emb.apply_gradients(lr=0.1)
            return losses, emb.table.gather(np.arange(96))

        if not _native_available():
            pytest.skip("native embed kernels not built")
        flags.set_flags({"FLAGS_host_emb_native": True})
        l_nat, t_nat = run()
        flags.set_flags({"FLAGS_host_emb_native": False})
        l_np, t_np = run()
        assert l_nat == l_np
        assert (t_nat == t_np).all()


class TestHotRowCache:
    def _run_sgd(self, cache_rows, scatter=False):
        flags.set_flags({"FLAGS_host_emb_cache_min_count": 1})
        emb = HostEmbedding(64, 8, seed=2, cache_rows=cache_rows)
        if scatter and emb.cache is not None:
            # force the Adagrad-style scatter path (per-pack leaves +
            # merged scatter update) instead of the dense-leaf default
            emb.cache.dense = False
            emb.cache.rows_t = None
        rng = np.random.RandomState(3)
        for _ in range(5):
            ids = (rng.zipf(1.5, 32) % 64).astype(np.int64).reshape(4, 8)
            out = emb(paddle.to_tensor(ids))
            paddle.sum(out * out).backward()
            emb.apply_gradients(lr=0.05)
        return emb

    def test_sgd_scatter_coherence_bit_exact(self):
        """The scatter cache path merges grads in np.add.at order and
        applies the same IEEE ops as the host rule — bit-exact through
        update, flush and evict."""
        ref = self._run_sgd(0)
        cached = self._run_sgd(16, scatter=True)
        assert cached.cache is not None and cached.cache.hits > 0
        cached.sync()
        assert (ref.table.gather(np.arange(64)) ==
                cached.table.gather(np.arange(64))).all()
        occ = np.nonzero(cached.cache._slot_ids >= 0)[0]
        cached.cache.evict(occ)
        assert cached.cache.stats()["occupied_rows"] == 0
        assert (ref.table.gather(np.arange(64)) ==
                cached.table.gather(np.arange(64))).all()

    def test_sgd_dense_coherence_after_update_and_evict(self):
        """The dense-leaf default accumulates hot grads on the device
        buffer (XLA scatter-add order), so it matches the host path to
        summation-order rounding — and stays coherent through flush and
        evict."""
        ref = self._run_sgd(0)
        cached = self._run_sgd(16)
        assert cached.cache is not None and cached.cache.hits > 0
        cached.sync()
        np.testing.assert_allclose(cached.table.gather(np.arange(64)),
                                   ref.table.gather(np.arange(64)),
                                   rtol=2e-5, atol=1e-8)
        occ = np.nonzero(cached.cache._slot_ids >= 0)[0]
        cached.cache.evict(occ)
        assert cached.cache.stats()["occupied_rows"] == 0
        np.testing.assert_allclose(cached.table.gather(np.arange(64)),
                                   ref.table.gather(np.arange(64)),
                                   rtol=2e-5, atol=1e-8)

    def test_adagrad_coherence(self):
        def run(cache_rows):
            flags.set_flags({"FLAGS_host_emb_cache_min_count": 1})
            emb = HostEmbedding(48, 8, seed=4, optimizer="adagrad",
                                cache_rows=cache_rows)
            rng = np.random.RandomState(5)
            for _ in range(4):
                ids = (rng.zipf(1.5, 24) % 48).astype(np.int64).reshape(3, 8)
                out = emb(paddle.to_tensor(ids))
                paddle.sum(out * out).backward()
                emb.apply_gradients(lr=0.05)
            emb.sync()
            return emb.table.gather(np.arange(48)), np.asarray(emb.table._accum)

        t_ref, a_ref = run(0)
        t_c, a_c = run(12)
        # device mean vs sequential host sum: reduction-order rounding only
        np.testing.assert_allclose(t_c, t_ref, rtol=2e-5, atol=2e-7)
        np.testing.assert_allclose(a_c, a_ref, rtol=2e-5, atol=2e-7)

    def test_admission_is_frequency_gated(self):
        flags.set_flags({"FLAGS_host_emb_cache_min_count": 3})
        emb = HostEmbedding(64, 4, seed=1, cache_rows=8)
        ids = np.array([[1, 2, 3, 4]], np.int64)
        for step in range(4):
            out = emb(paddle.to_tensor(ids))
            paddle.sum(out * out).backward()
            emb.apply_gradients(lr=0.01)
            if step < 2:  # below min_count: nothing admitted yet
                assert emb.cache.stats()["occupied_rows"] == 0
        assert emb.cache.stats()["occupied_rows"] == 4
        # admitted rows now hit
        emb(paddle.to_tensor(ids))
        assert emb.cache.hits >= 4

    def test_pressure_shrink_halves_capacity_and_writes_back(self):
        from paddle_tpu.fault import memory as fmem

        flags.set_flags({"FLAGS_host_emb_cache_min_count": 1})
        emb = HostEmbedding(64, 8, seed=2, cache_rows=16)
        rng = np.random.RandomState(3)
        ids = np.arange(12, dtype=np.int64).reshape(2, 6)
        for _ in range(3):
            out = emb(paddle.to_tensor(ids))
            paddle.sum(out * out).backward()
            emb.apply_gradients(lr=0.05)
        ref = emb.table  # host table handle
        before = emb.cache.stats()["occupied_rows"]
        assert before > 0
        # the registered free_pressure handler requests a shrink...
        res = fmem.free_pressure("test")
        name = next(k for k in res["handlers"] if k.startswith("host_emb_cache"))
        assert res["handlers"][name]["requested"]
        # ...applied at the next touch, halving capacity with write-back
        out = emb(paddle.to_tensor(ids))
        paddle.sum(out * out).backward()
        emb.apply_gradients(lr=0.05)
        assert emb.cache.capacity == 8
        # training continues coherently vs a no-cache replay (dense-leaf
        # mode: equal to summation-order rounding)
        emb.sync()
        emb2 = HostEmbedding(64, 8, seed=2)
        for _ in range(4):
            out = emb2(paddle.to_tensor(ids))
            paddle.sum(out * out).backward()
            emb2.apply_gradients(lr=0.05)
        np.testing.assert_allclose(emb.table.gather(np.arange(64)),
                                   emb2.table.gather(np.arange(64)),
                                   rtol=2e-5, atol=1e-8)

    def test_cache_refused_on_sharded_table(self):
        from paddle_tpu.incubate.host_embedding import ShardedHostEmbeddingTable

        t = ShardedHostEmbeddingTable(32, 4, store=None, rank=0, world_size=2)
        emb = HostEmbedding(32, 4, table=t, cache_rows=8)
        assert emb.cache is None


class TestPipelinedPull:
    def test_prefetch_ordering_two_ahead(self):
        emb = HostEmbedding(64, 8, seed=2)
        rng = np.random.RandomState(0)
        b1, b2 = rng.randint(0, 64, (2, 3, 4)).astype(np.int64)
        ref = HostEmbedding(64, 8, seed=2)
        r1 = ref(paddle.to_tensor(b1)).numpy()
        r2 = ref(paddle.to_tensor(b2)).numpy()
        c0 = profiler.counters().get("host_emb_prefetch_hits", 0)
        emb.prefetch(b1)
        emb.prefetch(b2)
        np.testing.assert_allclose(emb(paddle.to_tensor(b1)).numpy(), r1)
        np.testing.assert_allclose(emb(paddle.to_tensor(b2)).numpy(), r2)
        assert profiler.counters().get("host_emb_prefetch_hits", 0) == c0 + 2

    def test_skipped_prefetch_dropped_matching_consumed(self):
        emb = HostEmbedding(64, 8, seed=2)
        b1 = np.array([[1, 2, 3]], np.int64)
        b2 = np.array([[4, 5, 6]], np.int64)
        emb.prefetch(b1)
        emb.prefetch(b2)
        d0 = profiler.counters().get("host_emb_prefetch_drops", 0)
        emb(paddle.to_tensor(b2))  # skips b1's pack
        assert profiler.counters().get("host_emb_prefetch_drops", 0) == d0 + 1
        assert emb._slots == []

    def test_push_patches_staged_pack(self):
        """A prefetch staged BEFORE a push must serve post-push values —
        frequent ids recur batch to batch, so this is the common case."""
        ids = np.array([[7, 8, 9]], np.int64)
        emb = HostEmbedding(32, 4, seed=6)
        out = emb(paddle.to_tensor(ids))
        paddle.sum(out * out).backward()
        emb.prefetch(ids)          # staged with PRE-push rows
        emb.sync()                 # make sure it's staged, not queued
        emb.apply_gradients(0.25)  # inline push patches the staged pack
        got = emb(paddle.to_tensor(ids)).numpy()
        ref = HostEmbedding(32, 4, seed=6)
        r = ref(paddle.to_tensor(ids))
        paddle.sum(r * r).backward()
        ref.apply_gradients(0.25)
        np.testing.assert_array_equal(got, ref(paddle.to_tensor(ids)).numpy())

    def test_async_push_parity_and_ordering(self):
        def run(async_push, prefetch):
            flags.set_flags({"FLAGS_host_emb_async_push": async_push})
            emb = HostEmbedding(128, 8, seed=3)
            rng = np.random.RandomState(7)
            batches = [(rng.zipf(1.4, 48) % 128).astype(np.int64).reshape(6, 8)
                       for _ in range(5)]
            losses = []
            for k, ids in enumerate(batches):
                if prefetch and k + 1 < len(batches):
                    emb.prefetch(batches[k + 1])
                out = emb(paddle.to_tensor(ids))
                loss = paddle.sum(out * out)
                loss.backward()
                losses.append(float(loss.numpy()))
                emb.apply_gradients(lr=0.05)
            emb.sync()
            return losses, emb.table.gather(np.arange(128))

        l_ref, t_ref = run(False, False)
        l_async, t_async = run(True, True)
        assert l_ref == l_async
        assert (t_ref == t_async).all()

    def test_prefetch_iter_pipelines_batches(self):
        emb = HostEmbedding(64, 8, seed=2)
        rng = np.random.RandomState(1)
        batches = [rng.randint(0, 64, (2, 4)).astype(np.int64) for _ in range(4)]
        ref = HostEmbedding(64, 8, seed=2)
        c0 = profiler.counters().get("host_emb_prefetch_hits", 0)
        outs = [emb(paddle.to_tensor(b)).numpy() for b in emb.prefetch_iter(batches)]
        refs = [ref(paddle.to_tensor(b)).numpy() for b in batches]
        for a, b in zip(outs, refs):
            np.testing.assert_allclose(a, b)
        assert profiler.counters().get("host_emb_prefetch_hits", 0) >= c0 + 3

    def test_abandoned_layer_releases_worker_thread(self):
        emb = HostEmbedding(64, 8, seed=2)
        emb.prefetch(np.array([[1, 2]], np.int64))
        emb.sync()
        th = emb._worker._thread
        assert th.is_alive()
        del emb
        gc.collect()
        th.join(timeout=10)
        assert not th.is_alive(), "PS worker thread not released on GC"

    def test_worker_error_surfaces_at_caller(self):
        flags.set_flags({"FLAGS_host_emb_async_push": True})
        emb = HostEmbedding(32, 4, seed=1)
        out = emb(paddle.to_tensor(np.array([[1, 2]], np.int64)))
        paddle.sum(out * out).backward()
        # sabotage the table so the background apply fails
        emb.table.apply_update = None
        emb.apply_gradients(lr=0.1)
        with pytest.raises(RuntimeError, match="PS worker"):
            emb.sync()


class TestInertTripwire:
    def test_defaults_no_threads_no_cache(self):
        n0 = threading.active_count()
        emb = HostEmbedding(64, 8, seed=1)
        ids = paddle.to_tensor(np.array([[1, 2, 3]], np.int64))
        out = emb(ids)
        paddle.sum(out * out).backward()
        emb.apply_gradients(lr=0.1)
        assert emb.cache is None
        assert emb._worker is None
        assert threading.active_count() == n0

    def test_native_off_never_touches_kernels(self, monkeypatch):
        """FLAGS_host_emb_native=0 + cache/prefetch off ⇒ the native entry
        points are NEVER reached (exploded here), no worker thread exists,
        and the loop still lands the exact fallback numbers."""
        flags.set_flags({"FLAGS_host_emb_native": False})

        def boom(*a, **k):
            raise AssertionError("native kernel touched with FLAGS_host_emb_native=0")

        # the flag probe in _native_ops IS the documented disabled-path cost;
        # what must never run are the kernel entry points themselves
        from paddle_tpu.core import native

        L = native.lib()
        if L is not None:
            for sym in ("pte_unique", "pte_gather_f32", "pte_sgd_f32",
                        "pte_adagrad_f32", "pte_merge_f32"):
                if hasattr(L, sym):
                    monkeypatch.setattr(L, sym, boom, raising=False)
        n0 = threading.active_count()
        emb = HostEmbedding(64, 8, seed=1)
        rng = np.random.RandomState(0)
        for _ in range(2):
            ids = paddle.to_tensor(rng.randint(0, 64, (2, 3)))
            out = emb(ids)
            paddle.sum(out * out).backward()
            emb.apply_gradients(lr=0.1)
        assert emb._worker is None and emb.cache is None
        assert threading.active_count() == n0


class TestPhysicalSizeFallback:
    def test_fallback_accounts_initialized_rows(self, tmp_path, monkeypatch):
        # force the "st_blocks can't see holes" branch regardless of host fs
        monkeypatch.setattr(he, "_fs_sparse_probe", {str(tmp_path): False})
        t = HostEmbeddingTable(10_000, 64, path=str(tmp_path / "t.npy"))
        base = t.state_nbytes_physical()
        assert base <= 8192  # header page only
        t.gather(np.array([1, 2, 3], np.int64))
        grown = t.state_nbytes_physical()
        assert grown == base + 0 + 3 * 64 * 4 or grown == 3 * 64 * 4 + 4096
        assert grown < 10_000 * 64 * 4 // 100

    def test_probe_detects_this_fs(self, tmp_path):
        # whichever branch the probe picks, the number must stay sane on a
        # freshly-created lazily-initialized table
        t = HostEmbeddingTable(100_000, 32, path=str(tmp_path / "t.npy"))
        t.gather(np.arange(50, dtype=np.int64))
        phys = t.state_nbytes_physical()
        logical = 100_000 * 32 * 4
        assert phys < logical // 10, f"physical {phys} not sparse vs {logical}"


class TestGiantLogicalTable:
    def test_20gb_logical_table_trains_on_one_chip(self, tmp_path):
        # 5,242,880 rows x 1024 dims x f32 = 20 GiB LOGICAL; the memmap file
        # is sparse so only touched rows take physical pages (the reference's
        # ssd_sparse_table capability: table >> device memory). Runs
        # EVERYWHERE now: state_nbytes_physical() falls back to
        # initialized-row accounting where st_blocks can't see holes
        # (overlay/tmpfs CI mounts) instead of skipping the whole test.
        V, D = 5_242_880, 1024
        path = str(tmp_path / "table.npy")
        he_l = HostEmbedding(V, D, path=path, optimizer="sgd", seed=1)
        assert he_l.table.table.shape == (V, D)
        logical = V * D * 4
        assert logical >= 20 * 1024**3

        rng = np.random.RandomState(0)
        ids_np = rng.randint(0, V, (4, 64)).astype(np.int64)
        ids = paddle.to_tensor(ids_np)
        he_l.train()
        out = he_l(ids)
        assert out.shape == [4, 64, D]
        loss = (out * out).mean()
        loss.backward()
        before = he_l.table.gather(np.unique(ids_np)[:4]).copy()
        he_l.apply_gradients(lr=0.1)
        after = he_l.table.gather(np.unique(ids_np)[:4])
        assert np.abs(before - after).max() > 0  # rows actually updated

        physical = he_l.table.state_nbytes_physical()
        assert physical < 1024**3, f"file not sparse: {physical/1e9:.1f} GB resident"
