"""Host-offloaded giant embedding (incubate/host_embedding.py) — the
TPU-first stand-in for the reference brpc PS embedding tables
(memory_sparse_table.cc / ssd_sparse_table.cc / the_one_ps.py:606)."""
import os

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu.incubate.host_embedding import HostEmbedding, HostEmbeddingTable


class TestParityWithInHBMEmbedding:
    def test_forward_and_sgd_step_match_dense_embedding(self):
        V, D = 50, 8
        he = HostEmbedding(V, D, optimizer="sgd", seed=3)
        dense = nn.Embedding(V, D)
        # same initial rows
        ids_np = np.array([[1, 4, 4], [7, 1, 9]], np.int64)
        _ = he(paddle.to_tensor(ids_np))  # touch → init rows
        he._pending = []
        full = he.table.gather(np.arange(V))
        dense.weight.set_value(paddle.to_tensor(full.astype(np.float32)))

        ids = paddle.to_tensor(ids_np)
        target = paddle.to_tensor(np.random.RandomState(0).randn(2, 3, 8).astype(np.float32))

        he.train()
        out_h = he(ids)
        loss_h = F.mse_loss(out_h, target)
        loss_h.backward()
        he.apply_gradients(lr=0.5)

        out_d = dense(ids)
        loss_d = F.mse_loss(out_d, target)
        loss_d.backward()
        opt = paddle.optimizer.SGD(learning_rate=0.5, parameters=[dense.weight])
        opt.step()

        np.testing.assert_allclose(float(loss_h.numpy()), float(loss_d.numpy()), rtol=1e-6)
        np.testing.assert_allclose(
            he.table.gather(np.arange(V)), dense.weight.numpy(), rtol=1e-5, atol=1e-6
        )

    def test_adagrad_rule(self):
        V, D = 10, 4
        t = HostEmbeddingTable(V, D, optimizer="adagrad", seed=0)
        rows = t.gather(np.array([2, 3]))
        g = np.ones((2, D), np.float32)
        t.apply_update(np.array([2, 3]), g, lr=1.0)
        # accum = mean(g^2) = 1 → step = 1/sqrt(1) = 1
        np.testing.assert_allclose(
            t.gather(np.array([2, 3])), rows - 1.0, rtol=1e-5, atol=1e-5
        )


def _fs_keeps_memmap_holes_sparse(probe_dir="/tmp") -> bool:
    """Whether this filesystem materializes np.memmap holes lazily. Overlay/
    tmpfs-backed CI containers allocate every page at first write-through of
    the mapping, so a 20 GiB logical table becomes 20+ GiB RESIDENT — an
    environment limit of the test host, not a HostEmbedding regression."""
    import tempfile

    try:
        with tempfile.NamedTemporaryFile(dir=probe_dir) as f:
            f.truncate(64 * 1024 * 1024)  # 64 MiB hole
            m = np.memmap(f.name, dtype=np.float32, mode="r+",
                          shape=(16, 1024))
            m[0] = 1.0  # touch ONE page
            m.flush()
            del m
            blocks = os.stat(f.name).st_blocks * 512
            return blocks < 8 * 1024 * 1024  # holes stayed holes
    except Exception:
        return False


class TestGiantLogicalTable:
    @pytest.mark.skipif(
        not _fs_keeps_memmap_holes_sparse(),
        reason="environment limit: the test filesystem materializes memmap "
        "holes eagerly (overlay/tmpfs), so the 20 GiB logical table becomes "
        "fully resident — known CPU-CI env failure, not a regression",
    )
    def test_20gb_logical_table_trains_on_one_chip(self, tmp_path):
        # 5,242,880 rows x 1024 dims x f32 = 20 GiB LOGICAL; the memmap file
        # is sparse so only touched rows take physical pages (the reference's
        # ssd_sparse_table capability: table >> device memory)
        V, D = 5_242_880, 1024
        path = str(tmp_path / "table.npy")
        he = HostEmbedding(V, D, path=path, optimizer="sgd", seed=1)
        assert he.table.table.shape == (V, D)
        logical = V * D * 4
        assert logical >= 20 * 1024**3

        rng = np.random.RandomState(0)
        ids_np = rng.randint(0, V, (4, 64)).astype(np.int64)
        ids = paddle.to_tensor(ids_np)
        he.train()
        out = he(ids)
        assert out.shape == [4, 64, D]
        loss = (out * out).mean()
        loss.backward()
        before = he.table.gather(np.unique(ids_np)[:4]).copy()
        he.apply_gradients(lr=0.1)
        after = he.table.gather(np.unique(ids_np)[:4])
        assert np.abs(before - after).max() > 0  # rows actually updated

        physical = he.table.state_nbytes_physical()
        assert physical < 1024**3, f"file not sparse: {physical/1e9:.1f} GB resident"
