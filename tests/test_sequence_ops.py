"""Sequence-op family (masked-ragged LoD equivalents) + one-shot metric ops.

Reference methodology: unittests/sequence/test_sequence_*.py build LoD
tensors and compare against python loops; here the padded+lengths pair is
checked against the same per-row numpy loops.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.ops import sequence as seq
from paddle_tpu.ops import metrics_ops as mops


RNG = np.random.RandomState(3)


def ragged(b=3, t=6, d=2):
    lens = RNG.randint(1, t + 1, (b,))
    x = RNG.randn(b, t, d).astype(np.float32)
    for i, l in enumerate(lens):
        x[i, l:] = 0.0
    return x, lens


def T(a):
    return paddle.to_tensor(a)


class TestSequenceOps:
    def test_pad_unpad_roundtrip(self):
        x, lens = ragged()
        flat_rows = np.concatenate([x[i, :l] for i, l in enumerate(lens)], 0)
        flat = np.zeros((x.shape[0] * x.shape[1], x.shape[2]), np.float32)
        flat[: flat_rows.shape[0]] = flat_rows
        padded = seq.sequence_pad(T(flat), T(lens), max_len=x.shape[1])
        np.testing.assert_allclose(np.asarray(padded._data), x, atol=1e-6)
        unp = seq.sequence_unpad(T(x), T(lens))
        np.testing.assert_allclose(np.asarray(unp._data), flat, atol=1e-6)

    def test_softmax_masked(self):
        x, lens = ragged()
        out = np.asarray(seq.sequence_softmax(T(x), T(lens))._data)
        for i, l in enumerate(lens):
            e = np.exp(x[i, :l] - x[i, :l].max(0))
            np.testing.assert_allclose(out[i, :l], e / e.sum(0), atol=1e-5)
            assert np.all(out[i, l:] == 0)

    @pytest.mark.parametrize("pt", ["SUM", "AVERAGE", "SQRT", "MAX", "MIN", "LAST", "FIRST"])
    def test_pool(self, pt):
        x, lens = ragged()
        out = np.asarray(seq.sequence_pool(T(x), T(lens), pt)._data)
        for i, l in enumerate(lens):
            v = x[i, :l]
            want = {
                "SUM": v.sum(0), "AVERAGE": v.mean(0),
                "SQRT": v.sum(0) / np.sqrt(l), "MAX": v.max(0),
                "MIN": v.min(0), "LAST": v[-1], "FIRST": v[0],
            }[pt]
            np.testing.assert_allclose(out[i], want, atol=1e-5)

    def test_reverse(self):
        x, lens = ragged()
        out = np.asarray(seq.sequence_reverse(T(x), T(lens))._data)
        for i, l in enumerate(lens):
            np.testing.assert_allclose(out[i, :l], x[i, :l][::-1], atol=1e-6)
            np.testing.assert_allclose(out[i, l:], x[i, l:], atol=1e-6)

    def test_expand_and_expand_as(self):
        lens = np.array([2, 4, 1])
        x = RNG.randn(3, 5).astype(np.float32)
        out = np.asarray(seq.sequence_expand(T(x), T(lens), max_len=4)._data)
        for i, l in enumerate(lens):
            for t in range(4):
                want = x[i] if t < l else np.zeros_like(x[i])
                np.testing.assert_allclose(out[i, t], want, atol=1e-6)
        y = np.zeros((3, 4, 5), np.float32)
        out2 = np.asarray(seq.sequence_expand_as(T(x), T(y), T(lens))._data)
        np.testing.assert_allclose(out2, out, atol=1e-6)

    def test_concat(self):
        x, lx = ragged()
        y, ly = ragged()
        vals, nl = seq.sequence_concat(T(x), T(lx), T(y), T(ly))
        vals, nl = np.asarray(vals._data), np.asarray(nl._data)
        for i in range(3):
            want = np.concatenate([x[i, :lx[i]], y[i, :ly[i]]], 0)
            assert nl[i] == lx[i] + ly[i]
            np.testing.assert_allclose(vals[i, :nl[i]], want, atol=1e-6)
            assert np.all(vals[i, nl[i]:] == 0)

    def test_slice(self):
        x, lens = ragged(t=8)
        off = np.minimum(np.array([1, 2, 0]), np.maximum(lens - 1, 0))
        sl = np.array([2, 3, 1])
        vals, nl = seq.sequence_slice(T(x), T(lens), T(off), T(sl))
        vals, nl = np.asarray(vals._data), np.asarray(nl._data)
        for i in range(3):
            want_len = min(sl[i], max(lens[i] - off[i], 0))
            assert nl[i] == want_len
            np.testing.assert_allclose(
                vals[i, :want_len], x[i, off[i]:off[i] + want_len], atol=1e-6)

    def test_erase(self):
        ids = np.array([[3, 5, 3, 1, 0], [2, 2, 2, 9, 4]])
        lens = np.array([4, 3])
        vals, nl = seq.sequence_erase(T(ids), T(lens), tokens=[3, 2])
        vals, nl = np.asarray(vals._data), np.asarray(nl._data)
        assert list(nl) == [2, 0]
        assert list(vals[0, :2]) == [5, 1]

    def test_enumerate(self):
        ids = np.array([[1, 2, 3, 4], [5, 6, 0, 0]])
        lens = np.array([4, 2])
        out = np.asarray(seq.sequence_enumerate(T(ids), T(lens), win_size=2, pad_value=0)._data)
        assert list(out[0, 0]) == [1, 2]
        assert list(out[0, 3]) == [4, 0]  # window walks off the row
        assert list(out[1, 1]) == [6, 0]

    def test_reshape(self):
        x = RNG.randn(2, 4, 6).astype(np.float32)
        lens = np.array([2, 4])
        vals, nl = seq.sequence_reshape(T(x), T(lens), new_dim=3)
        assert list(np.asarray(nl._data)) == [4, 8]
        assert np.asarray(vals._data).shape == (2, 8, 3)

    def test_scatter(self):
        x = np.zeros((2, 5), np.float32)
        idx = np.array([[0, 2], [1, 1]])
        upd = np.array([[1.0, 2.0], [3.0, 4.0]], np.float32)
        ulen = np.array([2, 1])
        out = np.asarray(seq.sequence_scatter(T(x), T(idx), T(upd), T(ulen))._data)
        assert out[0, 0] == 1.0 and out[0, 2] == 2.0
        assert out[1, 1] == 3.0  # second update masked out by ulen=1

    def test_topk_avg_pooling(self):
        x = np.array([[5.0, 1.0, 3.0, 0.0], [2.0, 2.0, 0.0, 0.0]], np.float32)
        lens = np.array([3, 2])
        out = np.asarray(seq.sequence_topk_avg_pooling(T(x), T(lens), topks=[1, 2])._data)
        np.testing.assert_allclose(out[0], [5.0, 4.0], atol=1e-5)
        np.testing.assert_allclose(out[1], [2.0, 2.0], atol=1e-5)

    def test_conv(self):
        x, lens = ragged(d=3)
        w = RNG.randn(9, 4).astype(np.float32)  # ctx=3
        out = np.asarray(seq.sequence_conv(T(x), T(lens), T(w))._data)
        b, t, d = x.shape
        for i, l in enumerate(lens):
            xm = x[i].copy(); xm[l:] = 0
            for tt in range(l):
                ctx = []
                for c in range(3):
                    p = tt + (-1 + c)
                    ctx.append(xm[p] if 0 <= p < l else np.zeros(d, np.float32))
                want = np.concatenate(ctx) @ w
                np.testing.assert_allclose(out[i, tt], want, atol=1e-4)
            assert np.all(out[i, l:] == 0)

    def test_grad_through_pool(self):
        x, lens = ragged()
        xt = T(x)
        xt.stop_gradient = False
        loss = seq.sequence_pool(xt, T(lens), "AVERAGE").sum()
        loss.backward()
        g = np.asarray(xt.grad._data)
        for i, l in enumerate(lens):
            np.testing.assert_allclose(g[i, :l], np.full((l, x.shape[2]), 1.0 / l), atol=1e-5)
            assert np.all(g[i, l:] == 0)


class TestMetricOps:
    def test_auc_rank(self):
        pred = np.array([0.1, 0.9, 0.4, 0.8, 0.3], np.float32)
        label = np.array([0, 1, 0, 1, 1])
        got = float(mops.auc(T(pred), T(label))._data)
        # pairwise reference
        pos = pred[label == 1]; neg = pred[label == 0]
        want = np.mean([(p > n) + 0.5 * (p == n) for p in pos for n in neg])
        assert abs(got - want) < 1e-6

    def test_auc_large_n(self):
        """ADVICE r5: the rank statistic must be O(N log N) (searchsorted),
        not two N x N comparison matrices (~10 GB at N~1e5). Random scores
        at N=2e5 must run fast and land near 0.5; a separable slab must
        score ~1.0."""
        rng = np.random.RandomState(0)
        n = 200_000
        pred = rng.rand(n).astype(np.float32)
        label = (rng.rand(n) < 0.3).astype(np.int64)
        got = float(mops.auc(T(pred), T(label))._data)
        assert 0.49 < got < 0.51, got
        sep = float(mops.auc(T(np.where(label > 0, pred + 2.0, pred).astype(np.float32)),
                             T(label))._data)
        assert sep > 0.999, sep
        # parity with the pairwise definition on a slice (ties included)
        small = 400
        p_s = np.round(pred[:small], 2).astype(np.float32)  # force ties
        y_s = label[:small]
        got_s = float(mops.auc(T(p_s), T(y_s))._data)
        pos, neg = p_s[y_s == 1], p_s[y_s == 0]
        want = np.mean([(p > q) + 0.5 * (p == q) for p in pos for q in neg])
        assert abs(got_s - want) < 1e-5, (got_s, want)

    def test_edit_distance(self):
        hyp = np.array([[1, 2, 3, 0], [4, 4, 0, 0]])
        hl = np.array([3, 2])
        ref = np.array([[1, 3, 3, 5], [4, 0, 0, 0]])
        rl = np.array([4, 1])
        d = np.asarray(mops.edit_distance(T(hyp), T(hl), T(ref), T(rl), normalized=False)._data)
        assert d[0] == 2.0  # sub 2->3, insert 5
        assert d[1] == 1.0  # delete one 4
        dn = np.asarray(mops.edit_distance(T(hyp), T(hl), T(ref), T(rl))._data)
        np.testing.assert_allclose(dn, [2.0 / 4, 1.0], atol=1e-6)

    def test_mean_iou(self):
        pred = np.array([0, 0, 1, 1, 2])
        label = np.array([0, 1, 1, 1, 2])
        got = float(mops.mean_iou(T(pred), T(label), 3)._data)
        # class0: i1/u2, class1: i2/u3, class2: 1/1
        want = (0.5 + 2 / 3 + 1.0) / 3
        assert abs(got - want) < 1e-6

    def test_precision_recall(self):
        pred = np.array([0, 1, 1, 0])
        label = np.array([0, 1, 0, 0])
        p, r, f1 = mops.precision_recall(T(pred), T(label), 2)
        # class0: tp2 fp0 fn1 -> p=1, r=2/3; class1: tp1 fp1 fn0 -> p=.5, r=1
        assert abs(float(p._data) - 0.75) < 1e-6
        assert abs(float(r._data) - (2 / 3 + 1) / 2) < 1e-6
        assert float(f1._data) > 0

    def test_positive_negative_pair(self):
        score = np.array([0.8, 0.2, 0.5, 0.6], np.float32)
        label = np.array([1.0, 0.0, 0.0, 1.0], np.float32)
        qid = np.array([0, 0, 1, 1])
        pos, neg, neu = mops.positive_negative_pair(T(score), T(label), T(qid))
        assert int(pos._data) == 2 and int(neg._data) == 0 and int(neu._data) == 0
