"""1F1B pipeline schedule: parity, MEMORY DISCIPLINE, and a timed point.

Reference: ``fleet/meta_parallel/pipeline_parallel.py:80``
(forward_backward_pipeline) and ``framework/section_worker.cc:153``
(Run1F1B). The claim under test: the explicit 1F1B schedule's live
activation set is O(n_stages) while F-then-B (GPipe via reverse-AD through
the scan) stashes O(n_micro) — verified on the compiled HLO's temp-buffer
allocation, not by eyeballing the schedule.
"""
import time

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn


VOCAB, D, SEQ = 32, 64, 16
MEM_MB, MEM_SEQ = 8, 128


def build_pl(n_stages=4, n_blocks=6):
    from paddle_tpu.distributed.fleet.meta_parallel import LayerDesc, PipelineLayer

    class Embed(nn.Layer):
        def __init__(self):
            super().__init__()
            self.emb = nn.Embedding(VOCAB, D)

        def forward(self, ids):
            return self.emb(ids)

    class Block(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(D, D)

        def forward(self, x):
            return x + paddle.tanh(self.fc(x))

    class Head(nn.Layer):
        def __init__(self):
            super().__init__()
            self.proj = nn.Linear(D, VOCAB)

        def forward(self, x):
            return self.proj(x)

    ce = nn.CrossEntropyLoss()

    def loss_fn(logits, labels):
        return ce(logits.reshape([-1, VOCAB]), labels.reshape([-1]))

    descs = [LayerDesc(Embed)] + [LayerDesc(Block) for _ in range(n_blocks)] + [LayerDesc(Head)]
    return PipelineLayer(descs, num_stages=n_stages, loss_fn=loss_fn)


def _mesh(pp=4):
    import jax
    from jax.sharding import Mesh

    return Mesh(np.array(jax.devices()[:pp]), ("pp",))


def _make_step(schedule, n_micro, seed=3):
    from paddle_tpu.distributed.fleet.meta_parallel.pipeline_parallel import (
        PipelineTrainStep,
    )

    paddle.seed(seed)
    pl = build_pl()
    opt = paddle.optimizer.SGD(learning_rate=0.05, parameters=pl.parameters())
    return PipelineTrainStep(pl, opt, _mesh(), n_micro=n_micro, schedule=schedule), pl


def _data(n_micro, mb=2, seed=11):
    rng = np.random.RandomState(seed)
    b = n_micro * mb
    ids = rng.randint(0, VOCAB, (b, SEQ))
    labels = rng.randint(0, VOCAB, (b, SEQ))
    return paddle.to_tensor(ids), paddle.to_tensor(labels)


class Test1F1B:
    def test_1f1b_matches_fthenb_and_learns(self):
        ids, labels = _data(4)
        step_a, pl_a = _make_step("1F1B", 4, seed=3)
        step_b, pl_b = _make_step("F-then-B", 4, seed=3)
        la = [float(step_a(ids, labels).item()) for _ in range(3)]
        lb = [float(step_b(ids, labels).item()) for _ in range(3)]
        np.testing.assert_allclose(la, lb, rtol=2e-4, atol=1e-5)
        assert la[-1] < la[0]  # learns
        wa = np.asarray(pl_a.parameters()[0]._data)
        wb = np.asarray(pl_b.parameters()[0]._data)
        np.testing.assert_allclose(wa, wb, rtol=2e-4, atol=1e-5)

    def _peak_temp(self, schedule, n_micro):
        """Compiled-HLO temp allocation (bytes) of the pp=4 train step.

        Microbatches sized so the activation carrier dominates scratch
        (mb=8 x seq=128 x D=64 f32 = 256 KB per in-flight microbatch)."""
        import jax
        import jax.numpy as jnp
        from paddle_tpu.core import random as random_state

        step, pl = _make_step(schedule, n_micro)
        rng = np.random.RandomState(11)
        b = n_micro * MEM_MB
        ids = paddle.to_tensor(rng.randint(0, VOCAB, (b, MEM_SEQ)))
        labels = paddle.to_tensor(rng.randint(0, VOCAB, (b, MEM_SEQ)))
        ids_mb = ids._data.reshape((n_micro, MEM_MB) + ids._data.shape[1:])
        lbls_mb = labels._data.reshape((n_micro, MEM_MB) + labels._data.shape[1:])
        step._carrier = step._probe_carrier(ids_mb[0])
        build = step._build_1f1b if schedule == "1F1B" else step._build
        jitted = build()
        params = [p._data for p in step.params]
        opt_state = step.optimizer._functional_state(step.params)
        lowered = jitted.lower(
            params, opt_state, ids_mb, lbls_mb,
            jnp.asarray(0.05, jnp.float32), random_state.next_key(),
        )
        mem = lowered.compile().memory_analysis()
        return int(getattr(mem, "temp_size_in_bytes", 0))

    def test_1f1b_peak_memory_is_o_stages_not_o_micro(self):
        # quadruple n_micro: F-then-B's residual stack grows ~linearly with
        # it; 1F1B's stash is fixed at 2*n_stages carriers
        t1_small = self._peak_temp("1F1B", 8)
        t1_big = self._peak_temp("1F1B", 32)
        tg_small = self._peak_temp("F-then-B", 8)
        tg_big = self._peak_temp("F-then-B", 32)
        print(f"\ntemp bytes: 1F1B n_micro=8:{t1_small} 32:{t1_big}  "
              f"F-then-B 8:{tg_small} 32:{tg_big}")
        # GPipe grows materially with n_micro
        assert tg_big > tg_small * 2.0, (tg_small, tg_big)
        # 1F1B stays ~flat (input microbatch arrays grow, temps must not)
        assert t1_big < t1_small * 1.5, (t1_small, t1_big)
        # and at large n_micro 1F1B uses materially less scratch than GPipe
        assert t1_big < tg_big * 0.6, (t1_big, tg_big)

    def test_timed_point_pp4(self):
        """Timed 1F1B vs F-then-B at pp=4 on the CPU mesh (relative number —
        the schedules' compute content differs only in recompute policy)."""
        n_micro = 8
        ids, labels = _data(n_micro)
        results = {}
        for schedule in ("1F1B", "F-then-B"):
            step, _ = _make_step(schedule, n_micro)
            step(ids, labels)  # compile
            t0 = time.time()
            for _ in range(3):
                loss = step(ids, labels)
            float(loss.item())
            results[schedule] = 3 / (time.time() - t0)
        print(f"\npp=4 n_micro={n_micro} steps/s: {results}")
        # sanity only: both run; 1F1B must be within 3x of F-then-B
        assert results["1F1B"] > results["F-then-B"] / 3.0


def build_sqrt_pl(n_stages=2):
    """Pipeline whose middle block has an UNDEFINED derivative at 0:
    ``sqrt(|x|)`` — d/dx = sign(x)/(2 sqrt(|x|)) is 0 * inf = NaN at x=0.
    Warm-up/drain backward sub-ticks run the vjp on the zero-filled dummy
    carrier, so this stage produces NaN param cotangents on every invalid
    tick (ADVICE r5: arithmetic 0/1 masking turns them into 0*NaN = NaN)."""
    from paddle_tpu.distributed.fleet.meta_parallel import LayerDesc, PipelineLayer

    class Embed(nn.Layer):
        def __init__(self):
            super().__init__()
            self.emb = nn.Embedding(VOCAB, D)

        def forward(self, ids):
            return self.emb(ids)

    class Block(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(D, D)

        def forward(self, x):
            return x + paddle.tanh(self.fc(x))

    class SqrtBlock(nn.Layer):
        def __init__(self):
            super().__init__()
            # no bias: fc(0) == 0, so the zero dummy carrier hits sqrt's
            # singular point and d(sqrt|fc|)/dW = NaN flows into this
            # stage's param cotangents on invalid sub-ticks
            self.fc = nn.Linear(D, D, bias_attr=False)

        def forward(self, x):
            # real activations are a.s. nonzero -> finite grads
            return paddle.sqrt(paddle.abs(self.fc(x))) * 0.1 + x

    class Head(nn.Layer):
        def __init__(self):
            super().__init__()
            self.proj = nn.Linear(D, VOCAB)

        def forward(self, x):
            return self.proj(x)

    ce = nn.CrossEntropyLoss()

    def loss_fn(logits, labels):
        return ce(logits.reshape([-1, VOCAB]), labels.reshape([-1]))

    # 4 descs over 2 stages -> stage1 = [SqrtBlock, Head]: the sqrt stage
    # receives the inter-stage carrier (zeros on warm-up/drain sub-ticks)
    descs = [LayerDesc(Embed), LayerDesc(Block), LayerDesc(SqrtBlock),
             LayerDesc(Head)]
    return PipelineLayer(descs, num_stages=n_stages, loss_fn=loss_fn)


class Test1F1BNaNMasking:
    def test_nan_at_zero_stage_does_not_poison_grads(self):
        """Regression (ADVICE r5, pipeline_parallel.py:372): invalid
        backward sub-ticks must be masked per leaf with jnp.where, not by
        multiplying with a 0/1 scalar — sqrt'(0)=inf on the dummy carrier
        would otherwise poison the whole step's gradient accumulator."""
        from paddle_tpu.distributed.fleet.meta_parallel.pipeline_parallel import (
            PipelineTrainStep,
        )
        from paddle_tpu.jit import CompiledTrainStep

        n_micro = 4
        ids, labels = _data(n_micro, seed=13)

        # sequential reference (no dummy carrier ever exists, so no
        # singular vjp): same weights via same seed
        paddle.seed(5)
        m1 = build_sqrt_pl()
        o1 = paddle.optimizer.SGD(learning_rate=0.05, parameters=m1.parameters())
        lf = m1._loss_fn
        seq = CompiledTrainStep(m1, lambda m, x, y: lf(m(x), y), o1)
        seq_losses = [float(seq(ids, labels).item()) for _ in range(2)]
        assert all(np.isfinite(l) for l in seq_losses), seq_losses

        paddle.seed(5)
        pl = build_sqrt_pl()
        opt = paddle.optimizer.SGD(learning_rate=0.05, parameters=pl.parameters())
        step = PipelineTrainStep(pl, opt, _mesh(2), n_micro=n_micro,
                                 schedule="1F1B")
        ls = [float(step(ids, labels).item()) for _ in range(2)]
        assert all(np.isfinite(l) for l in ls), ls
        for p in pl.parameters():
            assert np.isfinite(np.asarray(p._data)).all(), p.name
        # the drain-tick NaNs masked correctly, the pipelined run must match
        # sequential training step-for-step
        np.testing.assert_allclose(ls, seq_losses, rtol=2e-4, atol=1e-5)
        np.testing.assert_allclose(
            np.asarray(pl.parameters()[0]._data),
            np.asarray(m1.parameters()[0]._data), rtol=2e-4, atol=1e-5,
        )
