"""LocalSGD + DGC comm-compression strategies (reference
fleet/meta_optimizers/{localsgd,dgc}_optimizer.py): k-step local training
with param averaging over the dp axis, and top-k error-feedback gradient
compression with momentum-factor masking."""
import numpy as np
import jax
import jax.numpy as jnp
from paddle_tpu.core.compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.distributed.fleet.meta_optimizers.localsgd_optimizer import (
    LocalSGDOptimizer,
)
from paddle_tpu.distributed.fleet.meta_optimizers.dgc_optimizer import (
    DGCMomentumOptimizer,
)


def _mesh(axes, shape):
    devs = np.array(jax.devices()[: int(np.prod(shape))]).reshape(shape)
    return Mesh(devs, axes)


class TestLocalSGD:
    def test_sync_params_averages_over_dp_axis(self):
        """sync_params inside a dp shard_map pmean-averages DIVERGED replica
        params — the inserted c_allreduce(param)/nranks of the reference."""
        mesh = _mesh(("dp",), (4,))
        paddle.seed(0)
        m = nn.Linear(4, 1)
        opt = LocalSGDOptimizer(
            paddle.optimizer.SGD(learning_rate=0.1, parameters=m.parameters()),
            k_steps=2, axis_name="dp",
        )

        def f(w_replica):
            saved = m.weight._data
            try:
                m.weight._data = w_replica  # per-replica diverged weights
                opt.sync_params()
                return m.weight._data
            finally:
                m.weight._data = saved

        w = np.random.RandomState(0).randn(4, 4, 1).astype(np.float32)
        sm = shard_map(
            f, mesh=mesh, in_specs=(P("dp"),), out_specs=P("dp"), check_vma=False,
        )
        out = np.asarray(jax.jit(sm)(w.reshape(16, 1))).reshape(4, 4, 1)
        mean = w.mean(axis=0)
        for r in range(4):
            np.testing.assert_allclose(out[r], mean, rtol=1e-5)

    def test_k_step_gating(self):
        """sync fires exactly every k_steps inner steps (local training
        between boundaries)."""
        paddle.seed(0)
        m = nn.Linear(4, 1)
        opt = LocalSGDOptimizer(
            paddle.optimizer.SGD(learning_rate=0.1, parameters=m.parameters()),
            k_steps=3,
        )
        syncs = []
        opt.sync_params = lambda: syncs.append(opt._local_steps)
        x = paddle.to_tensor(np.random.RandomState(0).randn(8, 4).astype(np.float32))
        y = paddle.to_tensor(np.random.RandomState(1).randn(8, 1).astype(np.float32))
        for _ in range(7):
            loss = paddle.mean((m(x) - y) ** 2)
            loss.backward()
            opt.step()
            opt.clear_grad()
        assert syncs == [3, 6], syncs

    def test_delegates_inner_api(self):
        m = nn.Linear(4, 2)
        inner = paddle.optimizer.SGD(learning_rate=0.5, parameters=m.parameters())
        opt = LocalSGDOptimizer(inner, k_steps=3)
        assert opt.get_lr() == 0.5
        st = opt.state_dict()
        assert "@local_steps" in st


class TestDGC:
    def _grad_step(self, opt, m, x, y):
        loss = paddle.mean((m(x) - y) ** 2)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return float(loss.numpy())

    def test_top_k_fraction_communicated(self):
        paddle.seed(0)
        m = nn.Linear(64, 32)  # 2048-elem weight
        opt = DGCMomentumOptimizer(
            learning_rate=0.05, momentum=0.9, parameters=m.parameters(),
            rampup_begin_step=0, sparsity=(0.99,),
        )
        x = paddle.to_tensor(np.random.RandomState(0).randn(8, 64).astype(np.float32))
        y = paddle.to_tensor(np.random.RandomState(1).randn(8, 32).astype(np.float32))
        self._grad_step(opt, m, x, y)
        # ~1% of elements applied
        assert 0.005 <= opt.last_comm_fraction <= 0.03, opt.last_comm_fraction

    def test_error_feedback_accumulates_and_releases(self):
        """Suppressed gradient mass stays in v and is eventually applied —
        over enough steps DGC training approaches dense momentum training."""
        paddle.seed(1)

        def train(opt_factory, steps=60):
            paddle.seed(1)
            m = nn.Linear(8, 1)
            opt = opt_factory(m)
            rng = np.random.RandomState(2)
            w_true = rng.randn(8, 1).astype(np.float32)
            losses = []
            for i in range(steps):
                x = paddle.to_tensor(rng.randn(16, 8).astype(np.float32))
                y = paddle.to_tensor((np.asarray(x.numpy()) @ w_true).astype(np.float32))
                losses.append(self._grad_step(opt, m, x, y))
            return losses

        dgc_losses = train(
            lambda m: DGCMomentumOptimizer(
                learning_rate=0.02, momentum=0.9, parameters=m.parameters(),
                sparsity=(0.75,),
            )
        )
        assert dgc_losses[-1] < 0.25 * dgc_losses[0], (dgc_losses[0], dgc_losses[-1])

    def test_rampup_trains_dense(self):
        paddle.seed(3)
        m = nn.Linear(16, 4)
        opt = DGCMomentumOptimizer(
            learning_rate=0.05, momentum=0.9, parameters=m.parameters(),
            rampup_begin_step=100, sparsity=(0.999,),
        )
        x = paddle.to_tensor(np.random.RandomState(0).randn(4, 16).astype(np.float32))
        y = paddle.to_tensor(np.random.RandomState(1).randn(4, 4).astype(np.float32))
        self._grad_step(opt, m, x, y)
        assert opt.last_comm_fraction == 1.0  # dense during ramp-up


class TestFleetWiring:
    def test_strategy_flags_wrap_optimizer(self):
        from paddle_tpu.distributed import fleet

        strategy = fleet.DistributedStrategy()
        strategy.localsgd = True
        strategy.localsgd_configs = {"k_steps": 7}
        fleet.init(is_collective=True, strategy=strategy)
        m = nn.Linear(4, 2)
        opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=m.parameters())
        dopt = fleet.distributed_optimizer(opt, strategy=strategy)
        inner = dopt._inner_opt if hasattr(dopt, "_inner_opt") else dopt.inner_opt
        assert isinstance(inner, LocalSGDOptimizer)
        assert inner.k_steps == 7
