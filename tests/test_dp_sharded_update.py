"""Communication-optimized DP gradient sync: ZeRO-1 sharded weight update,
bucketed collectives, and quantized all-reduce.

Methodology per SURVEY.md §4: parity between the sharded path and the
replicated reference on the 8-device virtual CPU mesh — the same standard the
reference's TestDistBase applies to its multiprocess runs. Memory claims are
asserted with array-size accounting over the actual device shardings, and the
wire-byte claims with the plan's analytic counters (the quantities the driver
captures from the multichip harness).
"""
import numpy as np
import pytest
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu import profiler
from paddle_tpu.distributed.engine import HybridParallelEngine
from paddle_tpu.distributed.fleet.grad_buckets import build_bucket_plan

pytestmark = pytest.mark.multichip


def _mesh(n):
    return Mesh(np.asarray(jax.devices()[:n]), ("dp",))


def _flags(**kw):
    base = {
        "FLAGS_shard_weight_update": True,
        "FLAGS_quantized_allreduce": False,
        "FLAGS_quantized_allreduce_error_feedback": False,
    }
    base.update(kw)
    paddle.set_flags(base)


@pytest.fixture(autouse=True)
def _restore_flags():
    yield
    _flags()


def _make_model(seed=7, opt_cls=None, **opt_kw):
    paddle.seed(seed)
    m = nn.Sequential(nn.Linear(8, 32), nn.Tanh(), nn.Linear(32, 4))
    opt_cls = opt_cls or paddle.optimizer.Adam
    o = opt_cls(parameters=m.parameters(), **({"learning_rate": 0.01} | opt_kw))
    return m, o


def _data(n=16):
    rng = np.random.RandomState(3)
    return (rng.rand(n, 8).astype(np.float32),
            rng.rand(n, 4).astype(np.float32))


def _loss(m, xb, yb):
    return ((m(xb) - yb) ** 2).mean()


class TestBucketPlan:
    def test_reverse_order_dtype_homogeneous_and_cap(self):
        params = [
            jnp.zeros((64, 64), jnp.float32),    # 16 KB
            jnp.zeros((64,), jnp.float32),
            jnp.zeros((32, 32), jnp.float16),    # dtype break
            jnp.zeros((128, 128), jnp.float32),  # 64 KB (over the cap alone)
        ]
        plan = build_bucket_plan(params, nranks=4, bucket_bytes=32 * 1024,
                                 block=128)
        # reverse-backward order: last param first
        assert plan.buckets[0].indices[0] == 3
        for b in plan.buckets:
            # dtype-homogeneous
            assert all(np.dtype(params[i].dtype) == b.dtype for i in b.indices)
            # padded to nranks*block so shards and blocks divide evenly
            assert b.padded % (4 * 128) == 0
            assert b.padded >= b.size
            # cap respected (single oversized params still get own bucket)
            if len(b.indices) > 1:
                assert b.size * b.itemsize <= 32 * 1024 + b.itemsize
        # the 64 KB param exceeds the cap alone -> its own bucket, then the
        # f64 param breaks dtype, so >= 3 buckets
        assert len(plan.buckets) >= 3
        # flatten/unflatten roundtrip
        b = plan.buckets[0]
        arrs = [jnp.arange(int(np.prod(params[i].shape)))
                .astype(b.dtype).reshape(params[i].shape) for i in b.indices]
        back = plan.unflatten(b, plan.flatten(b, arrs))
        for a, r in zip(arrs, back):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(r))

    def test_signature_hashable_and_stable(self):
        m, o = _make_model()
        p1 = build_bucket_plan(o._parameter_list, nranks=8)
        p2 = build_bucket_plan(o._parameter_list, nranks=8)
        assert hash(p1.signature) == hash(p2.signature)
        assert p1.signature == p2.signature

    def test_mixed_wd_stays_one_bucket_with_vector_gate(self):
        m, o = _make_model()
        wd_of = lambda p: 0.0 if len(p._data.shape) == 1 else 1.0  # gate biases off
        plan = build_bucket_plan(o._parameter_list, nranks=2, wd_of=wd_of)
        assert len(plan.buckets) == 1  # wd mix must NOT fragment buckets
        b = plan.buckets[0]
        assert b.wd_scale is None
        vec = np.asarray(plan.wd_vector(b))
        assert vec.shape == (b.padded,)
        assert set(np.unique(vec[:b.size])) == {0.0, 1.0}


class TestQuantizedPrims:
    def test_blockwise_roundtrip_error_bound(self):
        from paddle_tpu.distributed.collective import (
            blockwise_dequantize, blockwise_quantize,
        )

        rng = np.random.RandomState(0)
        x = (rng.randn(4096).astype(np.float32) * 10).astype(np.float32)
        q, s = blockwise_quantize(jnp.asarray(x), 128)
        back = np.asarray(blockwise_dequantize(q, s))
        # per-element error <= half a quantization step of its block
        step = np.repeat(np.asarray(s).reshape(-1), 128)
        assert np.all(np.abs(back - x) <= step / 2 + 1e-7)

    def test_quantized_psum_scatter_matches_mean(self):
        from paddle_tpu.core.compat import shard_map
        from paddle_tpu.distributed.collective import quantized_psum_scatter_mean

        mesh = _mesh(4)
        rng = np.random.RandomState(1)
        x = rng.randn(4, 1024).astype(np.float32)

        def f(a):
            shard, err = quantized_psum_scatter_mean(a.reshape(-1), "dp", 4, 128)
            return shard, err

        sm = shard_map(f, mesh=mesh, in_specs=P("dp"),
                       out_specs=(P("dp"), P("dp")), check_vma=False)
        shard, err = jax.jit(sm)(x.reshape(-1))
        got = np.asarray(shard)
        want = x.mean(axis=0)
        # int8 blockwise: relative error bounded by the block scales
        scale = np.abs(x).reshape(4, 8, 128).max(-1).max(0) / 127.0
        bound = np.repeat(scale, 128) * 1.0 + 1e-6
        assert np.all(np.abs(got - want) <= bound)
        # error feedback residual matches x - dequant(quant(x)) locally
        assert np.asarray(err).shape == (4 * 1024,)


class TestShardedUpdateParity:
    @pytest.mark.parametrize("world", [2, 4])
    def test_params_moments_step_match_unsharded(self, world):
        """DP=2/4 sharded-weight-update step pinned against the replicated
        GSPMD path: params, both Adam moments, and step count."""
        x, y = _data()
        _flags(FLAGS_shard_weight_update=False)
        m1, o1 = _make_model()
        e1 = HybridParallelEngine(m1, o1, _loss, mesh=_mesh(world))
        l1 = [float(e1.train_step(paddle.to_tensor(x), paddle.to_tensor(y)).item())
              for _ in range(5)]
        assert e1._wus is None

        _flags(FLAGS_shard_weight_update=True)
        m2, o2 = _make_model()
        e2 = HybridParallelEngine(m2, o2, _loss, mesh=_mesh(world))
        l2 = [float(e2.train_step(paddle.to_tensor(x), paddle.to_tensor(y)).item())
              for _ in range(5)]
        assert e2._wus is not None, "sharded weight update not engaged"

        np.testing.assert_allclose(l1, l2, rtol=1e-5, atol=1e-7)
        for p1, p2 in zip(e1.params, e2.params):
            np.testing.assert_allclose(
                np.asarray(p1._data), np.asarray(p2._data),
                rtol=1e-5, atol=1e-7, err_msg=p1.name,
            )
        assert o1._step_count == o2._step_count == 5
        e2.sync_optimizer_state()
        for p1, p2 in zip(e1.params, e2.params):
            st1 = o1._accumulators[id(p1)]
            st2 = o2._accumulators[id(p2)]
            assert sorted(st1) == sorted(st2) == ["moment1", "moment2"]
            for k in st1:
                np.testing.assert_allclose(
                    np.asarray(st1[k]), np.asarray(st2[k]),
                    rtol=1e-5, atol=1e-7, err_msg=f"{p1.name}.{k}",
                )

    def test_sgd_momentum_and_adamw_decay_gate(self):
        """Elementwise rules with state + per-param decay gates survive the
        flat-shard formulation (wd vector path)."""
        x, y = _data()

        def make(shard):
            _flags(FLAGS_shard_weight_update=shard)
            paddle.seed(9)
            m = nn.Sequential(nn.Linear(8, 16), nn.Tanh(), nn.Linear(16, 4))
            o = paddle.optimizer.AdamW(
                learning_rate=0.01, weight_decay=0.1,
                parameters=m.parameters(),
                apply_decay_param_fun=lambda n: "bias" not in n,
            )
            e = HybridParallelEngine(m, o, _loss, mesh=_mesh(4))
            for _ in range(4):
                e.train_step(paddle.to_tensor(x), paddle.to_tensor(y))
            return m, e

        m1, e1 = make(False)
        m2, e2 = make(True)
        assert e1._wus is None and e2._wus is not None
        for p1, p2 in zip(e1.params, e2.params):
            np.testing.assert_allclose(
                np.asarray(p1._data), np.asarray(p2._data),
                rtol=1e-5, atol=1e-7, err_msg=p1.name,
            )


class TestOptimizerStateMemory:
    def test_gpt_opt_state_drops_to_one_over_dp(self):
        """Acceptance: with FLAGS_shard_weight_update at dp=8, per-replica
        optimizer-state memory for the GPT bench model is ~1/8 of the
        replicated path (array-size accounting over device shardings)."""
        from paddle_tpu.models.gpt import GPTForPretraining, gpt_tiny

        _flags()
        paddle.seed(0)
        cfg = gpt_tiny(hidden_dropout=0.0, attention_dropout=0.0)
        model = GPTForPretraining(cfg)
        opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                     parameters=model.parameters())
        eng = HybridParallelEngine(model, opt,
                                   lambda m, i, l: m.loss(i, l), mesh=_mesh(8))
        rng = np.random.RandomState(0)
        ids = paddle.to_tensor(rng.randint(0, cfg.vocab_size, (16, 32)))
        lbl = paddle.to_tensor(rng.randint(0, cfg.vocab_size, (16, 32)))
        eng.train_step(ids, lbl)
        assert eng._wus is not None

        replicated_bytes = sum(
            2 * p.size * np.dtype(p._data.dtype).itemsize  # Adam m+v
            for p in eng.params
        )
        per_device = 0
        global_total = 0
        for st in eng._dp_state["accums"]:
            for v in st.values():
                global_total += v.size * v.dtype.itemsize
                per_device += int(
                    np.prod(v.sharding.shard_shape(v.shape)) * v.dtype.itemsize
                )
        # the flats really are 1/8-sharded on each device ...
        assert per_device * 8 == global_total
        # ... and per-replica state is ~1/8 of the replicated path (padding
        # to dp*block elements per bucket is the only slack)
        ratio = per_device / replicated_bytes
        assert ratio <= 1 / 8 * 1.10, ratio
        assert ratio >= 1 / 8 * 0.95, ratio


class TestCheckpointRoundtrip:
    def test_sharded_state_save_resume_matches_uninterrupted(self, tmp_path):
        """Checkpoint save/resume of the SHARDED optimizer state: 3 steps,
        save, restore into a fresh engine, 2 more steps == 5 uninterrupted
        steps (params and moments)."""
        from paddle_tpu.distributed.checkpoint import (
            engine_load_state_dict, engine_state_dict, save_state_dict,
        )

        x, y = _data()
        _flags()

        def steps(e, n):
            for _ in range(n):
                loss = e.train_step(paddle.to_tensor(x), paddle.to_tensor(y))
            return float(loss.item())

        m_ref, o_ref = _make_model()
        e_ref = HybridParallelEngine(m_ref, o_ref, _loss, mesh=_mesh(4))
        steps(e_ref, 5)

        m1, o1 = _make_model()
        e1 = HybridParallelEngine(m1, o1, _loss, mesh=_mesh(4))
        assert steps(e1, 3) is not None
        assert e1._wus is not None
        save_state_dict(engine_state_dict(e1), str(tmp_path / "ck"))

        m2, o2 = _make_model(seed=123)  # different init: restore must win
        e2 = HybridParallelEngine(m2, o2, _loss, mesh=_mesh(4))
        steps(e2, 1)  # materialize engine state before restoring over it
        engine_load_state_dict(e2, str(tmp_path / "ck"))
        assert o2._step_count == 3
        steps(e2, 2)

        for pr, p2 in zip(e_ref.params, e2.params):
            np.testing.assert_allclose(
                np.asarray(pr._data), np.asarray(p2._data),
                rtol=1e-5, atol=1e-7, err_msg=pr.name,
            )
        e_ref.sync_optimizer_state()
        e2.sync_optimizer_state()
        for pr, p2 in zip(e_ref.params, e2.params):
            for k in o_ref._accumulators[id(pr)]:
                np.testing.assert_allclose(
                    np.asarray(o_ref._accumulators[id(pr)][k]),
                    np.asarray(o2._accumulators[id(p2)][k]),
                    rtol=1e-5, atol=1e-7, err_msg=f"{pr.name}.{k}",
                )


class TestQuantizedAllReduce:
    def _run(self, quantized, error_feedback=False, steps=8):
        _flags(FLAGS_quantized_allreduce=quantized,
               FLAGS_quantized_allreduce_error_feedback=error_feedback)
        profiler.reset_counters()
        x, y = _data()
        m, o = _make_model()
        e = HybridParallelEngine(m, o, _loss, mesh=_mesh(4))
        losses = [float(e.train_step(paddle.to_tensor(x),
                                     paddle.to_tensor(y)).item())
                  for _ in range(steps)]
        return losses, dict(profiler.counters()), e

    def test_bytes_shrink_3x_and_loss_divergence_bounded(self):
        """Acceptance: dp_sync_bytes shrink >= 3x with int8 on the same
        model; the quantized loss curve stays within 2% of fp32 sync."""
        fp, c_fp, _ = self._run(False)
        q, c_q, _ = self._run(True)
        shrink = c_fp["dp_sync_bytes"] / c_q["dp_sync_bytes"]
        assert shrink >= 3.0, shrink
        # parity pin: blockwise int8 on smooth losses diverges slowly
        for lf, lq in zip(fp, q):
            assert abs(lq - lf) / max(abs(lf), 1e-6) < 0.02, (lf, lq)

    def test_error_feedback_carries_residual(self):
        q, _, e = self._run(True, error_feedback=True)
        assert all(np.isfinite(l) for l in q)
        assert e._dp_state["ef"], "error-feedback state missing"
        ef = np.asarray(e._dp_state["ef"][0])
        assert np.abs(ef).max() > 0.0  # residual actually accumulated
        fp, _, _ = self._run(False)
        for lf, lq in zip(fp, q):
            assert abs(lq - lf) / max(abs(lf), 1e-6) < 0.02, (lf, lq)


class TestCountersAndFallbacks:
    def test_counters_emitted_per_step(self):
        _flags()
        profiler.reset_counters()
        x, y = _data()
        m, o = _make_model()
        e = HybridParallelEngine(m, o, _loss, mesh=_mesh(8))
        for _ in range(3):
            e.train_step(paddle.to_tensor(x), paddle.to_tensor(y))
        c = profiler.counters()
        assert c["wus_enabled"] == 1
        assert c["dp_buckets"] == 3 * len(e._wus.plan)
        assert c["dp_reduce_scatters"] == c["dp_buckets"]
        assert c["dp_sync_bytes"] == 3 * e._wus.plan.sync_bytes("reduce_scatter")
        assert c["dp_gather_bytes"] == 3 * e._wus.plan.gather_bytes()

    def test_lamb_falls_back_to_replicated(self):
        """Non-elementwise rules (trust-ratio norms) must not take the
        flat-shard path."""
        _flags()
        x, y = _data()
        paddle.seed(7)
        m = nn.Sequential(nn.Linear(8, 16), nn.Tanh(), nn.Linear(16, 4))
        o = paddle.optimizer.Lamb(learning_rate=0.01, parameters=m.parameters())
        e = HybridParallelEngine(m, o, _loss, mesh=_mesh(4))
        loss = e.train_step(paddle.to_tensor(x), paddle.to_tensor(y))
        assert e._wus is None
        assert np.isfinite(float(loss.item()))

    def test_hybrid_mesh_falls_back(self):
        _flags()
        x, y = _data()
        m, o = _make_model()
        mesh = Mesh(np.asarray(jax.devices()[:8]).reshape(4, 2), ("dp", "mp"))
        e = HybridParallelEngine(m, o, _loss, mesh=mesh)
        loss = e.train_step(paddle.to_tensor(x), paddle.to_tensor(y))
        assert e._wus is None  # GSPMD owns hybrid meshes
        assert np.isfinite(float(loss.item()))

    def test_grad_accumulate_falls_back(self):
        _flags()
        x, y = _data()
        m, o = _make_model()
        e = HybridParallelEngine(m, o, _loss, mesh=_mesh(4), grad_accumulate=4)
        loss = e.train_step(paddle.to_tensor(x), paddle.to_tensor(y))
        assert e._wus is None
        assert np.isfinite(float(loss.item()))

    def test_kill_switch(self):
        _flags(FLAGS_shard_weight_update=False)
        x, y = _data()
        m, o = _make_model()
        e = HybridParallelEngine(m, o, _loss, mesh=_mesh(8))
        e.train_step(paddle.to_tensor(x), paddle.to_tensor(y))
        assert e._wus is None


class TestDataParallelBucketedSync:
    def test_traced_bucket_sync_pmean_parity(self):
        """apply_collective_grads inside a dp shard_map: every param grad
        comes back as the cross-replica mean, via a handful of flat-bucket
        collectives."""
        from paddle_tpu.core.compat import shard_map
        from paddle_tpu.distributed.collective import Group
        from paddle_tpu.distributed.parallel import DataParallel

        paddle.seed(0)
        m = nn.Linear(4, 2)
        dp = DataParallel(m, group=Group(axis_name="dp"))
        mesh = _mesh(4)

        def f(g1, g2):
            saved = (m.weight.grad, m.bias.grad)
            try:
                m.weight.grad = paddle.Tensor(g1, stop_gradient=True)
                m.bias.grad = paddle.Tensor(g2, stop_gradient=True)
                dp.apply_collective_grads()
                return m.weight.grad._data, m.bias.grad._data
            finally:
                m.weight.grad, m.bias.grad = saved

        gw = np.random.RandomState(0).randn(4, 4, 2).astype(np.float32)
        gb = np.random.RandomState(1).randn(4, 2).astype(np.float32)
        sm = shard_map(f, mesh=mesh, in_specs=(P("dp"), P("dp")),
                       out_specs=(P("dp"), P("dp")), check_vma=False)
        ow, ob = jax.jit(sm)(gw.reshape(16, 2), gb.reshape(8))
        ow = np.asarray(ow).reshape(4, 4, 2)
        ob = np.asarray(ob).reshape(4, 2)
        for r in range(4):
            np.testing.assert_allclose(ow[r], gw.mean(0), rtol=1e-5, atol=1e-6)
            np.testing.assert_allclose(ob[r], gb.mean(0), rtol=1e-5, atol=1e-6)

    def test_lazy_bucketed_sync_stable_signature(self):
        """Eager-lazy mode: the bucketed sync records into the pending graph
        with the bucket layout in the key — identical iterations keep
        hitting the warm flush executable, and the displaced grad buffers
        feed the donation pass."""
        from paddle_tpu.distributed.parallel import DataParallel

        paddle.seed(1)
        m = nn.Linear(8, 4)
        dp = DataParallel(m)
        opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=m.parameters())
        x = paddle.to_tensor(np.random.RandomState(2).randn(8, 8).astype(np.float32))
        y = paddle.to_tensor(np.random.RandomState(3).randn(8, 4).astype(np.float32))

        def step():
            loss = ((dp(x) - y) ** 2).mean()
            loss.backward()
            dp.apply_collective_grads()
            opt.step()
            opt.clear_grad()
            return loss

        step()  # compile
        c0 = profiler.counters()
        l1 = float(step().item())
        c1 = profiler.counters()
        l2 = float(step().item())
        c2 = profiler.counters()
        assert np.isfinite(l1) and np.isfinite(l2) and l2 < l1
        assert c1["dp_buckets"] == c0.get("dp_buckets", 0) + 1
        # identical iteration -> flush signature unchanged -> cache hit
        assert c2["lazy_cache_hits"] > c1.get("lazy_cache_hits", 0)
