"""Autograd engine tests (reference: imperative basic_engine + OpTest
check_grad finite differences)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from op_test import check_grad


class TestBackward:
    def test_scalar_chain(self):
        x = paddle.to_tensor(2.0, stop_gradient=False)
        y = x * x * x  # y = x^3, dy/dx = 3x^2 = 12
        y.backward()
        np.testing.assert_allclose(x.grad.numpy(), 12.0, rtol=1e-6)

    def test_grad_accumulation_multi_use(self):
        x = paddle.to_tensor(np.array([1.0, 2.0], np.float32), stop_gradient=False)
        y = x * 2 + x * 3  # dy/dx = 5
        y.sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), [5.0, 5.0])

    def test_repeated_backward_accumulates(self):
        x = paddle.to_tensor(1.0, stop_gradient=False)
        (x * 2).backward()
        (x * 3).backward()
        np.testing.assert_allclose(x.grad.numpy(), 5.0)

    def test_stop_gradient_blocks(self):
        x = paddle.to_tensor(1.0, stop_gradient=False)
        y = paddle.to_tensor(2.0, stop_gradient=True)
        z = x * y
        z.backward()
        np.testing.assert_allclose(x.grad.numpy(), 2.0)
        assert y.grad is None

    def test_detach(self):
        x = paddle.to_tensor(3.0, stop_gradient=False)
        y = (x * 2).detach()
        z = y * x
        z.backward()
        np.testing.assert_allclose(x.grad.numpy(), 6.0)

    def test_no_grad_context(self):
        x = paddle.to_tensor(1.0, stop_gradient=False)
        with paddle.no_grad():
            y = x * 2
        assert y.stop_gradient and y._grad_node is None

    def test_diamond_graph(self):
        x = paddle.to_tensor(np.array(2.0, np.float32), stop_gradient=False)
        a = x * 3
        b = x * 4
        c = a * b  # c = 12 x^2; dc/dx = 24x = 48
        c.backward()
        np.testing.assert_allclose(x.grad.numpy(), 48.0, rtol=1e-6)

    def test_non_scalar_backward_needs_grad_tensor(self):
        x = paddle.to_tensor(np.ones(3, np.float32), stop_gradient=False)
        y = x * 2
        with pytest.raises(RuntimeError):
            y.backward()
        y.backward(paddle.to_tensor(np.array([1.0, 2.0, 3.0], np.float32)))
        np.testing.assert_allclose(x.grad.numpy(), [2.0, 4.0, 6.0])

    def test_register_hook(self):
        x = paddle.to_tensor(1.0, stop_gradient=False)
        x.register_hook(lambda g: g * 10)
        (x * 2).backward()
        np.testing.assert_allclose(x.grad.numpy(), 20.0)

    def test_multi_output_op_grad(self):
        x = paddle.to_tensor(np.random.rand(4, 3).astype(np.float32), stop_gradient=False)
        parts = paddle.split(x, 3, axis=1)
        loss = parts[0].sum() + 2 * parts[2].sum()
        loss.backward()
        g = x.grad.numpy()
        np.testing.assert_allclose(g[:, 0], 1.0)
        np.testing.assert_allclose(g[:, 1], 0.0)
        np.testing.assert_allclose(g[:, 2], 2.0)


class TestFiniteDifference:
    def test_tanh(self):
        check_grad(paddle.tanh, [np.random.rand(3, 3)])

    def test_softmax(self):
        check_grad(lambda x: paddle.nn.functional.softmax(x, -1), [np.random.rand(2, 5)])

    def test_layer_norm(self):
        check_grad(
            lambda x: paddle.nn.functional.layer_norm(x, 4), [np.random.rand(3, 4)], atol=3e-2
        )

    def test_conv2d(self):
        check_grad(
            lambda x, w: paddle.nn.functional.conv2d(x, w, padding=1),
            [np.random.rand(1, 2, 5, 5), np.random.rand(3, 2, 3, 3)],
        )

    def test_gather_grad(self):
        idx = paddle.to_tensor(np.array([0, 2]))
        check_grad(lambda x: paddle.gather(x, idx, axis=0), [np.random.rand(4, 3)])


class TestPaddleGrad:
    def test_basic(self):
        x = paddle.to_tensor(2.0, stop_gradient=False)
        y = x * x
        (gx,) = paddle.grad(y, x)
        np.testing.assert_allclose(gx.numpy(), 4.0)
        assert x.grad is None  # paddle.grad does not touch .grad

    def test_intermediate(self):
        x = paddle.to_tensor(3.0, stop_gradient=False)
        h = x * 2
        y = h * h
        (gh,) = paddle.grad(y, h)
        np.testing.assert_allclose(gh.numpy(), 12.0)

    def test_allow_unused(self):
        x = paddle.to_tensor(1.0, stop_gradient=False)
        z = paddle.to_tensor(1.0, stop_gradient=False)
        y = x * 2
        gx, gz = paddle.grad(y, [x, z], allow_unused=True)
        assert gz is None

    def test_double_grad(self):
        x = paddle.to_tensor(2.0, stop_gradient=False)
        y = x * x * x
        (g1,) = paddle.grad(y, x, create_graph=True)
        (g2,) = paddle.grad(g1, x)
        np.testing.assert_allclose(g2.numpy(), 12.0, rtol=1e-5)  # d2(x^3)=6x


class TestPyLayer:
    def test_custom_forward_backward(self):
        class Double(paddle.autograd.PyLayer):
            @staticmethod
            def forward(ctx, x):
                ctx.save_for_backward(x)
                return x * 2

            @staticmethod
            def backward(ctx, grad):
                return grad * 2

        x = paddle.to_tensor(3.0, stop_gradient=False)
        y = Double.apply(x)
        np.testing.assert_allclose(y.numpy(), 6.0)
        y.backward()
        np.testing.assert_allclose(x.grad.numpy(), 2.0)

    def test_jacobian(self):
        x = paddle.to_tensor(np.array([1.0, 2.0], np.float32))
        jac = paddle.autograd.jacobian(lambda t: t * t, x)
        np.testing.assert_allclose(jac.numpy(), np.diag([2.0, 4.0]), rtol=1e-6)
