"""Lazy eager-op batching (core/lazy.py) — correctness + caching regression.

The lazy engine queues eager ops and flushes them as one XLA computation at
materialization points; backward is ONE jax.vjp over the composed forward
(tape backward, engine.py). These tests pin: numerical parity with per-op
dispatch, flush-executable-cache stability across identical train
iterations, vjp value-capture semantics, deep-graph robustness, and interop
with the compiled-step path.
"""
import numpy as np
import jax.numpy as jnp
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu.core import lazy


@pytest.fixture(autouse=True)
def _lazy_on():
    lazy.set_lazy_mode(True)
    yield
    lazy.set_lazy_mode(True)


class MLP(nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc1 = nn.Linear(16, 32)
        self.fc2 = nn.Linear(32, 10)

    def forward(self, x):
        return self.fc2(F.relu(self.fc1(x)))


def _train(lazy_on, steps=4):
    lazy.set_lazy_mode(lazy_on)
    paddle.seed(7)
    m = MLP()
    opt = paddle.optimizer.Adam(learning_rate=0.01, parameters=m.parameters())
    losses = []
    for i in range(steps):
        x = paddle.to_tensor(np.random.RandomState(i).randn(8, 16).astype("float32"))
        y = paddle.to_tensor(np.random.RandomState(100 + i).randint(0, 10, (8,)))
        loss = F.cross_entropy(m(x), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss.numpy()))
    return losses


class TestLazyParity:
    def test_train_loop_matches_per_op_dispatch(self):
        eager = _train(False)
        lz = _train(True)
        np.testing.assert_allclose(eager, lz, rtol=1e-5, atol=1e-6)

    def test_flush_cache_stable_across_iterations(self):
        lazy.set_lazy_mode(True)
        paddle.seed(0)
        m = MLP()
        opt = paddle.optimizer.SGD(learning_rate=0.01, parameters=m.parameters())
        x = paddle.to_tensor(np.random.RandomState(0).randn(8, 16).astype("float32"))
        y = paddle.to_tensor(np.random.RandomState(1).randint(0, 10, (8,)))

        def step():
            loss = F.cross_entropy(m(x), y)
            loss.backward()
            opt.step()
            opt.clear_grad()

        step()
        step()
        n = len(lazy._flush_cache)
        for _ in range(3):
            step()
        assert len(lazy._flush_cache) == n  # same signature → cache hit

    def test_recompute_cache_stable(self):
        from paddle_tpu.distributed.fleet.utils import recompute

        paddle.seed(0)
        lin = nn.Linear(8, 8)
        opt = paddle.optimizer.SGD(learning_rate=0.01, parameters=lin.parameters())
        x = paddle.to_tensor(np.random.RandomState(0).randn(2, 8).astype("float32"))
        for i in range(4):
            out = recompute(lambda h: F.relu(lin(h)), x)
            out.sum().backward()
            opt.step()
            opt.clear_grad()
            if i == 1:
                n = len(lazy._flush_cache)
        assert len(lazy._flush_cache) == n


class TestTapeBackward:
    def test_deep_chain_no_recursion_limit(self):
        t = paddle.to_tensor(np.ones(3, np.float32))
        t.stop_gradient = False
        z = t
        for _ in range(1500):
            z = z * 1.0001
        z.sum().backward()
        assert np.isfinite(t.grad.numpy()).all()

    def test_grad_uses_forward_time_values(self):
        # _set_data between forward and backward must not change the result
        w = paddle.to_tensor(np.array([2.0], np.float32))
        w.stop_gradient = False
        loss = (w * w).sum()
        w._set_data(jnp.asarray(np.array([10.0], np.float32)))
        loss.backward()
        np.testing.assert_allclose(w.grad.numpy(), [4.0])
        np.testing.assert_allclose(np.asarray(loss.numpy()), 4.0, rtol=1e-6)

    def test_backward_twice_raises(self):
        w = paddle.to_tensor(np.array([2.0], np.float32))
        w.stop_gradient = False
        loss = (w * 3.0).sum()
        loss.backward()
        with pytest.raises(RuntimeError):
            loss.backward()

    def test_retain_graph_allows_second_backward(self):
        w = paddle.to_tensor(np.array([2.0], np.float32))
        w.stop_gradient = False
        loss = (w * 3.0).sum()
        loss.backward(retain_graph=True)
        loss.backward()
        np.testing.assert_allclose(w.grad.numpy(), [6.0])

    def test_leaf_hooks_run(self):
        w = paddle.to_tensor(np.array([2.0], np.float32))
        w.stop_gradient = False
        w.register_hook(lambda g: g * 2)
        ((w * w).sum()).backward()
        np.testing.assert_allclose(w.grad.numpy(), [8.0])

    def test_nonleaf_hook_falls_back_and_works(self):
        w = paddle.to_tensor(np.array([2.0], np.float32))
        w.stop_gradient = False
        h = w * 3.0
        h.register_hook(lambda g: g * 10)
        (h * 1.0).sum().backward()
        np.testing.assert_allclose(w.grad.numpy(), [30.0])


class TestLazyInterop:
    def test_compiled_step_after_lazy_eager_steps(self):
        paddle.seed(0)
        m = nn.Linear(4, 4)
        opt = paddle.optimizer.AdamW(learning_rate=1e-3, parameters=m.parameters())
        x = paddle.to_tensor(np.random.RandomState(0).randn(2, 4).astype("float32"))
        y = paddle.to_tensor(np.random.RandomState(1).randn(2, 4).astype("float32"))
        for _ in range(2):
            loss = F.mse_loss(m(x), y)
            loss.backward()
            opt.step()
            opt.clear_grad()
        step = paddle.jit.compile_train_step(m, lambda mm, a, b: F.mse_loss(mm(a), b), opt)
        l = step(x, y)
        assert np.isfinite(float(l.item()))

    def test_kwonly_defaults_distinguish_cache_entries(self):
        def mk(s):
            def f(*xs, scale=s):
                return xs[0] * scale

            return f

        (a,), _ = lazy.record("kwtest", mk(0.5), [jnp.ones(3)])
        (b,), _ = lazy.record("kwtest", mk(2.0), [jnp.ones(3)])
        lazy.flush()
        assert float(np.asarray(a._concrete)[0]) == 0.5
        assert float(np.asarray(b._concrete)[0]) == 2.0

    def test_checkpoint_roundtrip_with_lazy_state(self, tmp_path):
        paddle.seed(0)
        m = nn.Linear(4, 4)
        opt = paddle.optimizer.Adam(learning_rate=1e-3, parameters=m.parameters())
        x = paddle.to_tensor(np.random.RandomState(0).randn(2, 4).astype("float32"))
        y = paddle.to_tensor(np.random.RandomState(1).randn(2, 4).astype("float32"))
        for _ in range(2):
            loss = F.mse_loss(m(x), y)
            loss.backward()
            opt.step()
            opt.clear_grad()
        sd = opt.state_dict()
        assert "@step" in sd
        path = str(tmp_path / "m.pdparams")
        paddle.save(m.state_dict(), path)
        m2 = nn.Linear(4, 4)
        m2.set_state_dict(paddle.load(path))
        np.testing.assert_allclose(
            m2.weight.numpy(), m.weight.numpy(), rtol=1e-6
        )


class TestLazyDunders:
    """Raw operator use on a LazyArray must RECORD, not flush (round-3
    verdict: a stray `lazy + 1` inside a library split the fused iteration)."""

    def test_arithmetic_stays_lazy(self):
        t = paddle.to_tensor(np.arange(8, dtype=np.float32))
        a = (t * 2.0)._data  # lazy product
        assert lazy.is_lazy(a)
        for expr in (a + 1.0, 1.0 + a, a - 1.0, a * 3.0, -a, a / 2.0, a ** 2):
            assert lazy.is_lazy(expr), expr
        assert lazy.is_lazy(a[2])  # static getitem records too
        np.testing.assert_allclose(np.asarray(a + 1.0), np.arange(8) * 2.0 + 1.0)

    def test_values_correct_through_lazy_ops(self):
        t = paddle.to_tensor(np.array([1.0, 2.0, 3.0], np.float32))
        a = (t + 0.0)._data
        out = ((2.0 * a - 1.0) / 2.0) ** 2
        np.testing.assert_allclose(
            np.asarray(out), ((2 * np.array([1.0, 2, 3]) - 1) / 2) ** 2
        )
        np.testing.assert_allclose(float(np.asarray(a[1])), 2.0)
