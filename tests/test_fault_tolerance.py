"""Fault-tolerant training end to end.

Proves the ISSUE-2 acceptance criteria on CPU:
* crash/resume parity — SIGTERM (preemption drain) at step k, and separately
  a failed checkpoint write, both resume from the last verified checkpoint
  and reproduce the uninterrupted loss sequence bit-for-bit;
* crash-safe checkpointing — manifest commit markers, checksum verification,
  walk-back past uncommitted/corrupt checkpoints (incl. orbax tmp litter);
* FLAGS_check_nan_inf under the lazy engine — raises within the step, names
  the producing op in per-op mode, suppresses donation while armed;
* the fault-injection harness itself (deterministic firing, retry backoff),
  with a tripwire asserting every registered injection point is exercised.
"""
import json
import os
import pathlib
import warnings

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import profiler
from paddle_tpu.profiler import flight
from paddle_tpu.core.lazy import is_lazy, lazy_guard
from paddle_tpu.distributed.checkpoint import (
    AutoCheckpoint, CheckpointError, load_state_dict, read_manifest,
    save_state_dict,
)
from paddle_tpu.distributed.fleet.elastic import ElasticLauncher, ElasticManager
from paddle_tpu.fault import (
    InjectedFault, PreemptionGuard, RESUMABLE_EXIT_CODE, inject, retry_call,
)

pytestmark = pytest.mark.faults


@pytest.fixture(autouse=True)
def _disarm_and_reset_flags():
    yield
    inject.disarm()
    paddle.set_flags(
        {"FLAGS_check_nan_inf": False, "FLAGS_check_nan_inf_per_op": False}
    )


# -- deterministic micro-training loop ---------------------------------------
def _data_for(step):
    rng = np.random.RandomState(1000 + step)
    x = rng.randn(8, 4).astype(np.float32)
    y = rng.randn(8, 1).astype(np.float32)
    return x, y


def _fresh_w():
    w = paddle.to_tensor(np.full((4, 1), 0.5, np.float32))
    w.stop_gradient = False
    return w


def _train_step(w, step, lr=0.1):
    x, y = _data_for(step)
    xt, yt = paddle.to_tensor(x), paddle.to_tensor(y)
    loss = ((paddle.matmul(xt, w) - yt) ** 2).mean()
    loss.backward()
    w._set_data(w._data - lr * w.grad._data)
    w.clear_grad()
    return float(loss)  # materialization point: one lazy flush per step


def _uninterrupted_losses(steps=6):
    w = _fresh_w()
    return [_train_step(w, s) for s in range(steps)]


# -- acceptance: crash/resume parity -----------------------------------------
class TestPreemptionResumeParity:
    def test_sigterm_at_step_k_resumes_bit_for_bit(self, tmp_path):
        ref = _uninterrupted_losses()

        ckdir = str(tmp_path / "auto")
        ac = AutoCheckpoint(ckdir, interval_steps=100)  # drain save only
        inject.arm({"preempt.sigterm": {"step": 2}})
        before = profiler.counters().get("preemption_drains", 0)
        w = _fresh_w()
        losses = []
        with PreemptionGuard(checkpoint=ac) as guard:
            with pytest.raises(SystemExit) as ei:
                for step in range(6):
                    losses.append(_train_step(w, step))
                    guard.check(step, {"w": w})
        assert ei.value.code == RESUMABLE_EXIT_CODE
        assert profiler.counters()["preemption_drains"] == before + 1
        inject.disarm()

        # a fresh process would start here: resume from the drained checkpoint
        w2 = _fresh_w()
        start = AutoCheckpoint(ckdir).resume({"w": w2})
        assert start == 2
        for step in range(start + 1, 6):
            losses.append(_train_step(w2, step))
        assert losses == ref  # bit-for-bit on CPU

    def test_failed_checkpoint_write_resumes_from_last_committed(self, tmp_path):
        ref = _uninterrupted_losses()

        ckdir = str(tmp_path / "auto")
        ac = AutoCheckpoint(ckdir, interval_steps=2, save_retries=2)
        # the SECOND checkpoint write (step 4) fails persistently — every
        # retry attempt fires too, so the save is genuinely lost
        inject.arm({"ckpt.write": {"from": 2}})
        w = _fresh_w()
        w_at_2 = None
        for step in range(6):
            _train_step(w, step)
            with warnings.catch_warnings():
                warnings.simplefilter("ignore")
                ac.maybe_save(step, {"w": w})
            if step == 2:
                w_at_2 = w.numpy().copy()
        ac.wait()
        inject.disarm()
        assert profiler.counters().get("retry_attempts", 0) >= 2

        # litter the save dir the way a mid-save kill does: an orbax tmp dir
        # and an uncommitted checkpoint dir (data present, no manifest commit)
        os.makedirs(os.path.join(ckdir, "step_6.orbax-checkpoint-tmp-123"))
        os.makedirs(os.path.join(ckdir, "step_6"))
        with open(os.path.join(ckdir, "step_6", "garbage"), "w") as f:
            f.write("partial write")

        w2 = _fresh_w()
        before = profiler.counters().get("ckpt_resume_fallbacks", 0)
        start = AutoCheckpoint(ckdir).resume({"w": w2})
        assert start == 2  # step-4 save failed; step-6 litter skipped
        assert profiler.counters()["ckpt_resume_fallbacks"] > before
        np.testing.assert_array_equal(w2.numpy(), w_at_2)  # bit-identical

        losses = []
        for step in range(start + 1, 6):
            losses.append(_train_step(w2, step))
        assert losses == ref[start + 1:]


# -- crash-safe checkpointing -------------------------------------------------
class TestManifest:
    def test_save_writes_committed_manifest(self, tmp_path):
        p = str(tmp_path / "ck")
        w = paddle.to_tensor(np.arange(4, dtype=np.float32))
        save_state_dict({"w": w, "nested": {"b": w}}, p, step=7)
        man = read_manifest(p)
        assert man["committed"] is True and man["step"] == 7
        assert set(man["tree"]) == {"w", "nested/b"}
        assert man["tree"]["w"]["crc32"] is not None

    def test_checksum_mismatch_detected_on_load(self, tmp_path):
        import json

        p = str(tmp_path / "ck")
        w = paddle.to_tensor(np.arange(4, dtype=np.float32))
        save_state_dict({"w": w}, p)
        man = read_manifest(p)
        man["tree"]["w"]["crc32"] ^= 0xDEAD
        with open(p + ".manifest.json", "w") as f:
            json.dump(man, f)
        with pytest.raises(CheckpointError, match="checksum mismatch"):
            load_state_dict({"w": paddle.to_tensor(np.zeros(4, np.float32))}, p)

    def test_resume_skips_uncommitted_manifest(self, tmp_path):
        import json

        ac = AutoCheckpoint(str(tmp_path / "auto"), interval_steps=1, keep_last=5)
        w = paddle.to_tensor(np.zeros(3, np.float32))
        for step in range(1, 4):
            w._set_data(w._data + 1)
            ac.maybe_save(step, {"w": w})
        ac.wait()
        # step_3 committed but marked mid-write: resume must fall back to 2
        man = read_manifest(ac._step_path(3))
        man["committed"] = False
        with open(ac._step_path(3) + ".manifest.json", "w") as f:
            json.dump(man, f)
        w2 = paddle.to_tensor(np.zeros(3, np.float32))
        assert ac.resume({"w": w2}) == 2
        np.testing.assert_array_equal(w2.numpy(), np.full(3, 2.0))

    def test_gc_never_deletes_last_verified_checkpoint(self, tmp_path):
        # async mode: the manifest commits only at wait_until_finished, so at
        # GC time the newest save is still UNVERIFIED — with keep_last=1 a
        # naive GC would delete step_1, the only good copy
        ac = AutoCheckpoint(
            str(tmp_path / "auto"), interval_steps=1, keep_last=1, async_save=True
        )
        w = paddle.to_tensor(np.zeros(2, np.float32))
        w._set_data((w + 1.0)._data)
        ac.maybe_save(1, {"w": w})
        ac.wait()  # step_1 committed
        w._set_data((w + 1.0)._data)
        ac.maybe_save(2, {"w": w})  # async: uncommitted until wait()
        assert read_manifest(ac._step_path(2)) is None
        assert os.path.isdir(ac._step_path(1))  # survived GC despite keep_last=1
        w2 = paddle.to_tensor(np.zeros(2, np.float32))
        assert AutoCheckpoint(str(tmp_path / "auto")).resume({"w": w2}) == 1
        np.testing.assert_array_equal(w2.numpy(), np.full(2, 1.0))
        ac.wait()  # commit lands: now step 2 is the resume target
        w3 = paddle.to_tensor(np.zeros(2, np.float32))
        assert AutoCheckpoint(str(tmp_path / "auto")).resume({"w": w3}) == 2

    def test_object_tree_resume_restores_optimizer_state(self, tmp_path):
        """{"model": model, "optimizer": opt} checkpoints as a tree and
        resume restores Adam moments + step count — and the restored buffers
        are jax-owned copies, so the post-resume lazy flush can DONATE them
        without corruption (regression: orbax hands back TensorStore-backed
        arrays; donating those made the first resumed steps read garbage)."""
        from paddle_tpu import nn

        paddle.seed(11)
        model = nn.Sequential(nn.Linear(16, 8), nn.Tanh(), nn.Linear(8, 4))
        opt = paddle.optimizer.Adam(
            learning_rate=0.01, parameters=model.parameters()
        )
        state = {"model": model, "optimizer": opt}
        ac = AutoCheckpoint(str(tmp_path / "auto"), interval_steps=3)

        def step_fn(step):
            rng = np.random.RandomState(2000 + step)
            xt = paddle.to_tensor(rng.randn(8, 16).astype(np.float32))
            yt = paddle.to_tensor(rng.randn(8, 4).astype(np.float32))
            loss = ((model(xt) - yt) ** 2).mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
            return float(loss)

        ref = []
        for step in range(6):
            ref.append(step_fn(step))
            ac.maybe_save(step, state)  # saves at step 3
        ac.wait()
        # rewind the SAME objects to the step-3 checkpoint and replay
        assert ac.resume(state) == 3
        assert int(opt._step_count) == 4  # Adam bias correction restored
        replay = [step_fn(step) for step in range(4, 6)]
        assert replay == ref[4:]  # bit-for-bit, with donation enabled

    def test_load_strict_reports_missing_and_unexpected(self, tmp_path):
        p = str(tmp_path / "ck")
        w = paddle.to_tensor(np.ones(2, np.float32))
        save_state_dict({"a": w, "b": w}, p)
        tgt = {"a": paddle.to_tensor(np.zeros(2, np.float32)),
               "c": paddle.to_tensor(np.zeros(2, np.float32))}
        with pytest.raises(CheckpointError, match=r"missing keys \['c'\].*unexpected keys \['b'\]"):
            load_state_dict(tgt, p)
        # strict=False keeps the old skip-silently behavior
        load_state_dict(tgt, p, strict=False)
        np.testing.assert_array_equal(tgt["a"].numpy(), np.ones(2, np.float32))
        np.testing.assert_array_equal(tgt["c"].numpy(), np.zeros(2, np.float32))


# -- lazy-mode nan/inf guard --------------------------------------------------
class TestLazyNanInfGuard:
    def test_trips_at_flush_within_same_step(self):
        paddle.set_flags({"FLAGS_check_nan_inf": True})
        a = paddle.to_tensor(np.array([0.0], np.float32))
        t = paddle.log(a - 1.0)
        assert is_lazy(t._data)  # the op stayed recorded — fusion survives
        before = profiler.counters().get("naninf_trips", 0)
        with pytest.raises(FloatingPointError, match="log"):
            t.numpy()
        assert profiler.counters()["naninf_trips"] == before + 1

    def test_per_op_mode_names_producing_op(self):
        paddle.set_flags(
            {"FLAGS_check_nan_inf": True, "FLAGS_check_nan_inf_per_op": True}
        )
        a = paddle.to_tensor(np.array([0.0], np.float32))
        # NaN born at log, then consumed: only the downstream output is held
        d = paddle.log(a - 1.0) * 2.0
        with pytest.raises(FloatingPointError, match=r"'log'.*flat index 0"):
            d.numpy()

    def test_per_op_mode_catches_dead_intermediate_nan(self):
        # a NaN born in an intermediate that is masked out of every live
        # output is invisible to the (fusion-preserving) default scan, but
        # per-op mode checks every node output on every flush
        paddle.set_flags({"FLAGS_check_nan_inf": True})
        a = paddle.to_tensor(np.array([0.0], np.float32))
        paddle.log(a - 1.0)  # result discarded: its node output is dead
        out = a + 1.0
        np.testing.assert_array_equal(out.numpy(), [1.0])  # default: clean
        paddle.set_flags({"FLAGS_check_nan_inf_per_op": True})
        paddle.log(a - 1.0)
        out2 = a + 2.0
        with pytest.raises(FloatingPointError, match="log"):
            out2.numpy()

    def test_donation_suppressed_while_armed(self):
        paddle.set_flags({"FLAGS_check_nan_inf": True})
        before = profiler.counters().get("naninf_donation_suppressed", 0)
        w = paddle.to_tensor(np.ones(4, np.float32))
        w._set_data((w + 1.0)._data)  # lazy rebind — the donation pattern
        w.numpy()
        assert profiler.counters().get("naninf_donation_suppressed", 0) > before

    def test_eager_message_details(self):
        with lazy_guard(False):
            paddle.set_flags({"FLAGS_check_nan_inf": True})
            a = paddle.to_tensor(np.array([1.0, 0.0], np.float32))
            with pytest.raises(FloatingPointError) as ei:
                paddle.log(a - 1.0)  # [-inf, nan] — raises at the call site
            msg = str(ei.value)
        assert "output 0" in msg and "shape=(2,)" in msg
        assert "float32" in msg and "2 non-finite" in msg and "flat index 0" in msg

    def test_nan_injection_into_named_op(self):
        paddle.set_flags({"FLAGS_check_nan_inf": True})
        inject.arm({"tensor.nan": {"op": "matmul", "call": 1}})
        w = _fresh_w()
        with pytest.raises(FloatingPointError):
            _train_step(w, 0)


# -- flight recorder: post-mortems on the fault paths --------------------------
class TestFlightRecorderDumps:
    def test_nan_trip_dumps_naming_producing_flush_span(self, tmp_path, monkeypatch):
        """ISSUE-5 acceptance: an injected NaN fault produces a flight dump
        whose active-span stack names the producing lazy_flush span, with
        the last >=32 spans and a full counter snapshot."""
        monkeypatch.setenv("PADDLE_TPU_FLIGHT_DIR", str(tmp_path))
        flight.clear()
        w = _fresh_w()
        for step in range(10):  # populate the ring: >=3 spans per step
            _train_step(w, step)
        paddle.set_flags({"FLAGS_check_nan_inf": True})
        inject.arm({"tensor.nan": {"op": "matmul", "call": 1}})
        with pytest.raises(FloatingPointError):
            _train_step(w, 10)
        path = flight.last_dump()
        assert path is not None and path.startswith(str(tmp_path))
        doc = json.load(open(path))
        assert doc["reason"] == "naninf"
        # async runtime: the trip surfaces at the deferred drain, where the
        # producing lazy_flush span (already closed) rides the dump's extra;
        # with FLAGS_lazy_async=0 it would still be on the open-span stack
        prod = doc["extra"].get("producing_span")
        assert (
            prod is not None and prod["name"] == "lazy_flush"
        ) or any(s["name"] == "lazy_flush" for s in doc["active_spans"])
        assert len(doc["recent_spans"]) >= 32
        assert doc["counters"].get("naninf_trips", 0) >= 1
        assert doc["counters"].get("lazy_flushes", 0) >= 10
        assert doc["extra"]["origin"].startswith("lazy")
        assert doc["fault_inject"]["armed"] is True

    def test_preemption_drain_dumps(self, tmp_path, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_FLIGHT_DIR", str(tmp_path))
        guard = PreemptionGuard(exit_fn=lambda code: None)
        guard.preempt()
        assert guard.check(7, None)
        doc = json.load(open(flight.last_dump()))
        assert doc["reason"] == "preemption"
        assert doc["extra"]["step"] == 7
        assert "preemption_drains" in doc["counters"]

    def test_ckpt_save_failure_dumps(self, tmp_path, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_FLIGHT_DIR", str(tmp_path / "fl"))
        ac = AutoCheckpoint(str(tmp_path / "auto"), interval_steps=1, save_retries=0)
        inject.arm({"ckpt.write": {}})  # every write fails
        w = _fresh_w()
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            assert ac.maybe_save(1, {"w": w}) is False
        doc = json.load(open(flight.last_dump()))
        assert doc["reason"] == "ckpt_save_failure"
        assert doc["extra"]["step"] == 1 and doc["extra"]["phase"] == "write"
        assert "InjectedFault" in doc["extra"]["error"]
        assert doc["counters"].get("ckpt_save_failures", 0) >= 1


# -- retry + elastic ----------------------------------------------------------
class _FakeStore:
    def __init__(self):
        self.kv = {}
        self.fail_next = 0

    def _maybe_fail(self):
        if self.fail_next > 0:
            self.fail_next -= 1
            raise OSError("transient store error")

    def set(self, k, v):
        self._maybe_fail()
        self.kv[k] = v

    def get(self, k):
        self._maybe_fail()
        return self.kv.get(k)

    def add(self, k, n=1):
        self._maybe_fail()
        self.kv[k] = self.kv.get(k, 0) + n
        return self.kv[k]

    def delete_key(self, k):
        self.kv.pop(k, None)


class _FakeProc:
    def __init__(self, code):
        self._code = code

    def poll(self):
        return self._code

    def wait(self):
        return self._code

    def terminate(self):
        pass


class TestRetryAndElastic:
    def test_retry_call_backoff_and_counter(self):
        calls, slept = [], []

        def flaky():
            calls.append(1)
            if len(calls) < 3:
                raise OSError("transient")
            return 42

        before = profiler.counters().get("retry_attempts", 0)
        assert retry_call(flaky, retries=5, base_delay=0.01, sleep=slept.append) == 42
        assert len(calls) == 3
        assert slept == [0.01, 0.02]  # exponential backoff
        assert profiler.counters()["retry_attempts"] == before + 2

    def test_heartbeat_survives_transient_store_errors(self):
        st = _FakeStore()
        m = ElasticManager(st, 1, worker_id="w0", retry_base_delay=0.001)
        st.fail_next = 2
        m._beat()  # retried through both failures
        assert m._hb_key("w0") in st.kv

        # injected transient store failure (times=2): absorbed by retry
        inject.arm({"store.op": {"times": 2}})
        m._beat()
        inject.disarm()

        # persistent store failure defeats the retry budget
        inject.arm({"store.op": {}})
        with pytest.raises(InjectedFault):
            m._beat()

    def test_launcher_treats_resumable_exit_as_clean_restart(self):
        spawns = []

        def spawn_fn(ids):
            code = RESUMABLE_EXIT_CODE if not spawns else 0
            spawns.append(1)
            return {w: _FakeProc(code) for w in ids}

        mgr = ElasticManager(_FakeStore(), 1)
        launcher = ElasticLauncher(spawn_fn, mgr, watch_interval=0.01)
        assert launcher.run(["w0"]) == 0
        assert len(spawns) == 2  # preempted generation + clean relaunch


# -- flags + harness tripwires ------------------------------------------------
class TestFlagsAndTripwire:
    def test_unknown_flag_typo_raises_with_suggestion(self):
        with pytest.raises(KeyError, match="FLAGS_check_nan_inf"):
            paddle.set_flags({"FLAGS_chek_nan_inf": True})

    def test_register_flag_then_set(self):
        from paddle_tpu.framework import flags

        flags.register_flag("FLAGS_test_fault_tolerance_custom", 1)
        paddle.set_flags({"FLAGS_test_fault_tolerance_custom": 2})
        assert flags.flag("FLAGS_test_fault_tolerance_custom") == 2

    def test_unknown_injection_point_raises(self):
        with pytest.raises(KeyError, match="ckpt.write"):
            inject.arm({"ckpt.wrte": {}})

    def test_spec_string_grammar(self):
        inject.arm("ckpt.write:at=2,times=1;preempt.sigterm:step=3")
        assert not inject.should_fire("ckpt.write")       # call 1
        assert inject.should_fire("ckpt.write")           # call 2 == at
        assert not inject.should_fire("preempt.sigterm", step=1)
        assert inject.should_fire("preempt.sigterm", step=3)

    def test_every_injection_point_is_exercised(self):
        # tripwire: every registered point name must appear somewhere in the
        # test suite (beyond the POINTS registry itself) AND fire through its
        # public mechanism — adding a point without a test breaks this. The
        # chaos points (rank.*, collective.drop, ckpt.serialize/ack/commit)
        # live in test_watchdog / test_coordinated_ckpt / test_chaos_recovery,
        # so the scan covers the whole tests directory.
        src = "".join(
            p.read_text() for p in sorted(pathlib.Path(__file__).parent.glob("test_*.py"))
        )
        for point in inject.POINTS:
            assert src.count(point) >= 1, f"injection point {point!r} has no test"
        for point in inject.POINTS:
            inject.arm({point: {}})
            try:
                if point in ("store.op", "ckpt.write"):
                    with pytest.raises(InjectedFault):
                        inject.check(point)
                else:
                    assert inject.should_fire(point, step=0, op="any")
                assert point in inject.exercised()
            finally:
                inject.disarm()
