"""New op-surface modules: fft, signal, control flow, detection ops, text,
misc — numeric checks vs numpy/brute-force references.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F


class TestFFT:
    def test_fft_roundtrip(self):
        x = np.random.randn(2, 16).astype(np.float32)
        X = paddle.fft.fft(paddle.to_tensor(x))
        np.testing.assert_allclose(np.asarray(X._data), np.fft.fft(x), rtol=1e-4, atol=1e-4)
        back = paddle.fft.ifft(X)
        np.testing.assert_allclose(np.asarray(back._data).real, x, rtol=1e-4, atol=1e-5)

    def test_rfft_grad(self):
        x = paddle.to_tensor(np.random.randn(8).astype(np.float32), stop_gradient=False)
        y = paddle.fft.irfft(paddle.fft.rfft(x))
        y.sum().backward()
        assert x.grad is not None

    def test_fftshift(self):
        x = np.arange(8, dtype=np.float32)
        out = paddle.fft.fftshift(paddle.to_tensor(x))
        np.testing.assert_array_equal(out.numpy(), np.fft.fftshift(x))


class TestSignal:
    def test_stft_istft_roundtrip(self):
        x = np.random.randn(2, 128).astype(np.float32)
        s = paddle.signal.stft(paddle.to_tensor(x), n_fft=32, hop_length=8)
        rec = paddle.signal.istft(s, n_fft=32, hop_length=8, length=128)
        np.testing.assert_allclose(rec.numpy(), x, atol=1e-5)

    def test_frame_overlap_add(self):
        x = np.arange(20, dtype=np.float32)
        fr = paddle.signal.frame(paddle.to_tensor(x), 4, 4)
        back = paddle.signal.overlap_add(fr, 4)
        np.testing.assert_allclose(back.numpy(), x[: back.shape[-1]])


class TestControlFlow:
    def test_cond_eager_and_traced(self):
        from paddle_tpu.ops.control_flow import cond

        x = paddle.to_tensor(np.array([1.0, 2.0], np.float32))
        out = cond(paddle.to_tensor(True), lambda: x + 1, lambda: x - 1)
        np.testing.assert_allclose(out.numpy(), [2.0, 3.0])

        # traced through to_static
        @paddle.jit.to_static
        def f(a, flag):
            return cond(flag > 0, lambda: a * 2, lambda: a * 3)

        r = f(x, paddle.to_tensor(np.array(1.0, np.float32)))
        np.testing.assert_allclose(r.numpy(), [2.0, 4.0])
        r2 = f(x, paddle.to_tensor(np.array(-1.0, np.float32)))
        np.testing.assert_allclose(r2.numpy(), [3.0, 6.0])

    def test_while_loop_eager_and_traced(self):
        from paddle_tpu.ops.control_flow import while_loop

        out = while_loop(lambda i: i < 10, lambda i: i + 3, [paddle.to_tensor(0)])
        assert int(out[0].numpy()) == 12

        @paddle.jit.to_static
        def f(n):
            res = while_loop(lambda i, acc: i < 5, lambda i, acc: (i + 1, acc + n), [paddle.to_tensor(0), paddle.to_tensor(np.float32(0))])
            return res[1]

        r = f(paddle.to_tensor(np.float32(2.0)))
        assert float(r.numpy()) == 10.0

    def test_switch_case(self):
        from paddle_tpu.ops.control_flow import switch_case

        x = paddle.to_tensor(np.array([1.0], np.float32))
        out = switch_case(paddle.to_tensor(1), [lambda: x * 10, lambda: x * 20, lambda: x * 30])
        np.testing.assert_allclose(out.numpy(), [20.0])


class TestDetectionOps:
    def test_roi_align_and_pool_shapes(self):
        from paddle_tpu.vision.ops import roi_align, roi_pool

        feat = paddle.to_tensor(np.random.randn(1, 3, 8, 8).astype(np.float32))
        boxes = paddle.to_tensor(np.array([[0, 0, 7, 7], [2, 2, 6, 6]], np.float32))
        ra = roi_align(feat, boxes, None, 4)
        rp = roi_pool(feat, boxes, None, 4)
        assert list(ra.shape) == [2, 3, 4, 4]
        assert list(rp.shape) == [2, 3, 4, 4]

    def test_roi_pool_max_semantics(self):
        from paddle_tpu.vision.ops import roi_pool

        feat = paddle.to_tensor(np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4))
        out = roi_pool(feat, paddle.to_tensor(np.array([[0, 0, 3, 3]], np.float32)), None, 2)
        # true max-pool of the full RoI into 2x2 bins
        np.testing.assert_allclose(out.numpy()[0, 0], [[5.0, 7.0], [13.0, 15.0]])

    def test_roi_pool_batch_ids(self):
        from paddle_tpu.vision.ops import roi_pool

        feat = np.zeros((2, 1, 4, 4), np.float32)
        feat[1] = 1.0
        boxes = paddle.to_tensor(np.array([[0, 0, 3, 3], [0, 0, 3, 3]], np.float32))
        nums = paddle.to_tensor(np.array([1, 1], np.int32))
        out = roi_pool(paddle.to_tensor(feat), boxes, nums, 2)
        assert out.numpy()[0].max() == 0.0 and out.numpy()[1].min() == 1.0

    def test_deform_conv_offset_layout(self):
        """Interleaved (dy,dx)-per-tap layout: dx of tap0 shifts sampling
        right by one column (reference/mmcv channel order)."""
        from paddle_tpu.vision.ops import deform_conv2d

        x = paddle.to_tensor(np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4))
        w = paddle.to_tensor(np.ones((1, 1, 1, 1), np.float32))
        off = np.zeros((1, 2, 4, 4), np.float32)
        off[:, 1] = 1.0  # dx of the single tap
        out = deform_conv2d(x, paddle.to_tensor(off), w)
        ref = np.arange(16, dtype=np.float32).reshape(4, 4)
        shifted = np.concatenate([ref[:, 1:], np.zeros((4, 1), np.float32)], axis=1)
        np.testing.assert_allclose(out.numpy()[0, 0], shifted, atol=1e-5)

    def test_switch_case_negative_default(self):
        from paddle_tpu.ops.control_flow import switch_case

        x = paddle.to_tensor(np.array([1.0], np.float32))
        out = switch_case(
            paddle.to_tensor(-1), [lambda: x * 10, lambda: x * 20], default=lambda: x * 99
        )
        np.testing.assert_allclose(out.numpy(), [99.0])

    def test_deform_conv_layer_params(self):
        from paddle_tpu.vision.ops import DeformConv2D

        layer = DeformConv2D(2, 4, 3)
        names = [n for n, _ in layer.named_parameters()]
        assert "weight" in names and "bias" in names
        assert DeformConv2D(2, 4, 3, bias_attr=False).bias is None

    def test_deform_conv_zero_offset_equals_conv(self):
        from paddle_tpu.vision.ops import deform_conv2d

        x = paddle.to_tensor(np.random.randn(1, 2, 6, 6).astype(np.float32))
        w = paddle.to_tensor(np.random.randn(3, 2, 3, 3).astype(np.float32) * 0.2)
        off = paddle.to_tensor(np.zeros((1, 18, 4, 4), np.float32))
        np.testing.assert_allclose(
            deform_conv2d(x, off, w).numpy(), F.conv2d(x, w).numpy(), rtol=1e-4, atol=1e-4
        )

    def test_prior_box_and_fpn(self):
        from paddle_tpu.vision.ops import distribute_fpn_proposals, prior_box

        pb, var = prior_box(
            paddle.to_tensor(np.zeros((1, 1, 4, 4), np.float32)),
            paddle.to_tensor(np.zeros((1, 1, 32, 32), np.float32)),
            min_sizes=[8.0], aspect_ratios=[1.0],
        )
        assert list(pb.shape) == [4, 4, 1, 4]
        rois = paddle.to_tensor(np.array([[0, 0, 10, 10], [0, 0, 100, 100]], np.float32))
        outs, restore, nums = distribute_fpn_proposals(rois, 2, 5, 4, 224)
        assert sum(int(o.shape[0]) for o in outs) == 2


class TestText:
    def test_viterbi_brute_force(self):
        import itertools

        emis = np.random.RandomState(3).randn(1, 4, 3).astype(np.float32)
        trans = np.random.RandomState(4).randn(5, 5).astype(np.float32)
        sc, path = paddle.text.viterbi_decode(
            paddle.to_tensor(emis), paddle.to_tensor(trans), paddle.to_tensor(np.array([4]))
        )
        best, bp = -1e30, None
        for seq in itertools.product(range(3), repeat=4):
            s = trans[-2, seq[0]] + emis[0, 0, seq[0]]
            for k in range(1, 4):
                s += trans[seq[k - 1], seq[k]] + emis[0, k, seq[k]]
            s += trans[seq[-1], -1]
            if s > best:
                best, bp = s, seq
        assert abs(best - float(sc.numpy()[0])) < 1e-4
        assert list(bp) == list(path.numpy()[0])


class TestMisc:
    def test_mode_multiplex_rank(self):
        x = paddle.to_tensor(np.array([[1.0, 1.0, 2.0], [3.0, 4.0, 4.0]], np.float32))
        v, i = paddle.mode(x)
        np.testing.assert_allclose(v.numpy(), [1.0, 4.0])
        idx = paddle.to_tensor(np.array([1, 0]))
        out = paddle.multiplex([x, x + 10], idx)
        np.testing.assert_allclose(out.numpy()[0], [11.0, 11.0, 12.0])
        assert int(paddle.rank(x).numpy()) == 2
        assert paddle.is_tensor(x) and paddle.is_floating_point(x)

    def test_inplace_variants(self):
        y = paddle.to_tensor(np.array([1.0, 4.0], np.float32))
        y.sqrt_()
        np.testing.assert_allclose(y.numpy(), [1.0, 2.0])
        y.fill_(7.0)
        np.testing.assert_allclose(y.numpy(), [7.0, 7.0])
        z = paddle.to_tensor(np.array([1.0], np.float32), stop_gradient=False)
        with pytest.raises(RuntimeError):
            z.exp_()

    def test_grid_sample_grad(self):
        x = paddle.to_tensor(np.random.randn(1, 2, 4, 4).astype(np.float32), stop_gradient=False)
        theta = paddle.to_tensor(np.array([[[1, 0, 0], [0, 1, 0]]], np.float32))
        g = F.affine_grid(theta, [1, 2, 4, 4])
        out = F.grid_sample(x, g)
        np.testing.assert_allclose(out.numpy(), x.numpy(), atol=1e-5)
        out.sum().backward()
        assert x.grad is not None

    def test_hsigmoid_margin_ce(self):
        lab = paddle.to_tensor(np.array([0, 1, 2]))
        xh = paddle.to_tensor(np.random.randn(3, 4).astype(np.float32), stop_gradient=False)
        wh = paddle.to_tensor(np.random.randn(7, 4).astype(np.float32))
        hl = F.hsigmoid_loss(xh, lab, 8, wh)
        assert list(hl.shape) == [3, 1]  # per-sample, reference shape
        hl.mean().backward()
        assert xh.grad is not None
        logits = paddle.to_tensor(
            (np.random.rand(3, 8).astype(np.float32) - 0.5) * 1.6, stop_gradient=False
        )
        F.margin_cross_entropy(logits, lab).backward()
        assert logits.grad is not None

    def test_einsum_segment(self):
        x = paddle.to_tensor(np.arange(6, dtype=np.float32).reshape(2, 3), stop_gradient=False)
        out = paddle.einsum("ij,kj->ik", x, x)
        np.testing.assert_allclose(out.numpy(), x.numpy() @ x.numpy().T, rtol=1e-5)
        out.sum().backward()
        assert x.grad is not None
        seg = paddle.segment_mean(
            paddle.to_tensor(np.array([[1.0, 2.0], [3.0, 4.0], [5.0, 6.0]], np.float32)),
            paddle.to_tensor(np.array([0, 0, 1])),
        )
        np.testing.assert_allclose(seg.numpy(), [[2.0, 3.0], [5.0, 6.0]])


class TestTextDatasets:
    def test_uci_housing_trains(self):
        ds = paddle.text.UCIHousing(mode="train")
        x, y = ds[0]
        assert x.shape == (13,) and y.shape == (1,)
        assert len(paddle.text.UCIHousing(mode="test")) > 0

    def test_imdb_and_friends(self):
        imdb = paddle.text.Imdb(mode="train")
        doc, lab = imdb[0]
        assert doc.dtype == np.int64 and lab in (0, 1)
        assert len(paddle.text.Imikolov()[0]) == 5
        words, pred, labels = paddle.text.Conll05st()[0]
        assert words.shape == pred.shape == labels.shape
        row = paddle.text.Movielens()[0]
        assert len(row) == 7


class TestProgramIntrospection:
    """Program IR view over traced computations (reference ProgramDesc/
    BlockDesc/OpDesc introspection, SURVEY §2.1 Program IR row)."""

    def test_linear_program_ops(self):
        from paddle_tpu import nn
        from paddle_tpu.static import InputSpec, Program

        m = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
        prog = Program.from_callable(m, [InputSpec([2, 4], "float32")])
        types = prog.global_block().all_op_types()
        assert types.count("dot_general") == 2
        assert "max" in types  # relu
        v = prog.global_block().vars
        assert any(d.shape == [2, 4] for d in v.values())

    def test_control_flow_subblocks(self):
        from paddle_tpu.ops.control_flow import while_loop
        from paddle_tpu.static import Program

        def f(x):
            out = while_loop(lambda i, a: i < 3, lambda i, a: (i + 1, a * 2),
                             [paddle.to_tensor(0), x])
            return out[1]

        prog = Program.from_callable(f, [paddle.to_tensor(np.ones(2, np.float32))])
        assert any(op.type == "while" for op in prog.global_block().ops)
        assert len(prog.blocks) >= 2  # cond/body sub-blocks like sub-BlockDescs

    def test_to_static_program(self):
        from paddle_tpu.static import InputSpec

        @paddle.jit.to_static
        def f(a):
            return paddle.tanh(a) * 2

        prog = f.program(InputSpec([3], "float32"))
        assert "tanh" in prog.global_block().all_op_types()
