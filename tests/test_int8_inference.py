"""int8 serving path: PTQ calibration → int8 layer swap → jit.save →
Predictor (quantization/convert_to_int8_inference; role of the reference's
slim quantization passes feeding AnalysisPredictor)."""
import os

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.inference import Config, create_predictor
from paddle_tpu.quantization import (
    PostTrainingQuantization,
    convert_to_int8_inference,
)
from paddle_tpu.static import InputSpec


def _calibrated_model():
    paddle.seed(0)
    model = nn.Sequential(
        nn.Conv2D(3, 8, 3, padding=1), nn.ReLU(),
        nn.Conv2D(8, 8, 3, padding=1), nn.ReLU(),
        nn.Flatten(), nn.Linear(8 * 8 * 8, 10),
    )
    model.eval()

    class Calib(paddle.io.Dataset):
        def __len__(self):
            return 4

        def __getitem__(self, i):
            return np.random.RandomState(i).randn(3, 8, 8).astype(np.float32)

    loader = paddle.io.DataLoader(Calib(), batch_size=2, num_workers=0)
    ptq = PostTrainingQuantization(model, data_loader=loader, batch_nums=2)
    ptq.quantize()
    return model, ptq


class TestInt8Inference:
    def test_int8_swap_outputs_close_to_float(self):
        model, ptq = _calibrated_model()
        x = paddle.to_tensor(np.random.RandomState(9).randn(2, 3, 8, 8).astype(np.float32))
        ref = model(x).numpy()
        convert_to_int8_inference(model, ptq)
        got = model(x).numpy()
        # per-tensor int8: coarse but bounded error
        rel = np.abs(got - ref).max() / (np.abs(ref).max() + 1e-6)
        assert rel < 0.12, f"int8 drift {rel:.3f}"

    def test_int8_artifact_through_predictor(self, tmp_path):
        model, ptq = _calibrated_model()
        convert_to_int8_inference(model, ptq)
        x = np.random.RandomState(3).randn(2, 3, 8, 8).astype(np.float32)
        want = model(paddle.to_tensor(x)).numpy()

        prefix = str(tmp_path / "int8net")
        paddle.static.save_inference_model(
            prefix, [InputSpec([2, 3, 8, 8], "float32", name="x")], model
        )
        # int8 constants shrink the artifact: weights are ~4x smaller than f32
        pred = create_predictor(Config(prefix))
        h = pred.get_input_handle(pred.get_input_names()[0])
        h.copy_from_cpu(x)
        pred.run()
        got = pred.get_output_handle(pred.get_output_names()[0]).copy_to_cpu()
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)
