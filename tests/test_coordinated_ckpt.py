"""Coordinated multi-rank checkpoint commit: no crash interleaving may leave
a resume-visible mixed-step checkpoint.

Rank concurrency is simulated with threads sharing a FileStore (each rank has
its own CoordinatedCheckpoint instance); the injection sweep walks the crash
point across serialize → write → ack → commit on each rank and asserts the
two protocol invariants after EVERY interleaving:

1. resume lands ALL ranks on the same step (never mixed);
2. that step is the newest one EVERY rank committed.
"""
import json
import os
import threading

import numpy as np
import pytest

import paddle_tpu
from paddle_tpu.distributed.checkpoint import (
    CheckpointError,
    CoordinatedCheckpoint,
    save_state_dict,
)
from paddle_tpu.distributed.coord import FileStore
from paddle_tpu.fault import inject

pytestmark = pytest.mark.faults

WORLD = 2


@pytest.fixture(autouse=True)
def _disarm():
    inject.disarm()
    yield
    inject.disarm()


def _state(rank, step):
    # distinct per (rank, step) so a mixed restore is detectable by value
    return {"w": paddle_tpu.to_tensor(
        np.full((4,), rank * 100.0 + step, np.float32))}


def _world(tmp_path, **kw):
    store = FileStore(str(tmp_path / "store"))
    return [
        CoordinatedCheckpoint(
            str(tmp_path / "ckpt"), world_size=WORLD, rank=r, store=store,
            commit_timeout_s=kw.pop("commit_timeout_s", 5.0), **dict(kw),
        )
        for r in range(WORLD)
    ]


def _save_all(ranks, step, timeout=30.0):
    """Run every rank's save_now concurrently; returns per-rank results."""
    results = [None] * len(ranks)

    def run(r):
        results[r] = ranks[r].save_now(step, _state(r, step))

    ts = [threading.Thread(target=run, args=(r,)) for r in range(len(ranks))]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout)
    return results


def _resume_all(ranks):
    """Each rank resolves + loads independently; returns (steps, values)."""
    steps, values = [], []
    for r, cc in enumerate(ranks):
        sd = _state(r, -1)
        steps.append(cc.resume(sd))
        values.append(float(np.asarray(sd["w"]._data)[0]))
    return steps, values


class TestHappyPath:
    def test_two_rank_commit_and_resume(self, tmp_path):
        ranks = _world(tmp_path)
        assert _save_all(ranks, 10) == [True, True]
        marker = os.path.join(str(tmp_path / "ckpt"), "step_10",
                              CoordinatedCheckpoint.COMMIT_MARKER)
        rec = json.load(open(marker))
        assert rec["committed"] and rec["world_size"] == WORLD
        steps, values = _resume_all(ranks)
        assert steps == [10, 10]
        assert values == [10.0, 110.0]  # each rank got ITS OWN shard back

    def test_maybe_save_interval(self, tmp_path):
        ranks = _world(tmp_path, interval_steps=5)
        assert ranks[0].maybe_save(3, _state(0, 3)) is False
        assert not os.path.isdir(str(tmp_path / "ckpt" / "step_3"))

    def test_preemption_guard_signature_compat(self, tmp_path):
        # PreemptionGuard.drain calls save_now(step, sd, sync=True)
        store = FileStore(str(tmp_path / "store"))
        cc = CoordinatedCheckpoint(str(tmp_path / "ckpt"), world_size=1,
                                   rank=0, store=store, commit_timeout_s=2.0)
        assert cc.save_now(4, _state(0, 4), sync=True) is True


class TestCrashSweep:
    """The acceptance pin: sweep the crash point across every protocol phase
    on every rank; no interleaving may produce a mixed-step resume."""

    @pytest.mark.parametrize("point", ["ckpt.serialize", "ckpt.write",
                                       "ckpt.ack", "ckpt.commit"])
    @pytest.mark.parametrize("crash_rank", [0, 1])
    def test_crash_point_never_mixes_steps(self, tmp_path, point, crash_rank):
        ranks = _world(tmp_path, commit_timeout_s=1.0)
        assert _save_all(ranks, 1) == [True, True]  # recovery point

        inject.arm({point: {"rank": crash_rank}} if point != "ckpt.write"
                   else {point: {}})  # ckpt.write has no rank ctx: fires once
        try:
            results = _save_all(ranks, 2)
        finally:
            inject.disarm()

        steps, values = _resume_all(ranks)
        assert steps[0] == steps[1], f"mixed-step resume: {steps}"
        landed = steps[0]
        assert landed in (1, 2)
        # value consistency: each rank's shard is from the SAME save
        assert values == [0 * 100.0 + landed, 1 * 100.0 + landed]
        if landed == 2:
            # only possible when every rank's shard was durable + acked —
            # i.e. the "crash" hit after the commit became inevitable
            assert ranks[0]._step_fully_committed(2)
        else:
            # the failed save must not have published a commit marker
            assert not os.path.exists(
                os.path.join(str(tmp_path / "ckpt"), "step_2",
                             CoordinatedCheckpoint.COMMIT_MARKER))
            assert results[crash_rank] is False

    def test_rank0_crash_before_marker_leaves_world_uncommitted(self, tmp_path):
        # the tightest window: every rank acked, marker not yet durable
        ranks = _world(tmp_path, commit_timeout_s=1.0)
        assert _save_all(ranks, 1) == [True, True]
        inject.arm({"ckpt.commit": {"rank": 0}})
        try:
            results = _save_all(ranks, 2)
        finally:
            inject.disarm()
        assert results[0] is False
        # rank 1 times out waiting for the marker — uncommitted for it too
        assert results[1] is False
        steps, _ = _resume_all(ranks)
        assert steps == [1, 1]


class TestManifestAgreement:
    def test_mixed_step_directory_rejected_naming_both_steps(self, tmp_path):
        ranks = _world(tmp_path)
        sdir = tmp_path / "ckpt" / "step_5"
        sdir.mkdir(parents=True)
        # rank manifests written at DIFFERENT steps — corrupt-by-construction
        save_state_dict(_state(0, 5), str(sdir / "rank_0"), step=5)
        save_state_dict(_state(1, 7), str(sdir / "rank_1"), step=7)
        with pytest.raises(CheckpointError) as ei:
            ranks[0].check_manifest_agreement(5)
        msg = str(ei.value)
        assert "step 5" in msg and "step 7" in msg
        # resume refuses loudly rather than walking past corruption
        with pytest.raises(CheckpointError):
            ranks[0].resume(_state(0, -1))

    def test_walkback_lands_on_newest_step_every_rank_committed(self, tmp_path):
        ranks = _world(tmp_path)
        assert _save_all(ranks, 100) == [True, True]
        # step 200: rank 0 wrote its shard, rank 1 died first — no marker
        sdir = tmp_path / "ckpt" / "step_200"
        sdir.mkdir(parents=True)
        save_state_dict(_state(0, 200), str(sdir / "rank_0"), step=200)
        steps, values = _resume_all(ranks)
        assert steps == [100, 100]
        assert values == [100.0, 200.0]

    def test_marker_without_all_manifests_not_committed(self, tmp_path):
        ranks = _world(tmp_path)
        assert _save_all(ranks, 100) == [True, True]
        sdir = tmp_path / "ckpt" / "step_300"
        sdir.mkdir(parents=True)
        save_state_dict(_state(0, 300), str(sdir / "rank_0"), step=300)
        # a forged/partial marker: rank 1's manifest is missing
        ranks[0]._write_marker(300)
        assert not ranks[0]._step_fully_committed(300)
        steps, _ = _resume_all(ranks)
        assert steps == [100, 100]

    def test_store_resume_agreement_rejects_disagreement(self, tmp_path):
        ranks = _world(tmp_path, commit_timeout_s=1.0)
        # rank 1 claims it resolved step 9; rank 0 resolved step 5
        ranks[0].store.set("ckpt/resume/1", "9")
        with pytest.raises(CheckpointError, match="disagree"):
            ranks[0]._agree_on_resume_step(5)

    def test_agreed_step_load_failure_raises_not_walks_back(self, tmp_path):
        # once the world AGREED on a step, a rank whose shard fails to load
        # must raise — silently walking back to an older step while peers
        # load the agreed one is exactly the mixed-step state the protocol
        # forbids
        ranks = _world(tmp_path, commit_timeout_s=1.0)
        assert _save_all(ranks, 1) == [True, True]
        assert _save_all(ranks, 2) == [True, True]
        # bitrot rank 1's step-2 shard AFTER commit: manifest still says
        # committed, checksum verify fails on load
        man_path = str(tmp_path / "ckpt" / "step_2" / "rank_1.manifest.json")
        man = json.load(open(man_path))
        key = next(iter(man["tree"]))
        man["tree"][key]["crc32"] = (man["tree"][key]["crc32"] ^ 1)
        json.dump(man, open(man_path, "w"))
        # rank 0 resolves step 2 (agreement advisory — peer vote absent —
        # but its vote stays on the store)...
        assert ranks[0].resume(_state(0, -1)) == 2
        # ...so rank 1's agreement is FULL and unanimous at step 2; its
        # corrupt shard must abort the resume, not fall back to step 1
        with pytest.raises(CheckpointError, match="agreed to resume"):
            ranks[1].resume(_state(1, -1))


class TestStaleAckLitter:
    """A crashed save attempt leaves acks/commit litter on the store; a
    relaunched job replaying to the same step must not count it."""

    def test_commit_barrier_reset_clears_litter(self, tmp_path):
        from paddle_tpu.distributed.coord import CommitBarrier

        st = FileStore(str(tmp_path))
        b = CommitBarrier(st, 2, 0)
        b.ack("s7")
        st.set("commit/s7/commit", "{}")
        b.reset("s7")
        assert b.acks("s7") == 0 and not b.committed("s7")

    def test_stale_acks_cannot_commit_a_retried_save_early(self, tmp_path):
        ranks = _world(tmp_path, commit_timeout_s=1.0)
        assert _save_all(ranks, 1) == [True, True]
        # dead attempt at step 2 left a FULL ack count behind
        ranks[0].store.set("ckpt/2/acks", str(WORLD))
        # rank 0 alone retries the save: without the entry reset it would
        # see world_size stale acks and commit a step rank 1 never wrote
        assert ranks[0].save_now(2, _state(0, 2)) is False
        assert not os.path.exists(
            os.path.join(str(tmp_path / "ckpt"), "step_2",
                         CoordinatedCheckpoint.COMMIT_MARKER))
        steps, _ = _resume_all(ranks)
        assert steps == [1, 1]

    def test_retried_save_over_litter_commits_normally(self, tmp_path):
        ranks = _world(tmp_path)
        ranks[0].store.set("ckpt/2/acks", str(WORLD))  # stale litter
        assert _save_all(ranks, 2) == [True, True]
        steps, values = _resume_all(ranks)
        assert steps == [2, 2]
        assert values == [2.0, 102.0]


class TestGC:
    def test_gc_keeps_newest_committed(self, tmp_path):
        ranks = _world(tmp_path, keep_last=1)
        assert _save_all(ranks, 1) == [True, True]
        assert _save_all(ranks, 2) == [True, True]
        # uncommitted litter from a crashed later save
        sdir = tmp_path / "ckpt" / "step_3"
        sdir.mkdir(parents=True)
        save_state_dict(_state(0, 3), str(sdir / "rank_0"), step=3)
        ranks[0]._gc()
        root = tmp_path / "ckpt"
        assert not (root / "step_1").exists()   # GC'd
        assert (root / "step_2").exists()       # newest committed: protected
        assert (root / "step_3").exists()       # within keep_last window
        steps, _ = _resume_all(ranks)
        assert steps == [2, 2]

    def test_resume_empty_dir_returns_minus_one(self, tmp_path):
        ranks = _world(tmp_path)
        steps, _ = _resume_all(ranks)
        assert steps == [-1, -1]
