"""Distributed tests on the 8-device virtual CPU mesh.

Methodology per SURVEY.md §4: loss/numeric parity between single-device and
N-device sharded execution (the reference's multiprocess TestDistBase trick,
here pure SPMD).
"""
import math

import numpy as np
import pytest
import jax
import jax.numpy as jnp
from jax import lax
from paddle_tpu.core.compat import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed.collective import Group


def _mesh(axes, shape):
    devs = np.asarray(jax.devices()[: int(np.prod(shape))]).reshape(shape)
    return Mesh(devs, axes)


class TestCollectives:
    def test_psum_under_shard_map(self):
        mesh = _mesh(("x",), (4,))

        def f(a):
            t = paddle.Tensor(a, stop_gradient=True)
            out = paddle.distributed.all_reduce(t, group=Group(axis_name="x"))
            return out._data

        data = np.arange(4, dtype=np.float32).reshape(4, 1)
        out = jax.jit(shard_map(f, mesh=mesh, in_specs=P("x"), out_specs=P("x")))(data)
        np.testing.assert_allclose(np.asarray(out).reshape(-1), [6, 6, 6, 6])

    def test_all_gather(self):
        mesh = _mesh(("x",), (4,))

        def f(a):
            t = paddle.Tensor(a, stop_gradient=True)
            return paddle.distributed.all_gather(None, t, group=Group(axis_name="x"))._data

        data = np.arange(4, dtype=np.float32).reshape(4, 1)
        out = jax.jit(shard_map(f, mesh=mesh, in_specs=P("x"), out_specs=P(None, "x")))(data)
        assert np.asarray(out).size == 16

    def test_eager_single_process_identity(self):
        t = paddle.to_tensor(np.ones(3, np.float32))
        out = paddle.distributed.all_reduce(t)
        np.testing.assert_array_equal(out.numpy(), t.numpy())


class TestTensorParallel:
    def test_column_row_parity_gspmd(self):
        """Megatron-sharded GPT matmuls under GSPMD == dense single-device."""
        paddle.seed(0)
        from paddle_tpu.distributed.fleet.meta_parallel.mp_layers import (
            ColumnParallelLinear, RowParallelLinear,
        )

        col = ColumnParallelLinear(8, 16, has_bias=True, gather_output=False)
        row = RowParallelLinear(16, 8, has_bias=True, input_is_parallel=True)
        x = np.random.rand(4, 8).astype(np.float32)

        # dense reference
        ref = (x @ col.weight.numpy() + col.bias.numpy()) @ row.weight.numpy() + row.bias.numpy()

        mesh = _mesh(("mp",), (4,))
        wc = jax.device_put(col.weight._data, NamedSharding(mesh, P(None, "mp")))
        bc = jax.device_put(col.bias._data, NamedSharding(mesh, P("mp")))
        wr = jax.device_put(row.weight._data, NamedSharding(mesh, P("mp", None)))
        br = jax.device_put(row.bias._data, NamedSharding(mesh, P()))

        @jax.jit
        def f(x, wc, bc, wr, br):
            return (x @ wc + bc) @ wr + br

        out = f(jnp.asarray(x), wc, bc, wr, br)
        np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-5)

    def test_mp_layers_shard_map_parity(self):
        """Explicit shard_map Megatron path == dense (c_identity/c_split/psum)."""
        paddle.seed(1)
        from paddle_tpu.distributed.fleet.meta_parallel.mp_layers import (
            ColumnParallelLinear, RowParallelLinear,
        )

        mesh = _mesh(("mp",), (4,))
        g = Group(axis_name="mp", nranks=4)
        col = ColumnParallelLinear(8, 16, has_bias=False, gather_output=False, mp_group=g)
        row = RowParallelLinear(16, 8, has_bias=False, input_is_parallel=True, mp_group=g)
        x = np.random.rand(4, 8).astype(np.float32)
        ref = (x @ col.weight.numpy()) @ row.weight.numpy()

        def f(xa, wc, wr):
            saved = (col.weight._data, row.weight._data)
            try:
                col.weight._data = wc
                row.weight._data = wr
                with paddle.no_grad():
                    out = row(col(paddle.Tensor(xa, stop_gradient=True)))
                return out._data
            finally:
                col.weight._data, row.weight._data = saved

        smapped = shard_map(
            f, mesh=mesh,
            in_specs=(P(), P(None, "mp"), P("mp", None)),
            out_specs=P(),
            check_vma=False,
        )
        out = jax.jit(smapped)(x, col.weight._data, row.weight._data)
        np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-4, atol=1e-5)

    def test_parallel_cross_entropy_parity(self):
        """Vocab-sharded softmax CE == dense CE (reference collective.py:1032)."""
        from paddle_tpu.distributed.collective import _c_softmax_with_cross_entropy

        paddle.seed(2)
        V = 16
        logits = np.random.randn(6, V).astype(np.float32)
        labels = np.random.randint(0, V, (6,))
        ref = -np.log(
            np.exp(logits - logits.max(-1, keepdims=True))
            / np.exp(logits - logits.max(-1, keepdims=True)).sum(-1, keepdims=True)
        )[np.arange(6), labels]

        mesh = _mesh(("mp",), (4,))
        g = Group(axis_name="mp", nranks=4)

        def f(lg, lb):
            out = _c_softmax_with_cross_entropy(
                paddle.Tensor(lg, stop_gradient=True), paddle.Tensor(lb, stop_gradient=True), group=g
            )
            return out._data

        smapped = shard_map(f, mesh=mesh, in_specs=(P(None, "mp"), P()), out_specs=P(), check_vma=False)
        out = jax.jit(smapped)(logits, labels)
        np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-4)


class TestDataParallel:
    def test_dp_training_parity_with_single_device(self):
        """dp=8 sharded engine step == single-device step (loss parity —
        the reference's TestDistBase assertion)."""
        paddle.seed(5)
        from paddle_tpu.distributed.engine import HybridParallelEngine

        def make():
            paddle.seed(7)
            m = nn.Sequential(nn.Linear(8, 16), nn.Tanh(), nn.Linear(16, 4))
            o = paddle.optimizer.SGD(learning_rate=0.1, parameters=m.parameters())
            return m, o

        x = np.random.rand(16, 8).astype(np.float32)
        y = np.random.rand(16, 4).astype(np.float32)

        def loss_fn(m, xb, yb):
            return ((m(xb) - yb) ** 2).mean()

        # single device eager
        m1, o1 = make()
        for _ in range(3):
            loss = loss_fn(m1, paddle.to_tensor(x), paddle.to_tensor(y))
            loss.backward()
            o1.step()
            o1.clear_grad()
        single_w = m1[0].weight.numpy()

        # dp=8 sharded
        m2, o2 = make()
        mesh = _mesh(("dp",), (8,))
        eng = HybridParallelEngine(m2, o2, loss_fn, mesh=mesh)
        for _ in range(3):
            eng.train_step(paddle.to_tensor(x), paddle.to_tensor(y))
        np.testing.assert_allclose(m2[0].weight.numpy(), single_w, rtol=1e-4, atol=1e-5)

    def test_zero1_state_sharding_parity(self):
        """ZeRO-1 (opt state sharded over dp) == unsharded Adam."""
        paddle.seed(11)
        from paddle_tpu.distributed.engine import HybridParallelEngine
        from paddle_tpu.distributed.fleet.meta_parallel.sharding import shard_spec_for

        def make():
            paddle.seed(13)
            m = nn.Linear(8, 8)
            o = paddle.optimizer.Adam(learning_rate=0.01, parameters=m.parameters())
            return m, o

        x = np.random.rand(8, 8).astype(np.float32)
        y = np.random.rand(8, 8).astype(np.float32)

        def loss_fn(m, xb, yb):
            return ((m(xb) - yb) ** 2).mean()

        m1, o1 = make()
        for _ in range(3):
            loss = loss_fn(m1, paddle.to_tensor(x), paddle.to_tensor(y))
            loss.backward()
            o1.step()
            o1.clear_grad()

        m2, o2 = make()
        mesh = _mesh(("dp",), (8,))
        for p in m2.parameters():
            p.opt_state_pspec = shard_spec_for(p, "dp", 8)
        eng = HybridParallelEngine(m2, o2, loss_fn, mesh=mesh)
        for _ in range(3):
            eng.train_step(paddle.to_tensor(x), paddle.to_tensor(y))
        np.testing.assert_allclose(m2.weight.numpy(), m1.weight.numpy(), rtol=1e-4, atol=1e-5)


class TestZeRO23:
    """Stage 2/3 must be materially different from stage 1 (VERDICT r1 weak
    #3): stage 2 pins grads to a reduce-scatter layout, stage 3 physically
    shards the params. Parity + layout assertions."""

    def _data(self):
        rng = np.random.RandomState(3)
        return rng.rand(16, 8).astype(np.float32), rng.rand(16, 8).astype(np.float32)

    def _make(self):
        paddle.seed(17)
        m = nn.Sequential(nn.Linear(8, 32), nn.Tanh(), nn.Linear(32, 8))
        o = paddle.optimizer.Adam(learning_rate=0.01, parameters=m.parameters())
        return m, o

    @staticmethod
    def _loss(m, xb, yb):
        return ((m(xb) - yb) ** 2).mean()

    def test_stage2_grads_reduce_scattered(self):
        """grad_pspec consumption is observable: the stage-2 program carries
        MORE @Sharding constraints than stage-1 (one per grad), so stage2
        cannot silently degenerate to stage1."""
        from paddle_tpu.distributed.engine import HybridParallelEngine
        from paddle_tpu.distributed.fleet.meta_parallel.sharding import (
            ShardingOptimizerStage1, ShardingStage2,
        )
        from paddle_tpu.distributed.collective import Group

        x, y = self._data()
        group = Group(axis_name="sharding", nranks=8)
        mesh = _mesh(("sharding",), (8,))

        # stage 1: opt-state pspecs only
        m1, o1 = self._make()
        s1_opt = ShardingOptimizerStage1(o1, group=group)
        eng1 = HybridParallelEngine(m1, o1, self._loss, mesh=mesh, dp_axes=())
        text1 = eng1.lower_text(paddle.to_tensor(x), paddle.to_tensor(y))

        # stage 2: + grad_pspec
        m2, o2 = self._make()
        s2 = ShardingStage2(m2, ShardingOptimizerStage1(o2, group=group), group=group)
        eng2 = HybridParallelEngine(s2, o2, self._loss, mesh=mesh, dp_axes=())
        text2 = eng2.lower_text(paddle.to_tensor(x), paddle.to_tensor(y))

        def count(text):  # GSPMD custom-call or Shardy dialect form
            return text.count("@Sharding") + text.count("sdy.sharding_constraint")

        n1 = count(text1)
        n2 = count(text2)
        n_grads = len([p for p in m2.parameters() if not p.stop_gradient])
        assert n2 >= n1 + n_grads, (n1, n2, n_grads)

        # and numerically still correct vs plain single-device training
        m0, o0 = self._make()
        for _ in range(3):
            loss = self._loss(m0, paddle.to_tensor(x), paddle.to_tensor(y))
            loss.backward()
            o0.step()
            o0.clear_grad()
        for _ in range(3):
            eng2.train_step(paddle.to_tensor(x), paddle.to_tensor(y))
        np.testing.assert_allclose(
            m2[0].weight.numpy(), m0[0].weight.numpy(), rtol=1e-4, atol=1e-5
        )

    def test_stage3_params_physically_sharded(self):
        from paddle_tpu.distributed.engine import HybridParallelEngine
        from paddle_tpu.distributed.fleet.meta_parallel.sharding import ShardingStage3
        from paddle_tpu.distributed.collective import Group

        x, y = self._data()
        group = Group(axis_name="sharding", nranks=8)
        mesh = _mesh(("sharding",), (8,))

        m3, o3 = self._make()
        s3 = ShardingStage3(m3, o3, group=group)
        eng3 = HybridParallelEngine(s3, o3, self._loss, mesh=mesh, dp_axes=())
        eng3.place()
        # each device holds 1/8 of each shardable param (true ZeRO-3 memory)
        w = m3[0].weight  # (8, 32): dim0 divisible by 8
        shard = w._data.addressable_shards[0].data
        assert shard.shape[0] * 8 == w._data.shape[0], (shard.shape, w._data.shape)

        # parity vs plain training
        m0, o0 = self._make()
        for _ in range(3):
            loss = self._loss(m0, paddle.to_tensor(x), paddle.to_tensor(y))
            loss.backward()
            o0.step()
            o0.clear_grad()
        for _ in range(3):
            eng3.train_step(paddle.to_tensor(x), paddle.to_tensor(y))
        np.testing.assert_allclose(
            m3[0].weight.numpy(), m0[0].weight.numpy(), rtol=1e-4, atol=1e-5
        )

    def test_grad_accumulate_matches_full_batch(self):
        """grad_accumulate=4: mean-of-chunk gradients == full-batch gradient
        for mean losses, so training must match exactly."""
        from paddle_tpu.distributed.engine import HybridParallelEngine

        x, y = self._data()
        mesh = _mesh(("dp",), (8,))

        ma, oa = self._make()
        enga = HybridParallelEngine(ma, oa, self._loss, mesh=mesh)
        mb, ob = self._make()
        engb = HybridParallelEngine(mb, ob, self._loss, mesh=mesh, grad_accumulate=4)
        for _ in range(3):
            la = enga.train_step(paddle.to_tensor(x), paddle.to_tensor(y))
            lb = engb.train_step(paddle.to_tensor(x), paddle.to_tensor(y))
        np.testing.assert_allclose(float(la.item()), float(lb.item()), rtol=1e-5)
        np.testing.assert_allclose(
            ma[0].weight.numpy(), mb[0].weight.numpy(), rtol=1e-4, atol=1e-6
        )


class TestHybridGPT:
    def test_gpt_hybrid_step_matches_dense(self):
        """dp*mp sharded GPT train step == single-device (same seed)."""
        from paddle_tpu.models.gpt import GPTForPretraining, gpt_tiny
        from paddle_tpu.distributed.engine import HybridParallelEngine

        def make():
            paddle.seed(21)
            cfg = gpt_tiny(hidden_dropout=0.0, attention_dropout=0.0)
            m = GPTForPretraining(cfg)
            o = paddle.optimizer.SGD(learning_rate=0.01, parameters=m.parameters())
            return m, o, cfg

        m1, o1, cfg = make()
        ids = np.random.randint(0, cfg.vocab_size, (4, 32))
        labels = np.random.randint(0, cfg.vocab_size, (4, 32))

        def loss_fn(m, i, l):
            return m.loss(i, l)

        loss1 = loss_fn(m1, paddle.to_tensor(ids), paddle.to_tensor(labels))
        loss1.backward()
        o1.step()

        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 4, "pp_degree": 1, "sharding_degree": 1, "sp_degree": 1}
        fleet.init(is_collective=True, strategy=strategy)
        hcg = fleet.get_hybrid_communicate_group()
        m2, o2, _ = make()
        eng = HybridParallelEngine(m2, o2, loss_fn, mesh=hcg.mesh)
        loss2 = eng.train_step(paddle.to_tensor(ids), paddle.to_tensor(labels))
        np.testing.assert_allclose(float(loss1.item()), float(loss2.item()), rtol=1e-4)
        w1 = m1.gpt.embeddings.word_embeddings.weight.numpy()
        w2 = m2.gpt.embeddings.word_embeddings.weight.numpy()
        np.testing.assert_allclose(w1, w2, rtol=1e-3, atol=1e-5)


class TestFleetPipeline:
    """fleet pp_degree=4 path: train_batch must ACTUALLY pipeline (ppermute
    schedule with per-stage switch bodies) and match sequential training."""

    VOCAB, D, SEQ, B = 64, 16, 12, 8

    def _build(self):
        from paddle_tpu.distributed.fleet.meta_parallel import LayerDesc, PipelineLayer

        V, D = self.VOCAB, self.D

        class Embed(nn.Layer):
            def __init__(self):
                super().__init__()
                self.emb = nn.Embedding(V, D)

            def forward(self, ids):
                return self.emb(ids)

        class Block(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc = nn.Linear(D, D)

            def forward(self, x):
                return x + paddle.tanh(self.fc(x))

        class Head(nn.Layer):
            def __init__(self):
                super().__init__()
                self.proj = nn.Linear(D, V)

            def forward(self, x):
                return self.proj(x)

        ce = nn.CrossEntropyLoss()

        def loss_fn(logits, labels):
            return ce(logits.reshape([-1, V]), labels.reshape([-1]))

        descs = [LayerDesc(Embed)] + [LayerDesc(Block) for _ in range(6)] + [LayerDesc(Head)]
        return PipelineLayer(descs, num_stages=4, loss_fn=loss_fn)

    def test_fleet_pp4_matches_sequential(self):
        from paddle_tpu.distributed import fleet
        from paddle_tpu.jit import CompiledTrainStep

        rng = np.random.RandomState(5)
        ids = rng.randint(0, self.VOCAB, (self.B, self.SEQ))
        labels = rng.randint(0, self.VOCAB, (self.B, self.SEQ))

        # sequential baseline (same weights via same seed)
        paddle.seed(7)
        m1 = self._build()
        o1 = paddle.optimizer.SGD(learning_rate=0.1, parameters=m1.parameters())
        lf = m1._loss_fn

        def full_loss(model, x, y):
            return lf(model(x), y)

        step = CompiledTrainStep(m1, full_loss, o1)
        seq_losses = [float(step(paddle.to_tensor(ids), paddle.to_tensor(labels)).item()) for _ in range(3)]

        # pipelined fleet path on pp=4
        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": 1, "mp_degree": 1, "pp_degree": 4, "sharding_degree": 1, "sp_degree": 1}
        strategy.pipeline_configs = {"accumulate_steps": 4, "micro_batch_size": 2, "schedule_mode": "1F1B"}
        fleet.init(is_collective=True, strategy=strategy)
        paddle.seed(7)
        m2 = self._build()
        o2 = paddle.optimizer.SGD(learning_rate=0.1, parameters=m2.parameters())
        pp_model = fleet.distributed_model(m2)
        from paddle_tpu.distributed.fleet.meta_parallel.pipeline_parallel import PipelineTrainStep

        pp_losses = [
            float(pp_model.train_batch((paddle.to_tensor(ids), paddle.to_tensor(labels)), o2).item())
            for _ in range(3)
        ]
        # must have gone through the real pipeline, not the fused fallback
        assert isinstance(pp_model._train_fn, PipelineTrainStep)

        np.testing.assert_allclose(seq_losses, pp_losses, rtol=2e-4, atol=1e-5)
        # weights advanced identically
        w1 = np.asarray(m1.parameters()[0]._data)
        w2 = np.asarray(m2.parameters()[0]._data)
        np.testing.assert_allclose(w1, w2, rtol=1e-3, atol=1e-5)


class TestPipelineSPMD:
    def test_pipeline_matches_sequential(self):
        from paddle_tpu.distributed.fleet.meta_parallel.pipeline_parallel import spmd_pipeline_fn

        pp, n_micro, D = 4, 6, 8
        Ws = np.random.randn(pp, D, D).astype(np.float32) * 0.3
        mbs = np.random.randn(n_micro, 3, D).astype(np.float32)

        def stage_fn(w, x):
            return jnp.tanh(x @ w)

        # sequential reference
        ref = []
        for i in range(n_micro):
            h = mbs[i]
            for s in range(pp):
                h = np.tanh(h @ Ws[s])
            ref.append(h)
        ref = np.stack(ref)

        mesh = _mesh(("pp",), (pp,))
        pipe = spmd_pipeline_fn(stage_fn, pp, n_micro, axis="pp")
        smapped = shard_map(
            lambda w, mb: pipe(w[0], mb),
            mesh=mesh, in_specs=(P("pp"), P()), out_specs=P("pp"), check_vma=False,
        )
        out = np.asarray(jax.jit(smapped)(Ws, mbs))
        # outputs valid on last stage → gathered dim0 = pp blocks of n_micro
        last = out.reshape(pp, n_micro, 3, D)[-1]
        np.testing.assert_allclose(last, ref, rtol=1e-4, atol=1e-5)


class TestMoE:
    def test_moe_layer_forward_backward(self):
        paddle.seed(31)
        from paddle_tpu.distributed.fleet.meta_parallel.moe_layer import MoELayer

        layer = MoELayer(d_model=8, d_hidden=16, n_experts=4, top_k=2)
        x = paddle.to_tensor(np.random.rand(2, 6, 8).astype(np.float32), stop_gradient=False)
        out = layer(x)
        assert out.shape == [2, 6, 8]
        (out.sum() + layer.aux_loss if isinstance(layer.aux_loss, paddle.Tensor) else out.sum()).backward()
        assert layer.w_up.grad is not None


class TestShardingAPI:
    def test_shard_tensor_places(self):
        mesh = _mesh(("dp",), (8,))
        from paddle_tpu.distributed import shard_tensor
        from paddle_tpu.distributed.mesh import set_global_mesh

        set_global_mesh(mesh)
        t = paddle.to_tensor(np.random.rand(16, 4).astype(np.float32))
        shard_tensor(t, mesh, [  "dp", None])
        assert len(t._data.sharding.device_set) == 8
