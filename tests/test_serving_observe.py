"""Serving SLO observability (ISSUE 20) — request-scoped tracing,
token-latency histograms, the telemetry endpoint, and cost-model drift.

Pins the acceptance surface on the tier-1 (in-process, CPU-fast) side:

* a traced multi-stream drive yields exactly ONE completed timeline per
  request (queue → prefill → decode steps), with EXACT histogram counts
  (TTFT / inter-token / e2e / queue-wait) keyed by priority class;
* the trace id survives the four hard paths — preemption + re-prefill,
  supervisor crash recovery (with and without snapshot re-attach),
  engine→engine handoff, and chunked prefill — one timeline per request,
  no orphan or duplicate trace ids;
* ``/metrics`` is valid Prometheus text with le-cumulative histograms,
  ``/healthz`` flips 200→503 on an injected wedge, ``/readyz`` follows
  the rolling-restart contract, ``/debug/requests`` shows live trace ids,
  and the supervisor owns the port across a restart;
* all three cost-model drift gauges (step_eta, hbm_admission,
  kernel_estimate) go live from their real call sites;
* the whole layer is inert when unconfigured: no import, no threads, and
  monkeypatch-exploded hooks prove the flag-off scheduler never calls one.
"""
import contextlib
import json
import re
import socket
import threading
import time

import numpy as np
import pytest

from paddle_tpu import profiler
from paddle_tpu.fault import inject
from paddle_tpu.framework import flags
from paddle_tpu.serving import (
    Engine, Readiness, ServeError, ServingSupervisor, observe,
)
from serving_util import ENGINE_KW, make_prompts as _prompts, tiny_gpt

_KW = dict(ENGINE_KW)
_TRACED = dict(_KW, trace=True)
_MISS = object()


@pytest.fixture(scope="module")
def model():
    return tiny_gpt()


@pytest.fixture(autouse=True)
def _clean():
    observe.reset()
    yield
    inject.disarm()
    observe.reset()


@contextlib.contextmanager
def _flags(**kv):
    old = {k: flags._FLAGS.get(k, _MISS) for k in kv}
    flags._FLAGS.update(kv)
    try:
        yield
    finally:
        for k, v in old.items():
            if v is _MISS:
                flags._FLAGS.pop(k, None)
            else:
                flags._FLAGS[k] = v


def _drive(eng, prompts, max_new=4, **kw):
    hs = [eng.submit(p, max_new_tokens=max_new, **kw) for p in prompts]
    return [h.result(timeout=600) for h in hs]


def _get(port, path):
    from urllib.error import HTTPError
    from urllib.request import urlopen

    try:
        with urlopen(f"http://127.0.0.1:{port}{path}", timeout=30) as r:
            return r.status, r.read().decode()
    except HTTPError as e:
        return e.code, e.read().decode()


def _event_names(tl):
    return [ev["name"] for ev in tl["events"]]


# -- timelines + exact histogram counts ---------------------------------------

class TestTimelines:
    def test_multi_stream_drive_one_timeline_per_request(self, model, tmp_path):
        """THE acceptance pin: a 64-stream drive yields one complete
        timeline per request (queue → prefill → decode_step, outcome ok)
        with unique trace ids and EXACT histogram counts."""
        rng = np.random.RandomState(40)
        prompts = _prompts(64, rng)
        max_new = 4
        with Engine(model, **_TRACED) as eng:
            outs = _drive(eng, prompts, max_new=max_new)
        assert all(len(o) == len(p) + max_new
                   for o, p in zip(outs, prompts))
        book = observe.trace_book()
        done = book.completed()
        assert len(done) == 64
        assert len({tl["trace"] for tl in done}) == 64  # no dup/orphan ids
        assert book.open_traces() == {}                 # nothing leaked open
        for tl in done:
            names = _event_names(tl)
            assert tl["outcome"] == "ok"
            assert "queue" in names
            assert "prefill" in names
            assert "decode_step" in names
            assert tl["t_close"] >= tl["t_open"]
        # exact SLO histogram counts: 1 TTFT + 1 e2e + 1 queue wait per
        # request, max_new-1 inter-token gaps (prefill emits token #1)
        snap = observe.slo().snapshot()

        def total(metric):
            return sum(s["count"] for s in snap.get(metric, {}).values())

        assert total("serve_ttft_seconds") == 64
        assert total("serve_e2e_seconds") == 64
        assert total("serve_queue_seconds") == 64
        assert total("serve_inter_token_seconds") == 64 * (max_new - 1)
        # exports: chrome-trace document + one JSONL line per timeline
        ct = tmp_path / "trace.json"
        jl = tmp_path / "trace.jsonl"
        book.chrome_trace(str(ct))
        book.jsonl(str(jl))
        doc = json.loads(ct.read_text())
        metas = [e for e in doc["traceEvents"] if e["ph"] == "M"]
        assert len(metas) == 64  # one display thread per request
        lines = [json.loads(ln) for ln in jl.read_text().splitlines()]
        assert len(lines) == 64
        assert {ln["trace"] for ln in lines} == {tl["trace"] for tl in done}

    def test_priority_classes_key_histograms(self, model):
        rng = np.random.RandomState(41)
        prompts = _prompts(8, rng)
        with Engine(model, **_TRACED) as eng:
            hs = [eng.submit(p, max_new_tokens=3, priority=i % 2)
                  for i, p in enumerate(prompts)]
            for h in hs:
                h.result(timeout=300)
        snap = observe.slo().snapshot()["serve_ttft_seconds"]
        assert set(snap) == {"0", "1"}
        assert snap["0"]["count"] == 4
        assert snap["1"]["count"] == 4
        # percentile merges classes unless one is named
        assert observe.percentile("serve_ttft_seconds", 0.5) > 0.0
        assert observe.percentile("serve_ttft_seconds", 0.5, priority=1) > 0.0

    def test_histogram_bucket_semantics(self):
        h = observe.Histogram((0.1, 1.0))
        for v in (0.05, 0.1, 0.5, 5.0):
            h.observe(v)
        s = h.snapshot()
        assert s["counts"] == [2, 1, 1]       # le=0.1 holds 0.05 AND 0.1
        assert s["cumulative"] == [2, 3, 4]   # Prometheus le-cumulative
        assert s["count"] == 4
        assert abs(s["sum"] - 5.65) < 1e-9

    def test_percentile_empty_is_zero(self):
        assert observe.percentile("serve_ttft_seconds", 0.99) == 0.0


# -- trace-id continuity across the hard paths --------------------------------

class TestTraceContinuity:
    def test_preemption_reprefill_stays_one_timeline(self, model):
        """Pool pressure forces evict + re-prefill: the victim's timeline
        keeps its trace id — evict and BOTH prefills land on ONE record."""
        rng = np.random.RandomState(42)
        prompts = [rng.randint(0, 211, (8,)).tolist() for _ in range(4)]
        with Engine(model, trace=True, block_size=8, num_blocks=10,
                    max_batch=4, max_seq_len=72) as eng:
            outs = _drive(eng, prompts, max_new=24)
        assert all(len(o) == 32 for o in outs)
        done = observe.trace_book().completed()
        assert len(done) == 4
        assert len({tl["trace"] for tl in done}) == 4
        assert observe.trace_book().open_traces() == {}
        assert all(tl["outcome"] == "ok" for tl in done)
        evicted = [tl for tl in done if "evict" in _event_names(tl)]
        assert evicted, "geometry must force at least one eviction"
        for tl in evicted:
            # re-admission re-prefills: ≥ 2 prefill events, same timeline
            assert _event_names(tl).count("prefill") >= 2

    def test_crash_recovery_trace_continuity(self, model):
        """A supervised crash requeues work as continuation requests that
        RE-ATTACH the original trace ids: one timeline per request, with
        the recovery relay as the last hop, and bit-identical output."""
        rng = np.random.RandomState(43)
        prompts = _prompts(6, rng)
        with Engine(model, **_KW) as eng:
            baseline = _drive(eng, prompts, max_new=8)
        observe.reset()
        inject.arm("serve.crash:at=4")  # 4th scheduler step: mid-decode
        with ServingSupervisor(model, watchdog_s=4.0, **_TRACED) as sup:
            outs = _drive(sup, prompts, max_new=8)
            assert sup.restarts == 1
        assert outs == baseline
        done = observe.trace_book().completed()
        assert len(done) == 6
        assert len({tl["trace"] for tl in done}) == 6
        assert observe.trace_book().open_traces() == {}
        assert all(tl["outcome"] == "ok" for tl in done)
        # the relay lands on the recovered requests' timelines (done-ring
        # fallback: the continuation may close before the relay thread runs)
        relayed = [tl for tl in done if "relay" in _event_names(tl)]
        assert relayed, "crash recovery must stamp relay events"
        for tl in relayed:
            ev = [e for e in tl["events"] if e["name"] == "relay"][-1]
            assert ev["attrs"]["error"] is None

    def test_snapshot_reattach_trace_continuity(self, model):
        """Crash recovery through the snapshot re-attach path keeps the
        same one-timeline-per-request contract."""
        rng = np.random.RandomState(44)
        prompts = _prompts(6, rng)
        with Engine(model, **_KW) as eng:
            baseline = _drive(eng, prompts, max_new=8)
        observe.reset()
        inject.arm("serve.crash:at=4")
        with ServingSupervisor(model, watchdog_s=4.0, snapshot=True,
                               **_TRACED) as sup:
            outs = _drive(sup, prompts, max_new=8)
            assert sup.restarts == 1
            assert sup.health()["last_recovery"]["mode"] in (
                "reattach", "reprefill")
        assert outs == baseline
        done = observe.trace_book().completed()
        assert len(done) == 6
        assert len({tl["trace"] for tl in done}) == 6
        assert observe.trace_book().open_traces() == {}
        assert all(tl["outcome"] == "ok" for tl in done)

    def test_handoff_trace_continuity(self, model):
        """Engine→engine handoff: the successor's spans land on the SAME
        timelines the predecessor opened (the book is process-global)."""
        rng = np.random.RandomState(45)
        prompts = _prompts(6, rng)
        old = Engine(model, **_TRACED)
        try:
            hs = [old.submit(p, max_new_tokens=10) for p in prompts]
            deadline = time.monotonic() + 30
            while old.stats()["decode_steps"] < 2 \
                    and time.monotonic() < deadline:
                time.sleep(0.005)
            snap = old.handoff()
            with Engine(model, **_TRACED) as new:
                info = new.adopt(snap)
                assert info["mode"] == "reattach"
                outs = [h.result(timeout=600) for h in hs]
        finally:
            old.close()
        assert all(len(o) == len(p) + 10 for o, p in zip(outs, prompts))
        done = observe.trace_book().completed()
        assert len(done) == 6
        assert len({tl["trace"] for tl in done}) == 6
        assert observe.trace_book().open_traces() == {}
        assert all(tl["outcome"] == "ok" for tl in done)

    def test_chunked_prefill_single_timeline(self, model):
        """A chunked prefill is several prefill spans on ONE timeline."""
        rng = np.random.RandomState(46)
        prompts = [rng.randint(0, 211, (n,)).tolist() for n in (40, 61)]
        c0 = profiler.counters().get("serve_prefill_chunks", 0)
        with Engine(model, prefill_chunk=8, **_TRACED) as eng:
            outs = _drive(eng, prompts, max_new=4)
        assert all(len(o) == len(p) + 4 for o, p in zip(outs, prompts))
        assert profiler.counters().get("serve_prefill_chunks", 0) > c0
        done = observe.trace_book().completed()
        assert len(done) == 2
        assert len({tl["trace"] for tl in done}) == 2
        long_tl = next(tl for tl in done if tl["prompt_len"] == 61)
        chunks = [e for e in long_tl["events"]
                  if e["name"] == "prefill" and e["attrs"].get("chunked")]
        assert len(chunks) >= 2


# -- telemetry endpoint -------------------------------------------------------

_SAMPLE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^{}]*\})? "
    r"(NaN|[-+]?(\d+(\.\d*)?|\.\d+)([eE][-+]?\d+)?)$")
_TTFT_BUCKET = re.compile(
    r'^paddle_tpu_serve_ttft_seconds_bucket'
    r'\{priority="(\d+)",le="([^"]+)"\} (\d+)$')


class TestEndpoint:
    def test_metrics_is_valid_prometheus(self, model):
        rng = np.random.RandomState(47)
        prompts = _prompts(8, rng)
        with Engine(model, **_TRACED) as eng:
            _drive(eng, prompts, max_new=3)
            ep = observe.start_endpoint(eng, 0)  # port 0: bind ephemeral
            try:
                code, body = _get(ep.port, "/metrics")
            finally:
                ep.close()
        assert code == 200
        lines = [ln for ln in body.splitlines() if ln]
        for ln in lines:
            if not ln.startswith("#"):
                assert _SAMPLE.match(ln), f"invalid exposition line: {ln!r}"
        # TTFT histogram: le-cumulative monotone per priority, +Inf == count
        cum = {}
        for ln in lines:
            m = _TTFT_BUCKET.match(ln)
            if m:
                prio, le, v = m.group(1), m.group(2), int(m.group(3))
                assert v >= cum.get(prio, (0, None))[0], ln
                cum[prio] = (v, le)
        assert cum, "TTFT histogram missing from /metrics"
        assert all(last_le == "+Inf" for _, last_le in cum.values())
        assert sum(v for v, _ in cum.values()) == 8
        counts = {m.group(1): int(m.group(2)) for m in re.finditer(
            r'paddle_tpu_serve_ttft_seconds_count\{priority="(\d+)"\} (\d+)',
            body)}
        assert sum(counts.values()) == 8
        # derived summary + shed-rate gauges ride along
        assert "# TYPE paddle_tpu_serve_e2e_latency summary" in body
        assert "paddle_tpu_serve_shed_rate" in body

    def test_healthz_flips_on_injected_wedge(self, model):
        """/healthz 200 on a live engine, 503 once the injected wedge makes
        the heartbeat stale (the acceptance pin for the liveness route)."""
        rng = np.random.RandomState(48)
        with _flags(FLAGS_serve_watchdog_s=2.0):
            eng = Engine(model, **_KW)
            ep = observe.start_endpoint(eng, 0)
            try:
                eng.generate(rng.randint(0, 211, (5,)).tolist(),
                             max_new_tokens=3)  # warm: no compile grace
                code, body = _get(ep.port, "/healthz")
                assert code == 200 and json.loads(body)["ok"]
                code, body = _get(ep.port, "/readyz")
                assert code == 200 and json.loads(body)["ready"]
                inject.arm("serve.wedge:at=1,ms=60000")
                eng.submit(rng.randint(0, 211, (5,)).tolist(),
                           max_new_tokens=20)
                deadline = time.monotonic() + 30
                while eng.health()["ok"] and time.monotonic() < deadline:
                    time.sleep(0.05)
                assert not eng.health()["ok"]
                code, body = _get(ep.port, "/healthz")
                assert code == 503
                assert json.loads(body)["stale"]
                code, body = _get(ep.port, "/readyz")
                assert code == 503
                assert json.loads(body)["reason"] == "unhealthy"
            finally:
                ep.close()
                eng.close(timeout=0.5)

    def test_readyz_flips_on_close(self, model):
        eng = Engine(model, **_KW)
        ep = observe.start_endpoint(eng, 0)
        try:
            assert _get(ep.port, "/readyz")[0] == 200
            eng.close()
            code, body = _get(ep.port, "/readyz")
            assert code == 503
            assert not json.loads(body)["ready"]
        finally:
            ep.close()
            eng.close()

    def test_debug_requests_shows_live_traces(self, model):
        rng = np.random.RandomState(49)
        prompts = _prompts(8, rng)
        with Engine(model, **_TRACED) as eng:
            ep = observe.start_endpoint(eng, 0)
            try:
                hs = [eng.submit(p, max_new_tokens=32) for p in prompts]
                rows, deadline = [], time.monotonic() + 30
                while not rows and time.monotonic() < deadline:
                    code, body = _get(ep.port, "/debug/requests")
                    assert code == 200
                    rows = json.loads(body)
                assert rows, "no in-flight rows observed"
                for row in rows:
                    assert row["phase"] in ("queued", "prefilling",
                                            "chunk_prefill", "running",
                                            "preempted")
                    assert row["trace"]  # traced engine: ids everywhere
                for h in hs:
                    h.result(timeout=600)
                assert _get(ep.port, "/nope")[0] == 404
            finally:
                ep.close()

    def test_bind_failure_is_counter_not_crash(self):
        class _T:
            pass

        c0 = profiler.counters().get("serve_http_bind_failed", 0)
        blocker = socket.socket()
        try:
            blocker.bind(("", 0))
            blocker.listen(1)
            port = blocker.getsockname()[1]
            assert observe.start_endpoint(_T(), port) is None
        finally:
            blocker.close()
        assert profiler.counters().get("serve_http_bind_failed", 0) == c0 + 1

    def test_supervisor_owns_port_across_restart(self, model):
        """The SUPERVISOR binds the port (engines are forced to 0), so the
        probe survives a crash restart and reports the REPLACEMENT
        engine's young heartbeat/uptime."""
        rng = np.random.RandomState(50)
        prompts = _prompts(4, rng)
        s = socket.socket()
        s.bind(("", 0))
        port = s.getsockname()[1]
        s.close()
        inject.arm("serve.crash:at=4")
        with ServingSupervisor(model, watchdog_s=4.0, metrics_port=port,
                               **_TRACED) as sup:
            assert sup._endpoint is not None
            assert sup._engine._endpoint is None
            assert sup._engine.config.metrics_port == 0
            assert _get(port, "/healthz")[0] == 200
            _drive(sup, prompts, max_new=8)
            assert sup.restarts == 1
            code, body = _get(port, "/healthz")  # same port, new engine
            assert code == 200
            h = json.loads(body)
            assert h["ok"]
            # heartbeat/uptime fields are the replacement's: restarted
            # young, strictly below the supervisor's own uptime
            assert h["uptime_s"] < h["supervisor_uptime_s"]

    def test_engine_config_endpoint_lifecycle(self, model):
        """metrics_port on the engine config binds at construction and the
        thread is gone after close()."""
        s = socket.socket()
        s.bind(("", 0))
        port = s.getsockname()[1]
        s.close()
        eng = Engine(model, metrics_port=port, **_KW)
        try:
            assert eng._endpoint is not None
            assert _get(port, "/healthz")[0] == 200
        finally:
            eng.close()
        assert eng._endpoint is None
        assert not any(t.name == "serve-metrics"
                       for t in threading.enumerate())


# -- cost-model drift ---------------------------------------------------------

class TestDrift:
    def test_step_eta_drift_from_warm_decode(self, model):
        """Drift (a): warm decode steps score the shed-ETA predictor."""
        rng = np.random.RandomState(51)
        with Engine(model, **_TRACED) as eng:
            # first request compiles+warms the width-1 decode bucket; the
            # second runs warm steps, the EMA is live from its 2nd step on
            eng.generate(rng.randint(0, 211, (5,)).tolist(),
                         max_new_tokens=8)
            eng.generate(rng.randint(0, 211, (6,)).tolist(),
                         max_new_tokens=8)
        g = observe.drift_gauges()
        assert "step_eta" in g
        assert g["step_eta"]["samples"] >= 1
        assert np.isfinite(g["step_eta"]["rel_err"])
        assert g["step_eta"]["rel_err"] >= 0.0
        assert g["step_eta"]["actual"] > 0.0

    def test_hbm_admission_drift(self, model):
        """Drift (b): with admission armed, predicted peak is scored
        against the realized post-step census each scheduler step."""
        import paddle_tpu as paddle

        rng = np.random.RandomState(52)
        with _flags(FLAGS_hbm_admission="warn"):
            # seed the preflight prediction: one lazy dispatch pays it
            t = paddle.to_tensor(np.ones((64, 64), np.float32))
            (t @ t).numpy()
            from paddle_tpu.fault import memory as fmem

            assert fmem.last_prediction().get("hbm_predicted_peak_bytes")
            with Engine(model, **_TRACED) as eng:
                _drive(eng, _prompts(2, rng), max_new=4)
        g = observe.drift_gauges()
        assert "hbm_admission" in g
        assert g["hbm_admission"]["samples"] >= 1
        assert g["hbm_admission"]["rel_err"] >= 0.0

    def test_kernel_estimate_drift_from_search(self):
        """Drift (c): an autotune search scores the cost model's ORDERING
        against measured timings (discordant-pair fraction)."""
        from paddle_tpu.ops.kernels import autotune, registry

        sleeps = {32: 0.004, 64: 0.0, 128: 0.008}

        def runner(key):
            def make(cfg):
                delay = sleeps[cfg["block_rows"]]

                def step():
                    time.sleep(delay)
                    return np.zeros((2, 2), np.float32)
                return step
            return make

        old = registry.get_kernel("fused_ce")
        registry.register_kernel(
            "fused_ce", defaults={"block_rows": 32},
            space={"block_rows": (32, 64, 128)}, runner=runner)
        autotune.clear_cache()
        try:
            with _flags(FLAGS_kernel_tune_samples=1,
                        FLAGS_kernel_tune_budget_s=30.0):
                _, _, _, searched = autotune.search(
                    registry.get_kernel("fused_ce"),
                    (256, 64, 512, "float32"))
            assert searched
        finally:
            registry._REGISTRY["fused_ce"] = old
            autotune.clear_cache()
        g = observe.drift_gauges()
        assert "kernel_estimate" in g
        assert g["kernel_estimate"]["samples"] >= 1
        assert 0.0 <= g["kernel_estimate"]["last_rel_err"] <= 1.0
        assert g["kernel_estimate"]["pairs"] >= 1

    def test_drift_gauges_in_prometheus_export(self):
        observe.drift("step_eta", 0.010, 0.008)
        text = profiler.export_metrics(format="prometheus")
        assert "# TYPE paddle_tpu_cost_drift gauge" in text
        assert 'paddle_tpu_cost_drift{model="step_eta"}' in text

    def test_drift_math(self):
        rel = observe.drift("x", 10.0, 5.0)
        assert rel == 1.0
        g = observe.drift_gauges()["x"]
        assert g["rel_err"] == 1.0 and g["samples"] == 1
        observe.drift("x", 5.0, 5.0)  # EMA: 0.8*1.0 + 0.2*0.0
        g = observe.drift_gauges()["x"]
        assert abs(g["rel_err"] - 0.8) < 1e-9
        assert g["samples"] == 2 and g["last_rel_err"] == 0.0


# -- health / readiness surface -----------------------------------------------

class TestHealthReady:
    def test_uptime_advances(self, model):
        with Engine(model, **_KW) as eng:
            u0 = eng.health()["uptime_s"]
            assert u0 >= 0.0
            time.sleep(0.05)
            assert eng.health()["uptime_s"] > u0

    def test_last_recovery_age_after_adopt(self, model):
        rng = np.random.RandomState(53)
        prompts = _prompts(2, rng)
        old = Engine(model, **_KW)
        try:
            hs = [old.submit(p, max_new_tokens=6) for p in prompts]
            snap = old.handoff()
            with Engine(model, **_KW) as new:
                assert new.health()["last_recovery"] == {"mode": "none"}
                new.adopt(snap)
                for h in hs:
                    h.result(timeout=600)
                lr = new.health()["last_recovery"]
                assert lr["mode"] == "reattach"
                assert lr["age_s"] >= 0.0
                assert "t" not in lr  # raw monotonic stamp never exported
        finally:
            old.close()

    def test_readiness_is_truthy_dict(self, model):
        eng = Engine(model, **_KW)
        try:
            r = eng.ready()
            assert isinstance(r, Readiness) and isinstance(r, dict)
            assert bool(r) and r["reason"] is None
            assert r["uptime_s"] >= 0.0
            json.dumps(r)  # the /readyz body must be JSON-able
        finally:
            eng.close()
        r = eng.ready()
        assert not r
        assert r["reason"] == "unhealthy"


# -- inert when unconfigured --------------------------------------------------

class TestInertTripwire:
    def test_flag_off_engine_never_touches_observe(self, model, monkeypatch):
        """Flag-off: no observe state, no endpoint thread — and every hook
        monkeypatch-exploded proves no code path can reach one."""
        rng = np.random.RandomState(54)

        def _explode(*a, **k):
            raise AssertionError("observe hook reached with tracing off")

        for name in ("on_submit", "on_admit", "on_shed", "on_prefix_match",
                     "on_cow", "on_relay", "on_tokens", "on_done",
                     "drift", "drift_value"):
            monkeypatch.setattr(observe, name, _explode)
        with Engine(model, **_KW) as eng:
            assert eng._obs is None
            assert eng._endpoint is None
            outs = _drive(eng, _prompts(4, rng), max_new=4)
        assert all(len(o) > 4 for o in outs)
        assert observe._book is None  # no TraceBook was ever created
        assert not any(t.name == "serve-metrics"
                       for t in threading.enumerate())

    def test_trace_flag_arms_engine(self, model):
        with _flags(FLAGS_serve_trace=True):
            with Engine(model, **_KW) as eng:
                assert eng._obs is not None
        with Engine(model, **_KW) as eng:
            assert eng._obs is None
