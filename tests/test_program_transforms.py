"""Program-as-data transforms (reference framework.py Program API +
backward.py:1413 append_backward / :2010 gradients): capture-level clone /
prune / feed rebinding / grad programs, and the save → load →
append-loss-and-grads → train-a-step workflow on a .pdtrain artifact."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.static import (
    InputSpec,
    Program,
    append_backward,
    gradients,
    load_program,
    save_inference_model,
)


def _mlp():
    paddle.seed(7)
    return nn.Sequential(nn.Linear(6, 16), nn.Tanh(), nn.Linear(16, 3))


class TestProgramTransforms:
    def test_clone_is_independent_and_equal(self):
        m = _mlp()
        p = Program.from_callable(m, [InputSpec([2, 6], "float32")])
        p2 = p.clone()
        assert p2 is not p
        assert p2.global_block().all_op_types() == p.global_block().all_op_types()
        x = np.random.RandomState(0).randn(2, 6).astype(np.float32)
        np.testing.assert_allclose(
            p.run(x)[0].numpy(), p2.run(x)[0].numpy(), rtol=1e-6
        )

    def test_prune_drops_dead_ops(self):
        m = _mlp()

        def two_headed(x):
            h = m(x)
            return h, paddle.exp(paddle.sum(h * h))  # second head: extra ops

        p = Program.from_callable(two_headed, [InputSpec([2, 6], "float32")], layer=m)
        pruned = p.prune(0)  # keep only the first output
        assert pruned.num_outputs == 1
        assert pruned.num_ops() < p.num_ops()  # exp/sum head vanished
        x = np.random.RandomState(1).randn(2, 6).astype(np.float32)
        np.testing.assert_allclose(
            pruned.run(x)[0].numpy(), p.run(x)[0].numpy(), rtol=1e-6
        )

    def test_rebind_feeds_new_batch(self):
        m = _mlp()
        p = Program.from_callable(m, [InputSpec([2, 6], "float32")])
        p8 = p.rebind_feeds([InputSpec([8, 6], "float32")])
        x = np.random.RandomState(2).randn(8, 6).astype(np.float32)
        out = p8.run(x)[0].numpy()
        assert out.shape == (8, 3)
        np.testing.assert_allclose(
            out[:2], p.run(x[:2])[0].numpy(), rtol=1e-5, atol=1e-6
        )

    def test_append_backward_matches_autograd(self):
        m = _mlp()

        def loss_prog(x):
            return paddle.mean(m(x) ** 2)

        # params become program inputs only when the owning layer is named
        # (reference: append_backward needs params as Variables, not consts)
        p = Program.from_callable(loss_prog, [InputSpec([4, 6], "float32")], layer=m)
        bp = append_backward(program=p)
        x = np.random.RandomState(3).randn(4, 6).astype(np.float32)
        outs = bp.run(x)
        loss, grads = outs[0], outs[1:]
        assert len(grads) == len([p_ for p_ in m.parameters() if not p_.stop_gradient])

        # parity vs the eager tape
        xt = paddle.to_tensor(x)
        l2 = paddle.mean(m(xt) ** 2)
        l2.backward()
        np.testing.assert_allclose(float(loss.numpy()), float(l2.numpy()), rtol=1e-5)
        eager_grads = [p_.grad.numpy() for p_ in m.parameters() if p_.grad is not None]
        for g_prog, g_eager in zip(grads, eager_grads):
            np.testing.assert_allclose(g_prog.numpy(), g_eager, rtol=1e-4, atol=1e-5)

    def test_gradients_wrt_feed(self):
        m = _mlp()

        def loss_prog(x):
            return paddle.sum(m(x))

        p = Program.from_callable(loss_prog, [InputSpec([2, 6], "float32")])
        gp = gradients(program=p, inputs=0)
        x = np.random.RandomState(4).randn(2, 6).astype(np.float32)
        gx = gp.run(x)[0].numpy()
        assert gx.shape == (2, 6)
        # finite-difference spot check on one coordinate
        eps = 1e-3
        xp = x.copy(); xp[0, 0] += eps
        xm = x.copy(); xm[0, 0] -= eps
        fd = (float(p.run(xp)[0].numpy()) - float(p.run(xm)[0].numpy())) / (2 * eps)
        np.testing.assert_allclose(gx[0, 0], fd, rtol=1e-2, atol=1e-3)


class TestLoadFinetune:
    def test_save_load_append_loss_train_step(self, tmp_path):
        m = _mlp()
        prefix = str(tmp_path / "prog")
        save_inference_model(prefix, [InputSpec([4, 6], "float32")], m)

        prog = load_program(prefix)
        assert prog.param_names  # params are program inputs, not constants

        x = np.random.RandomState(5).randn(4, 6).astype(np.float32)
        y = np.random.RandomState(6).randn(4, 3).astype(np.float32)

        # loaded forward matches the live model
        np.testing.assert_allclose(
            prog(x)[0].numpy(), m(paddle.to_tensor(x)).numpy(), rtol=1e-5, atol=1e-6
        )

        prog.append_backward(
            lambda outs, label: paddle.mean((outs[0] - label) ** 2)
        )
        loss0, grads = prog.gradients([x], [y])
        assert set(grads) == set(prog.param_names)
        assert all(np.isfinite(g.numpy()).all() for g in grads.values())

        losses = [float(prog.train_step([x], [y], lr=0.05).numpy()) for _ in range(5)]
        assert losses[-1] < losses[0], f"no descent: {losses}"

        # trained params round-trip through state_dict back into a live model
        m2 = _mlp()
        m2.set_state_dict(prog.state_dict())
        out_trained = prog(x)[0].numpy()
        np.testing.assert_allclose(
            m2(paddle.to_tensor(x)).numpy(), out_trained, rtol=1e-5, atol=1e-6
        )

    def test_grad_parity_with_eager(self, tmp_path):
        m = _mlp()
        prefix = str(tmp_path / "prog2")
        save_inference_model(prefix, [InputSpec([4, 6], "float32")], m)
        prog = load_program(prefix)
        prog.append_backward(lambda outs, label: paddle.mean((outs[0] - label) ** 2))

        x = np.random.RandomState(8).randn(4, 6).astype(np.float32)
        y = np.random.RandomState(9).randn(4, 3).astype(np.float32)
        _, grads = prog.gradients([x], [y])

        xt, yt = paddle.to_tensor(x), paddle.to_tensor(y)
        loss = paddle.mean((m(xt) - yt) ** 2)
        loss.backward()
        named = dict(m.named_parameters())
        for name, g in grads.items():
            np.testing.assert_allclose(
                g.numpy(), named[name].grad.numpy(), rtol=1e-4, atol=1e-5
            )
