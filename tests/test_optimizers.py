"""Optimizer numeric tests vs hand-computed update rules (reference:
unittests/test_{sgd,adam,momentum,...}_op.py)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import optimizer as opt_mod
import paddle_tpu.nn as nn


def _one_param(val=None):
    p = paddle.Parameter(np.asarray(val if val is not None else [1.0, 2.0, 3.0], np.float32))
    g = np.asarray([0.1, 0.2, 0.3], np.float32)
    p.grad = paddle.to_tensor(g)
    return p, g


class TestRules:
    def test_sgd(self):
        p, g = _one_param()
        opt = opt_mod.SGD(learning_rate=0.1, parameters=[p])
        opt.step()
        np.testing.assert_allclose(p.numpy(), np.array([1, 2, 3], np.float32) - 0.1 * g, rtol=1e-6)

    def test_momentum(self):
        p, g = _one_param()
        opt = opt_mod.Momentum(learning_rate=0.1, momentum=0.9, parameters=[p])
        p0 = p.numpy().copy() + 0.1 * g  # undo? no — track manually
        x = np.array([1, 2, 3], np.float32)
        v = np.zeros(3, np.float32)
        opt.step()
        v = 0.9 * v + g
        x = x - 0.1 * v
        np.testing.assert_allclose(p.numpy(), x, rtol=1e-6)
        p.grad = paddle.to_tensor(g)
        opt.step()
        v = 0.9 * v + g
        x = x - 0.1 * v
        np.testing.assert_allclose(p.numpy(), x, rtol=1e-6)

    def test_adam(self):
        p, g = _one_param()
        opt = opt_mod.Adam(learning_rate=0.01, beta1=0.9, beta2=0.999, epsilon=1e-8, parameters=[p])
        x = np.array([1, 2, 3], np.float64)
        m = np.zeros(3)
        v = np.zeros(3)
        for t in range(1, 4):
            opt.step()
            m = 0.9 * m + 0.1 * g
            v = 0.999 * v + 0.001 * g * g
            mh = m / (1 - 0.9**t)
            vh = v / (1 - 0.999**t)
            x = x - 0.01 * mh / (np.sqrt(vh) + 1e-8)
            np.testing.assert_allclose(p.numpy(), x, rtol=1e-5)
            p.grad = paddle.to_tensor(g)

    def test_adamw_decay(self):
        p, g = _one_param()
        opt = opt_mod.AdamW(learning_rate=0.01, weight_decay=0.1, parameters=[p])
        x = np.array([1, 2, 3], np.float64)
        m = np.zeros(3)
        v = np.zeros(3)
        opt.step()
        x = x * (1 - 0.01 * 0.1)
        m = 0.9 * m + 0.1 * g
        v = 0.999 * v + 0.001 * g * g
        x = x - 0.01 * (m / 0.1) / (np.sqrt(v / 0.001) + 1e-8)
        np.testing.assert_allclose(p.numpy(), x, rtol=1e-4)

    def test_adamw_apply_decay_param_fun(self):
        p, g = _one_param()
        p.name = "bias_1"
        opt = opt_mod.AdamW(
            learning_rate=0.0, weight_decay=0.5, parameters=[p],
            apply_decay_param_fun=lambda n: "bias" not in n,
        )
        opt.step()  # lr=0 → only decay could change p; excluded → unchanged
        np.testing.assert_allclose(p.numpy(), [1, 2, 3], rtol=1e-6)

    def test_weight_decay_l2_coupled(self):
        p, g = _one_param()
        opt = opt_mod.SGD(learning_rate=0.1, weight_decay=0.01, parameters=[p])
        opt.step()
        ref = np.array([1, 2, 3], np.float32) - 0.1 * (g + 0.01 * np.array([1, 2, 3], np.float32))
        np.testing.assert_allclose(p.numpy(), ref, rtol=1e-6)

    @pytest.mark.parametrize("cls,lr", [(opt_mod.RMSProp, 0.1), (opt_mod.Adagrad, 1.0)])
    def test_moment_optimizers_decrease_quadratic(self, cls, lr):
        p = paddle.Parameter(np.asarray([5.0], np.float32))
        opt = cls(learning_rate=lr, parameters=[p])
        for _ in range(50):
            loss = (p * p).sum()
            loss.backward()
            opt.step()
            opt.clear_grad()
        assert abs(float(p.numpy()[0])) < 1.0

    def test_lamb_trust_ratio(self):
        p, g = _one_param()
        opt = opt_mod.Lamb(learning_rate=0.01, lamb_weight_decay=0.0, parameters=[p])
        before = p.numpy().copy()
        opt.step()
        assert not np.allclose(p.numpy(), before)

    def test_state_dict_roundtrip(self):
        p, g = _one_param()
        opt = opt_mod.Adam(learning_rate=0.01, parameters=[p])
        opt.step()
        sd = opt.state_dict()
        p2, _ = _one_param()
        p2.name = p.name
        opt2 = opt_mod.Adam(learning_rate=0.01, parameters=[p2])
        opt2.set_state_dict(sd)
        np.testing.assert_allclose(
            np.asarray(opt2._state(p2)["moment1"]), np.asarray(opt._state(p)["moment1"])
        )
        assert opt2._step_count == 1

    def test_grad_clip_in_optimizer(self):
        p = paddle.Parameter(np.zeros(4, np.float32))
        p.grad = paddle.to_tensor(np.ones(4, np.float32) * 10)
        opt = opt_mod.SGD(learning_rate=1.0, parameters=[p], grad_clip=paddle.nn.ClipGradByGlobalNorm(1.0))
        opt.step()
        np.testing.assert_allclose(np.linalg.norm(p.numpy()), 1.0, rtol=1e-5)


class TestFunctionalParity:
    """Fused compiled train step must match the eager path bit-for-bit-ish."""

    @pytest.mark.parametrize("cls,kw", [
        (opt_mod.SGD, {}),
        (opt_mod.Momentum, {"momentum": 0.9}),
        (opt_mod.Adam, {}),
        (opt_mod.AdamW, {"weight_decay": 0.01}),
    ])
    def test_compiled_matches_eager(self, cls, kw):
        import paddle_tpu.nn as nn

        paddle.seed(3)
        m1 = nn.Linear(4, 3)
        m2 = nn.Linear(4, 3)
        m2.set_state_dict(m1.state_dict())
        o1 = cls(learning_rate=0.1, parameters=m1.parameters(), **kw)
        o2 = cls(learning_rate=0.1, parameters=m2.parameters(), **kw)
        x = paddle.to_tensor(np.random.rand(5, 4).astype(np.float32))
        y = paddle.to_tensor(np.random.rand(5, 3).astype(np.float32))

        def loss_fn(m, xb, yb):
            return ((m(xb) - yb) ** 2).mean()

        step = paddle.jit.compile_train_step(m2, loss_fn, o2)
        for _ in range(3):
            loss = loss_fn(m1, x, y)
            loss.backward()
            o1.step()
            o1.clear_grad()
            step(x, y)
        np.testing.assert_allclose(m1.weight.numpy(), m2.weight.numpy(), rtol=1e-4, atol=1e-5)


class TestLRSchedulers:
    def test_step_decay(self):
        s = opt_mod.lr.StepDecay(0.1, step_size=2, gamma=0.5)
        vals = []
        for _ in range(5):
            vals.append(s())
            s.step()
        np.testing.assert_allclose(vals, [0.1, 0.1, 0.05, 0.05, 0.025])

    def test_warmup(self):
        s = opt_mod.lr.LinearWarmup(0.1, warmup_steps=4, start_lr=0.0, end_lr=0.1)
        vals = [s() for _ in range(4) if s.step() or True]
        assert vals[0] < vals[-1] <= 0.1

    def test_cosine(self):
        s = opt_mod.lr.CosineAnnealingDecay(1.0, T_max=10)
        first = s()
        for _ in range(10):
            s.step()
        np.testing.assert_allclose(first, 1.0)
        np.testing.assert_allclose(s(), 0.0, atol=1e-6)

    def test_optimizer_reads_scheduler(self):
        sched = opt_mod.lr.StepDecay(0.1, step_size=1, gamma=0.1)
        p, _ = _one_param()
        opt = opt_mod.SGD(learning_rate=sched, parameters=[p])
        assert opt.get_lr() == pytest.approx(0.1)
        sched.step()
        assert opt.get_lr() == pytest.approx(0.01)

    def test_noam(self):
        s = opt_mod.lr.NoamDecay(d_model=512, warmup_steps=10, learning_rate=1.0)
        vals = []
        for _ in range(20):
            vals.append(s())
            s.step()
        peak = int(np.argmax(vals))
        assert 8 <= peak <= 11


class TestDistributedFusedLamb:
    def test_matches_lamb_and_resumes(self):
        import numpy as np
        from paddle_tpu.incubate.distributed_fused_lamb import DistributedFusedLamb

        rng = np.random.RandomState(0)
        X = rng.randn(32, 8).astype(np.float32)
        Y = (X @ rng.randn(8, 1).astype(np.float32))

        def build():
            paddle.seed(4)
            m = nn.Linear(8, 1)
            return m

        def train(m, opt, steps=6):
            losses = []
            for _ in range(steps):
                loss = ((m(paddle.to_tensor(X)) - paddle.to_tensor(Y)) ** 2).mean()
                loss.backward()
                opt.step()
                opt.clear_grad()
                losses.append(float(loss.item()))
            return losses

        # no-clip fused LAMB must match the per-param Lamb rule exactly
        m1 = build()
        o1 = paddle.optimizer.Lamb(learning_rate=0.05, parameters=m1.parameters())
        l1 = train(m1, o1)
        m2 = build()
        o2 = DistributedFusedLamb(learning_rate=0.05, parameters=m2.parameters(),
                                  max_global_grad_norm=0.0)
        l2 = train(m2, o2)
        np.testing.assert_allclose(l1, l2, rtol=1e-5, atol=1e-6)

        # global-norm clip changes the trajectory (clip actually engages)
        m3 = build()
        o3 = DistributedFusedLamb(learning_rate=0.05, parameters=m3.parameters(),
                                  max_global_grad_norm=0.1)
        l3 = train(m3, o3)
        assert abs(l3[-1] - l2[-1]) > 1e-6

        # checkpoint roundtrip restores fused state
        sd = o2.state_dict()
        m4 = build()
        o4 = DistributedFusedLamb(learning_rate=0.05, parameters=m4.parameters(),
                                  max_global_grad_norm=0.0)
        for p4, p2 in zip(m4.parameters(), m2.parameters()):
            p4._set_data(p2._data)
        o4.set_state_dict(sd)
        a = train(m2, o2, steps=2)
        b = train(m4, o4, steps=2)
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)


class TestIncubateOptimizerExtras:
    def _fit_problem(self):
        rng = np.random.RandomState(0)
        X = rng.randn(32, 6).astype(np.float32)
        Y = X @ rng.randn(6, 1).astype(np.float32)
        return X, Y

    def test_lookahead_interpolates_every_k(self):
        from paddle_tpu.incubate import LookAhead

        X, Y = self._fit_problem()
        paddle.seed(1)
        m = nn.Linear(6, 1)
        inner = paddle.optimizer.SGD(learning_rate=0.05, parameters=m.parameters())
        la = LookAhead(inner, alpha=0.5, k=3)
        losses = []
        for _ in range(9):
            loss = ((m(paddle.to_tensor(X)) - paddle.to_tensor(Y)) ** 2).mean()
            loss.backward()
            la.step()
            la.clear_grad()
            losses.append(float(loss.item()))
        assert losses[-1] < losses[0]
        assert la._slow  # slow weights materialized at the k-step syncs
        # state roundtrip
        sd = la.state_dict()
        la2 = LookAhead(paddle.optimizer.SGD(learning_rate=0.05,
                                             parameters=m.parameters()),
                        alpha=0.5, k=3)
        la2.set_state_dict(sd)
        assert la2._step_count == la._step_count
        for k, v in la._slow.items():  # slow weights actually roundtrip
            np.testing.assert_allclose(np.asarray(la2._slow[k]), np.asarray(v))
        # mismatched param names must fail loudly, not silently reset
        m3 = nn.Linear(6, 1)
        la3 = LookAhead(paddle.optimizer.SGD(learning_rate=0.05,
                                             parameters=m3.parameters()))
        with pytest.raises(ValueError, match="slow-weight keys"):
            la3.set_state_dict(sd)

    def test_lookahead_first_sync_interpolates_from_init(self):
        """ADVICE r5: slow weights seed from the BUILD-time params, so the
        FIRST k-step sync lands at w0 + alpha*(w_k - w0) — lazily adopting
        the current fast weights would make it a no-op (== w_k)."""
        from paddle_tpu.incubate import LookAhead

        X, Y = self._fit_problem()

        def run_steps(opt_factory, steps):
            paddle.seed(6)
            m = nn.Linear(6, 1)
            w0 = m.weight.numpy().copy()
            opt = opt_factory(m)
            for _ in range(steps):
                loss = ((m(paddle.to_tensor(X)) - paddle.to_tensor(Y)) ** 2).mean()
                loss.backward()
                opt.step()
                opt.clear_grad()
            return w0, m.weight.numpy()

        k, alpha = 3, 0.5
        # fast-only reference: plain SGD k steps -> w_k
        _, w_k = run_steps(
            lambda m: paddle.optimizer.SGD(learning_rate=0.05,
                                           parameters=m.parameters()), k)
        w0, w_sync = run_steps(
            lambda m: LookAhead(
                paddle.optimizer.SGD(learning_rate=0.05,
                                     parameters=m.parameters()),
                alpha=alpha, k=k), k)
        want = w0 + alpha * (w_k - w0)
        np.testing.assert_allclose(w_sync, want, rtol=1e-5, atol=1e-7)
        # and it is NOT the no-op (w_k itself)
        assert np.abs(w_sync - w_k).max() > 1e-6

    def test_model_average_apply_restore(self):
        from paddle_tpu.incubate import ModelAverage

        X, Y = self._fit_problem()
        paddle.seed(2)
        m = nn.Linear(6, 1)
        opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=m.parameters())
        ma = ModelAverage(0.15, parameters=m.parameters(),
                          min_average_window=2, max_average_window=10)
        for _ in range(6):
            loss = ((m(paddle.to_tensor(X)) - paddle.to_tensor(Y)) ** 2).mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
            ma.step()
        w_train = np.asarray(m.weight._data).copy()
        ma.apply()
        w_avg = np.asarray(m.weight._data).copy()
        assert not np.allclose(w_train, w_avg)  # averaged differs from last
        ma.restore()
        np.testing.assert_allclose(np.asarray(m.weight._data), w_train)

    def test_model_average_constant_weights_unbiased(self):
        """Fold-down must keep sum and divisor consistent: averaging a
        CONSTANT weight must return exactly that weight through folds."""
        from paddle_tpu.incubate import ModelAverage

        paddle.seed(3)
        m = nn.Linear(4, 1)
        w = np.asarray(m.weight._data).copy()
        ma = ModelAverage(0.15, parameters=m.parameters(),
                          min_average_window=2, max_average_window=4)
        for _ in range(7):  # crosses several folds, incl. odd counts
            ma.step()
        ma.apply()
        np.testing.assert_allclose(np.asarray(m.weight._data), w, rtol=1e-6)
        ma.restore()
