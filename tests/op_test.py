"""OpTest-style helpers.

Reference: ``python/paddle/fluid/tests/unittests/op_test.py:282`` — numeric
output check vs numpy reference + finite-difference gradient check against
the recorded autograd. Same methodology, JAX-native.
"""
from __future__ import annotations

import numpy as np

import paddle_tpu as paddle
from paddle_tpu.core.tensor import Tensor


def check_output(op_fn, np_fn, inputs, atol=1e-5, rtol=1e-5, **kwargs):
    """op_fn(*tensors, **kwargs) vs np_fn(*arrays, **kwargs)."""
    tensors = [paddle.to_tensor(np.asarray(a)) for a in inputs]
    out = op_fn(*tensors, **kwargs)
    ref = np_fn(*[np.asarray(a) for a in inputs], **kwargs)
    outs = out if isinstance(out, (list, tuple)) else [out]
    refs = ref if isinstance(ref, (list, tuple)) else [ref]
    for o, r in zip(outs, refs):
        np.testing.assert_allclose(
            np.asarray(o.numpy(), dtype=np.float64) if o.dtype != np.dtype("bool") else o.numpy(),
            np.asarray(r, dtype=np.float64) if np.asarray(r).dtype != np.bool_ else r,
            atol=atol, rtol=rtol,
        )
    return out


def check_grad(op_fn, inputs, grad_inputs=None, eps=1e-3, atol=1e-2, rtol=1e-2, out_index=None, **kwargs):
    """Finite-difference gradient check (fp64 host) vs autograd gradient."""
    arrays = [np.asarray(a, dtype=np.float64) for a in inputs]
    grad_idx = grad_inputs if grad_inputs is not None else list(range(len(arrays)))

    def run(arrs):
        tensors = [paddle.to_tensor(a.astype(np.float32), stop_gradient=False) for a in arrs]
        out = op_fn(*tensors, **kwargs)
        if out_index is not None:
            out = out[out_index]
        return tensors, out

    tensors, out = run(arrays)
    loss = out.sum() if out.size > 1 else out
    loss.backward()

    for gi in grad_idx:
        analytic = tensors[gi].grad.numpy().astype(np.float64)
        numeric = np.zeros_like(arrays[gi])
        flat = arrays[gi].reshape(-1)
        num_flat = numeric.reshape(-1)
        for k in range(flat.size):
            orig = flat[k]
            flat[k] = orig + eps
            _, out_p = run(arrays)
            f_p = float(np.asarray(out_p.numpy(), np.float64).sum())
            flat[k] = orig - eps
            _, out_m = run(arrays)
            f_m = float(np.asarray(out_m.numpy(), np.float64).sum())
            flat[k] = orig
            num_flat[k] = (f_p - f_m) / (2 * eps)
        np.testing.assert_allclose(analytic, numeric, atol=atol, rtol=rtol)
