"""AMP, jit, and io tests."""
import os

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
import paddle_tpu.nn.functional as F


class TestAMP:
    def test_autocast_casts_matmul_to_bf16(self):
        x = paddle.to_tensor(np.random.rand(4, 4).astype(np.float32))
        with paddle.amp.auto_cast(dtype="bfloat16"):
            out = paddle.matmul(x, x)
        assert out.dtype == paddle.bfloat16

    def test_blacklist_stays_fp32(self):
        x = paddle.to_tensor(np.random.rand(4, 4).astype(np.float32))
        with paddle.amp.auto_cast():
            out = F.softmax(x)
        assert out.dtype == np.dtype("float32")

    def test_disabled_outside_context(self):
        x = paddle.to_tensor(np.random.rand(4, 4).astype(np.float32))
        out = paddle.matmul(x, x)
        assert out.dtype == np.dtype("float32")

    def test_grad_scaler_scales_and_steps(self):
        model = nn.Linear(4, 2)
        opt = paddle.optimizer.SGD(0.1, parameters=model.parameters())
        scaler = paddle.amp.GradScaler(init_loss_scaling=1024.0)
        x = paddle.to_tensor(np.random.rand(3, 4).astype(np.float32))
        loss = model(x).mean()
        before = model.weight.numpy().copy()
        scaled = scaler.scale(loss)
        scaled.backward()
        scaler.step(opt)
        scaler.update()
        assert not np.allclose(model.weight.numpy(), before)

    def test_grad_scaler_skips_on_inf(self):
        model = nn.Linear(2, 2)
        opt = paddle.optimizer.SGD(0.1, parameters=model.parameters())
        scaler = paddle.amp.GradScaler(init_loss_scaling=4.0, decr_every_n_nan_or_inf=1)
        before = model.weight.numpy().copy()
        model.weight.grad = paddle.to_tensor(np.full((2, 2), np.inf, np.float32))
        scaler.step(opt)
        scaler.update()
        np.testing.assert_array_equal(model.weight.numpy(), before)
        assert scaler.get_scale() == 2.0  # halved

    def test_decorate_o2(self):
        model = nn.Linear(4, 4)
        model = paddle.amp.decorate(model, level="O2", dtype="bfloat16")
        assert model.weight.dtype == paddle.bfloat16


class TestJit:
    def test_to_static_matches_eager(self):
        paddle.seed(0)
        model = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
        x = paddle.to_tensor(np.random.rand(3, 4).astype(np.float32))
        eager_out = model(x).numpy()
        static = paddle.jit.to_static(model)
        np.testing.assert_allclose(static(x).numpy(), eager_out, rtol=1e-5)

    def test_to_static_grads_match(self):
        paddle.seed(0)
        m1 = nn.Linear(4, 2)
        m2 = nn.Linear(4, 2)
        m2.set_state_dict(m1.state_dict())
        x = paddle.to_tensor(np.random.rand(3, 4).astype(np.float32))
        m1(x).sum().backward()
        sm = paddle.jit.to_static(m2)
        sm(x).sum().backward()
        np.testing.assert_allclose(m1.weight.grad.numpy(), m2.weight.grad.numpy(), rtol=1e-4)

    def test_jit_save_load(self, tmp_path):
        from paddle_tpu.static import InputSpec

        model = nn.Linear(4, 2)
        model.eval()
        path = str(tmp_path / "linear")
        paddle.jit.save(model, path, input_spec=[InputSpec([2, 4], "float32")])
        loaded = paddle.jit.load(path)
        x = paddle.to_tensor(np.random.rand(2, 4).astype(np.float32))
        np.testing.assert_allclose(loaded(x).numpy(), model(x).numpy(), rtol=1e-5)

    def test_dropout_under_jit_varies(self):
        model = nn.Dropout(0.5)
        sf = paddle.jit.to_static(lambda x: model(x))
        x = paddle.to_tensor(np.ones((100,), np.float32))
        a = sf(x).numpy()
        b = sf(x).numpy()
        assert not np.array_equal(a, b)  # traced RNG must advance per call


class TestIO:
    def test_save_load_nested(self, tmp_path):
        obj = {
            "w": paddle.to_tensor(np.random.rand(3, 3).astype(np.float32)),
            "nested": {"b": paddle.to_tensor(np.arange(4))},
            "scalar": 7,
            "list": [paddle.to_tensor(np.ones(2, np.float32))],
        }
        p = str(tmp_path / "ckpt.pdparams")
        paddle.save(obj, p)
        loaded = paddle.load(p)
        np.testing.assert_array_equal(loaded["w"].numpy(), obj["w"].numpy())
        np.testing.assert_array_equal(loaded["nested"]["b"].numpy(), np.arange(4))
        assert loaded["scalar"] == 7

    def test_bfloat16_roundtrip(self, tmp_path):
        t = paddle.to_tensor(np.random.rand(4).astype(np.float32)).astype("bfloat16")
        p = str(tmp_path / "bf16.pdparams")
        paddle.save({"t": t}, p)
        loaded = paddle.load(p)
        assert loaded["t"].dtype == paddle.bfloat16
        np.testing.assert_array_equal(
            loaded["t"].astype("float32").numpy(), t.astype("float32").numpy()
        )

    def test_dataloader_batching(self):
        from paddle_tpu.io import DataLoader, Dataset

        class DS(Dataset):
            def __len__(self):
                return 10

            def __getitem__(self, i):
                return np.full((3,), i, np.float32), np.int64(i % 2)

        dl = DataLoader(DS(), batch_size=4, drop_last=False)
        batches = list(dl)
        assert len(batches) == 3
        x, y = batches[0]
        assert x.shape == [4, 3] and y.shape == [4]
        dl2 = DataLoader(DS(), batch_size=4, drop_last=True)
        assert len(list(dl2)) == 2

    def test_dataloader_workers_match_serial(self):
        from paddle_tpu.io import DataLoader, Dataset

        class DS(Dataset):
            def __len__(self):
                return 16

            def __getitem__(self, i):
                return np.full((2,), i, np.float32)

        serial = {tuple(b.numpy()[:, 0].tolist()) for b in DataLoader(DS(), batch_size=4)}
        threaded = {tuple(b.numpy()[:, 0].tolist()) for b in DataLoader(DS(), batch_size=4, num_workers=3)}
        assert serial == threaded

    def test_worker_error_propagates(self):
        from paddle_tpu.io import DataLoader, Dataset

        class Bad(Dataset):
            def __len__(self):
                return 4

            def __getitem__(self, i):
                raise RuntimeError("boom")

        with pytest.raises(RuntimeError, match="boom"):
            list(DataLoader(Bad(), batch_size=2, num_workers=2))

    def test_distributed_batch_sampler_partitions(self):
        from paddle_tpu.io import DistributedBatchSampler, TensorDataset

        ds = TensorDataset([paddle.to_tensor(np.arange(12))])
        seen = []
        for rank in range(3):
            s = DistributedBatchSampler(ds, batch_size=2, num_replicas=3, rank=rank)
            for batch in s:
                seen.extend(batch)
        assert sorted(seen) == list(range(12))

    def test_hapi_model_fit(self):
        from paddle_tpu.io import TensorDataset

        paddle.seed(0)
        x = np.random.rand(32, 4).astype(np.float32)
        w_true = np.random.rand(4, 1).astype(np.float32)
        y = x @ w_true
        ds = TensorDataset([paddle.to_tensor(x), paddle.to_tensor(y)])
        net = nn.Linear(4, 1)
        model = paddle.Model(net)
        initial = float(nn.MSELoss()(net(paddle.to_tensor(x)), paddle.to_tensor(y)).item())
        sched = paddle.optimizer.lr.StepDecay(0.05, step_size=60, gamma=0.2)
        model.prepare(
            optimizer=paddle.optimizer.Adam(sched, parameters=net.parameters()),
            loss=nn.MSELoss(),
        )
        model.fit(ds, batch_size=8, epochs=30, verbose=0)
        final = model.evaluate(ds, batch_size=32)
        assert final["loss"] < initial / 10, (initial, final)
