"""graft-analyze: repo-invariant linter, lock-discipline checker, runtime
thread checks, and the tier-1 tree-clean tripwire.

The tripwire test IS the CI gate: `python -m paddle_tpu.analysis` semantics
run in-process over the installed package, failing on any unsuppressed
finding. Every rule also gets a seeded violation proving it still catches
what it claims to.
"""
import os
import textwrap
import threading

import numpy as np
import pytest

import paddle_tpu  # noqa: F401  (package import before analysis)
from paddle_tpu import analysis
from paddle_tpu.analysis import lint as lint_mod
from paddle_tpu.analysis import locks as locks_mod
from paddle_tpu.analysis import thread_checks
from paddle_tpu.framework import flags


def _lint(source, relpath):
    findings, _refs, _regs = lint_mod.lint_source(textwrap.dedent(source), relpath)
    return findings


# -- the tier-1 gate ----------------------------------------------------------
class TestTreeClean:
    def test_package_has_no_unsuppressed_findings(self):
        """Tier-1 tripwire: the full analysis over paddle_tpu/ must be clean
        (an empty or justified-only baseline). A new hidden host sync,
        non-atomic write, wall-clock deadline, compat bypass, unregistered
        flag or unguarded mutation fails HERE instead of on TPU."""
        findings = analysis.run_all()
        assert findings == [], "\n".join(map(repr, findings))

    def test_cli_main_exits_zero(self):
        from paddle_tpu.analysis.__main__ import main

        assert main([]) == 0

    def test_baseline_entries_are_justified(self):
        # load_baseline raises on an entry without a '# why' — reparse the
        # checked-in file so a drive-by edit can't drop justifications
        entries = lint_mod.load_baseline(analysis.baseline_path())
        assert len(entries) >= 1


# -- seeded lint violations ---------------------------------------------------
class TestHostSyncRule:
    SRC = """
    def hot(t):
        return t.numpy()
    """

    def test_flags_in_hot_scope(self):
        assert any(f.rule == "host-sync" for f in self._run("core/foo.py"))
        assert any(f.rule == "host-sync" for f in self._run("distributed/foo.py"))
        assert any(f.rule == "host-sync" for f in self._run("optimizer/foo.py"))

    def test_silent_outside_hot_scope(self):
        assert self._run("hapi/foo.py") == []
        assert self._run("metric/foo.py") == []

    def _run(self, rel):
        return _lint(self.SRC, rel)

    def test_item_and_raw_buffer_asarray(self):
        src = """
        import numpy as np
        def hot(t):
            a = t.item()
            b = np.asarray(t._data)
            return a, b
        """
        rules = [f.rule for f in _lint(src, "core/foo.py")]
        assert rules.count("host-sync") == 2

    def test_inline_suppression_same_line_and_above(self):
        src = """
        def hot(t):
            a = t.item()  # lint: ok(host-sync)
            # lint: ok(host-sync)
            b = t.numpy()
            return a, b
        """
        assert _lint(src, "core/foo.py") == []


class TestCompatShimRule:
    def test_direct_uses_flagged(self):
        src = """
        import jax
        from jax import lax
        def f(g):
            jax.shard_map(g)
            lax.axis_size("dp")
            return jax.export.export(g)
        """
        findings = _lint(src, "distributed/foo.py")
        assert sum(f.rule == "compat-shim" for f in findings) == 3

    def test_shim_imports_flagged(self):
        src = """
        from jax.experimental.shard_map import shard_map
        from jax.experimental import export
        from jax import enable_x64
        """
        findings = _lint(src, "ops/foo.py")
        assert sum(f.rule == "compat-shim" for f in findings) == 3

    def test_compat_module_itself_exempt(self):
        src = "from jax.experimental.shard_map import shard_map\n"
        assert _lint(src, "core/compat.py") == []


class TestAtomicWriteRule:
    def test_plain_write_flagged(self):
        src = """
        import json
        def save(path, doc):
            with open(path, "w") as f:
                json.dump(doc, f)
        """
        findings = _lint(src, "distributed/store.py")
        assert [f.rule for f in findings] == ["atomic-write"]

    def test_tmp_replace_pattern_passes(self):
        src = """
        import json, os
        def save(path, doc):
            with open(path + ".tmp", "w") as f:
                json.dump(doc, f)
            os.replace(path + ".tmp", path)
        """
        assert _lint(src, "distributed/store.py") == []

    def test_atomic_open_helper_passes(self):
        src = """
        from paddle_tpu.framework.io import atomic_open
        def save(path, data):
            with atomic_open(path, "wb") as f:
                f.write(data)
        """
        assert _lint(src, "distributed/store.py") == []

    def test_write_bytes_flagged_append_not(self):
        src = """
        def a(p, data):
            p.write_bytes(data)
        def b(path, line):
            with open(path, "a") as f:
                f.write(line)
        """
        findings = _lint(src, "io/foo.py")
        assert [f.rule for f in findings] == ["atomic-write"]
        assert findings[0].scope == "a"


class TestMonotonicDeadlineRule:
    def test_direct_deadline_arith_flagged(self):
        src = """
        import time
        def f(timeout_s):
            deadline = time.time() + timeout_s
            return deadline
        """
        findings = _lint(src, "distributed/foo.py")
        assert [f.rule for f in findings] == ["monotonic-deadline"]

    def test_tainted_compare_flagged(self):
        src = """
        import time
        def f(t0, timeout_s):
            now = time.time()
            if now - t0 > timeout_s:
                return True
            return False
        """
        findings = _lint(src, "fault/foo.py")
        assert [f.rule for f in findings] == ["monotonic-deadline"]

    def test_plain_timing_not_flagged(self):
        src = """
        import time
        def f(iters):
            t0 = time.time()
            for _ in range(iters):
                pass
            return (time.time() - t0) / iters
        """
        assert _lint(src, "cost_model/foo.py") == []

    def test_monotonic_passes(self):
        src = """
        import time
        def f(timeout_s):
            deadline = time.monotonic() + timeout_s
            return time.monotonic() > deadline
        """
        assert _lint(src, "distributed/foo.py") == []


class TestBareExceptRule:
    def test_bare_except_in_commit_path(self):
        src = """
        def commit(store):
            try:
                store.set("k", "v")
            except:
                pass
        """
        findings = _lint(src, "fault/retry2.py")
        assert [f.rule for f in findings] == ["bare-except"]

    def test_base_exception_with_reraise_passes(self):
        src = """
        def commit(store):
            try:
                store.set("k", "v")
            except BaseException:
                store.cleanup()
                raise
        """
        assert _lint(src, "distributed/coord.py") == []

    def test_out_of_scope_module_not_checked(self):
        src = """
        def f():
            try:
                return 1
            except:
                pass
        """
        assert _lint(src, "ops/foo.py") == []


class TestOomHandlerRule:
    def test_broad_except_in_dispatch_file_flagged(self):
        src = """
        def launch(jitted, leaves):
            try:
                return jitted(*leaves)
            except Exception:
                return None
        """
        findings = _lint(src, "core/lazy.py")
        assert [f.rule for f in findings] == ["oom-handler"]

    def test_classifier_routing_passes(self):
        src = """
        def launch(jitted, leaves):
            try:
                return jitted(*leaves)
            except Exception as e:
                from ..fault import memory as _mem
                if _mem.is_oom(e):
                    return _recover(e)
                return None
        """
        assert _lint(src, "serving/engine.py") == []

    def test_bare_reraise_passes(self):
        src = """
        def launch(jitted, leaves):
            try:
                return jitted(*leaves)
            except RuntimeError:
                cleanup()
                raise
        """
        assert _lint(src, "distributed/engine.py") == []

    def test_narrow_type_not_flagged(self):
        src = """
        def launch(path):
            try:
                return open(path, "rb").read()
            except OSError:
                return None
        """
        assert _lint(src, "core/dispatch.py") == []

    def test_tuple_with_catchable_type_flagged(self):
        src = """
        def launch(jitted, leaves):
            try:
                return jitted(*leaves)
            except (ValueError, RuntimeError):
                return None
        """
        findings = _lint(src, "serving/supervisor.py")
        assert [f.rule for f in findings] == ["oom-handler"]

    def test_outside_dispatch_layer_not_checked(self):
        src = """
        def f(x):
            try:
                return g(x)
            except Exception:
                return None
        """
        assert _lint(src, "core/tensor.py") == []
        assert _lint(src, "serving/pool.py") == []

    def test_inline_suppression(self):
        src = """
        def launch(jitted, leaves):
            try:
                return jitted(*leaves)
            except Exception:  # lint: ok(oom-handler)
                return None
        """
        assert _lint(src, "core/lazy.py") == []


class TestFlagRegistryRule:
    def test_unregistered_flag_reported(self, tmp_path):
        pkg = tmp_path / "pkg"
        (pkg / "framework").mkdir(parents=True)
        (pkg / "framework" / "flags.py").write_text(
            '_FLAGS = {"FLAGS_known": True}\n'
            "def register_flag(name, default):\n    pass\n"
        )
        (pkg / "mod.py").write_text(
            "from .framework import flags as _flags\n"
            '_flags.register_flag("FLAGS_runtime_added", 0)\n'
            'A = _flags.flag("FLAGS_known", True)\n'
            'B = _flags.flag("FLAGS_runtime_added", 1)\n'
            'C = _flags.flag("FLAGS_typo_nver_registered", None)\n'
        )
        findings = lint_mod.lint_package(str(pkg))
        bad = [f for f in findings if f.rule == "flag-registry"]
        assert len(bad) == 1
        assert "FLAGS_typo_nver_registered" in bad[0].message

    def test_installed_tree_flags_all_registered(self):
        findings = [
            f for f in lint_mod.lint_package(analysis.package_root())
            if f.rule == "flag-registry"
        ]
        assert findings == []


class TestCounterRegistryRule:
    @staticmethod
    def _pkg(tmp_path, registered, documented, mod_src):
        pkg = tmp_path / "pkg"
        (pkg / "profiler").mkdir(parents=True)
        doc = ", ".join(f"``{n}``" for n in documented)
        (pkg / "profiler" / "__init__.py").write_text(
            "def counters():\n"
            f'    """Counter snapshot.\n\n    Telemetry: {doc}.\n    """\n'
            "    return {}\n\n"
            "KNOWN_COUNTERS = frozenset({"
            + ", ".join(repr(n) for n in sorted(registered)) + "})\n"
        )
        (pkg / "mod.py").write_text(textwrap.dedent(mod_src))
        return pkg

    @staticmethod
    def _findings(pkg):
        return [f for f in lint_mod.lint_package(str(pkg))
                if f.rule == "counter-registry"]

    def test_bumped_but_unregistered_reported_at_bump_site(self, tmp_path):
        pkg = self._pkg(tmp_path, {"good"}, {"good"}, """
        from .profiler import counter_inc
        def f():
            counter_inc("good")
            counter_inc("ghost")
        """)
        bad = self._findings(pkg)
        assert len(bad) == 1
        assert "'ghost'" in bad[0].message and "KNOWN_COUNTERS" in bad[0].message
        assert bad[0].path == "mod.py" and bad[0].scope == "f"

    def test_registered_but_never_bumped(self, tmp_path):
        pkg = self._pkg(tmp_path, {"good", "stale"}, {"good", "stale"}, """
        from .profiler import counter_inc
        def f():
            counter_inc("good")
        """)
        bad = self._findings(pkg)
        assert len(bad) == 1
        assert "'stale'" in bad[0].message and "never" in bad[0].message
        assert bad[0].path == "profiler/__init__.py"

    def test_registered_but_undocumented(self, tmp_path):
        pkg = self._pkg(tmp_path, {"good", "undoc"}, {"good"}, """
        from .profiler import counter_inc
        def f():
            counter_inc("good")
            counter_inc("undoc")
        """)
        bad = self._findings(pkg)
        assert len(bad) == 1
        assert "'undoc'" in bad[0].message and "docstring" in bad[0].message
        assert bad[0].scope == "counters"

    def test_ifexp_branches_counted_test_strings_not(self, tmp_path):
        """`counter_inc("a" if kind == "wedge" else "b")` bumps a AND b;
        the predicate's "wedge" literal is NOT a counter name (the false
        positive the first implementation hit on supervisor.py)."""
        pkg = self._pkg(tmp_path, {"a", "b"}, {"a", "b"}, """
        from .profiler import counter_inc
        def f(kind):
            counter_inc("a" if kind == "wedge" else "b")
        """)
        assert self._findings(pkg) == []
        # drop b from the registry: the branch ref surfaces it
        pkg2 = self._pkg(tmp_path / "two", {"a"}, {"a"}, """
        from .profiler import counter_inc
        def f(kind):
            counter_inc("a" if kind == "wedge" else "b")
        """)
        bad = self._findings(pkg2)
        assert [f for f in bad if "'b'" in f.message]
        assert not [f for f in bad if "wedge" in f.message]

    def test_step_counters_dict_keys_are_bumps(self, tmp_path):
        """A step_counters() dict is fed verbatim into counter_inc(k, v)
        by the distributed engine — its keys count as bump sites."""
        pkg = self._pkg(tmp_path, {"sc_a"}, {"sc_a"}, """
        def step_counters():
            return {"sc_a": 1}
        """)
        assert self._findings(pkg) == []
        pkg2 = self._pkg(tmp_path / "two", set(), set(), """
        def step_counters():
            return {"sc_a": 1}
        """)
        # empty frozenset({}) registers nothing -> rule disengages; seed one
        # registered name so the registry exists
        pkg2 = self._pkg(tmp_path / "three", {"other"}, {"other"}, """
        from .profiler import counter_inc
        def step_counters():
            return {"sc_a": 1}
        def g():
            counter_inc("other")
        """)
        bad = self._findings(pkg2)
        assert len(bad) == 1 and "'sc_a'" in bad[0].message

    def test_package_without_registry_disengages(self, tmp_path):
        pkg = tmp_path / "pkg"
        pkg.mkdir()
        (pkg / "mod.py").write_text(
            "from x import counter_inc\n"
            'counter_inc("anything_at_all")\n'
        )
        assert self._findings(pkg) == []

    def test_installed_tree_counters_all_registered(self):
        findings = [
            f for f in lint_mod.lint_package(analysis.package_root())
            if f.rule == "counter-registry"
        ]
        assert findings == []


class TestBaselineGrammar:
    def test_missing_justification_rejected(self, tmp_path):
        p = tmp_path / "baseline.txt"
        p.write_text("host-sync\tcore/foo.py\thot\n")
        with pytest.raises(ValueError, match="justification"):
            lint_mod.load_baseline(str(p))

    def test_unknown_rule_rejected(self, tmp_path):
        p = tmp_path / "baseline.txt"
        p.write_text("made-up-rule\tcore/foo.py\thot\t# because\n")
        with pytest.raises(ValueError, match="unknown rule"):
            lint_mod.load_baseline(str(p))

    def test_baseline_filters_by_scope(self, tmp_path):
        pkg = tmp_path / "pkg"
        (pkg / "core").mkdir(parents=True)
        (pkg / "core" / "foo.py").write_text(
            "def hot(t):\n    return t.numpy()\n"
            "def other(t):\n    return t.numpy()\n"
        )
        base = [("host-sync", "core/foo.py", "hot")]
        findings = lint_mod.lint_package(str(pkg), baseline=base)
        assert [f.scope for f in findings] == ["other"]


# -- lock-discipline checker --------------------------------------------------
class TestLockDiscipline:
    def test_unguarded_mutations_flagged(self):
        src = """
        import threading
        _lock = threading.Lock()
        _table = {}  # guarded_by: _lock
        def bad_set(k, v):
            _table[k] = v
        def bad_method():
            _table.clear()
        def bad_del(k):
            del _table[k]
        """
        findings = locks_mod.check_source(textwrap.dedent(src), "x.py")
        assert len(findings) == 3
        assert all(f.rule == "lock-discipline" for f in findings)
        assert {f.scope for f in findings} == {"bad_set", "bad_method", "bad_del"}

    def test_with_lock_and_requires_lock_pass(self):
        src = """
        import threading
        _lock = threading.Lock()
        _table = {}  # guarded_by: _lock
        def good(k, v):
            with _lock:
                _table[k] = v
        @requires_lock("_lock")
        def helper(k):
            _table.pop(k, None)
        """
        assert locks_mod.check_source(textwrap.dedent(src), "x.py") == []

    def test_instance_attr_and_init_exemption(self):
        src = """
        import threading
        class T:
            def __init__(self):
                self._lk = threading.Lock()
                self._items = []  # guarded_by: _lk
                self._items.append(0)  # building, not yet shared
            def bad(self, x):
                self._items.append(x)
            def good(self, x):
                with self._lk:
                    self._items.append(x)
        """
        findings = locks_mod.check_source(textwrap.dedent(src), "x.py")
        assert [f.scope for f in findings] == ["T.bad"]

    def test_suppression_applies(self):
        src = """
        import threading
        _lock = threading.Lock()
        _t = {}  # guarded_by: _lock
        def startup(v):
            _t["k"] = v  # lint: ok(lock-discipline)
        """
        assert locks_mod.check_source(textwrap.dedent(src), "x.py") == []

    def test_same_attr_name_in_two_classes_keeps_its_own_lock(self):
        # annotations are keyed by enclosing class: B's _q guarded by _lb
        # must not be validated against A's _la (or vice versa)
        src = """
        import threading
        class A:
            def __init__(self):
                self._la = threading.Lock()
                self._q = []  # guarded_by: _la
            def good(self, x):
                with self._la:
                    self._q.append(x)
        class B:
            def __init__(self):
                self._lb = threading.Lock()
                self._q = []  # guarded_by: _lb
            def bad(self, x):
                with self._la:  # wrong lock: A's, not B's
                    self._q.append(x)
        """
        findings = locks_mod.check_source(textwrap.dedent(src), "x.py")
        assert [f.scope for f in findings] == ["B.bad"]
        assert "_lb" in findings[0].message

    def test_closure_does_not_inherit_enclosing_with_lock(self):
        # a def inside `with _lock:` is a closure that may run LATER on
        # another thread — its body must not be treated as lock-held
        src = """
        import threading
        _lock = threading.Lock()
        _t = {}  # guarded_by: _lock
        def spawn():
            with _lock:
                def worker():
                    _t["k"] = 1
                return worker
        """
        findings = locks_mod.check_source(textwrap.dedent(src), "x.py")
        assert [f.scope for f in findings] == ["spawn.worker"]

    def test_annotated_modules_in_tree_are_clean(self):
        findings = locks_mod.check_lock_discipline(analysis.package_root())
        assert findings == [], "\n".join(map(repr, findings))


# -- runtime ownership assertions (FLAGS_thread_checks) -----------------------
@pytest.fixture
def thread_checks_on():
    flags.set_flags({"FLAGS_thread_checks": True})
    yield
    flags.set_flags({"FLAGS_thread_checks": False})


class TestThreadChecks:
    def test_flag_off_is_identity(self):
        d = {}
        assert thread_checks.guarded(d, threading.Lock(), "t") is d
        assert thread_checks.owned(d, "t") is d

    def test_guarded_mutation_requires_lock(self, thread_checks_on):
        lk = threading.RLock()
        d = thread_checks.guarded({}, lk, "test.table")
        with pytest.raises(thread_checks.OwnershipError):
            d["k"] = 1
        with lk:
            d["k"] = 1
            d.update(z=2)
            del d["z"]
        assert d["k"] == 1  # reads never need the lock
        assert "k" in d and len(d) == 1

    def test_deliberately_racy_mutation_fails_deterministically(
        self, thread_checks_on
    ):
        """The acceptance fixture: two threads, one lock, one of them
        'forgets' it — the race fails at the mutation site every time, not
        as a corrupted table later. RLock: ownership (not mere locked-ness)
        is what makes the verdict deterministic under GIL interleaving."""
        lk = threading.RLock()
        table = thread_checks.guarded({}, lk, "racy.table")
        errors = []

        def disciplined():
            for i in range(50):
                with lk:
                    table[f"d{i}"] = i

        def racy():
            try:
                for i in range(50):
                    table[f"r{i}"] = i  # no lock: must raise on iteration 0
            except thread_checks.OwnershipError as e:
                errors.append(e)

        t1 = threading.Thread(target=disciplined)
        t2 = threading.Thread(target=racy)
        t1.start(); t2.start(); t1.join(); t2.join()
        assert len(errors) == 1
        assert not any(k.startswith("r") for k in table)

    def test_augmented_assignment_on_proxy_checked(self, thread_checks_on):
        lk = threading.RLock()
        lst = thread_checks.guarded([1], lk, "aug")
        st = thread_checks.guarded({1}, lk, "aug-set")
        with pytest.raises(thread_checks.OwnershipError):
            lst += [2]
        with pytest.raises(thread_checks.OwnershipError):
            st |= {2}
        with lk:
            lst += [2]
            st |= {2}
        assert list(lst) == [1, 2] and 2 in st

    def test_atomic_open_threads_do_not_share_tmp(self, tmp_path):
        from paddle_tpu.framework.io import atomic_open

        path = str(tmp_path / "out.json")
        payloads = [("a" * 4096) + "\n", ("b" * 4096) + "\n"]
        errs = []

        def write(p):
            try:
                for _ in range(20):
                    with atomic_open(path) as f:
                        f.write(p)
            except Exception as e:  # pragma: no cover
                errs.append(e)

        ts = [threading.Thread(target=write, args=(p,)) for p in payloads]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert not errs
        content = open(path).read()
        assert content in payloads  # one COMPLETE write won; never interleaved
        assert not [n for n in os.listdir(tmp_path) if ".tmp" in n]

    def test_owned_structure_pins_owner_thread(self, thread_checks_on):
        box = thread_checks.owned([0], "counter")
        box[0] += 1  # this thread becomes the owner
        caught = []

        def foreign():
            try:
                box[0] += 1
            except thread_checks.OwnershipError as e:
                caught.append(e)

        t = threading.Thread(target=foreign)
        t.start(); t.join()
        assert len(caught) == 1 and "owned by" in str(caught[0])
        assert box[0] == 1

    def test_requires_lock_decorator_asserts(self, thread_checks_on):
        lk = threading.RLock()

        @thread_checks.requires_lock(lk, name="lk")
        def helper(d):
            d["x"] = 1

        with pytest.raises(thread_checks.OwnershipError):
            helper({})
        with lk:
            d = {}
            helper(d)
        assert d == {"x": 1}

    def test_watchdog_tables_wrapped_under_flag(self, thread_checks_on, tmp_path):
        from paddle_tpu.distributed import watchdog

        watchdog.reset()
        try:
            watchdog.configure(rank=0, world_size=1, store=None,
                               progress_dir=str(tmp_path))
            # publish goes through the lock internally: fine
            watchdog.publish(step=1, phase="test", force=True)
            assert watchdog.local_progress()["step"] == 1
            # an unguarded direct mutation of the shared table raises
            with pytest.raises(thread_checks.OwnershipError):
                watchdog._guards[99] = (0.0, "rogue")
        finally:
            watchdog.reset()

    def test_device_prefetcher_consumer_ownership(self, thread_checks_on):
        from paddle_tpu.io import DevicePrefetcher

        p = DevicePrefetcher(iter([np.zeros((2, 2), np.float32)]))
        try:
            batch = next(p)  # main thread becomes the consumer/owner
            assert tuple(batch.shape) == (2, 2)
            caught = []

            def foreign():
                try:
                    p._consumed[0] += 1
                except thread_checks.OwnershipError as e:
                    caught.append(e)

            t = threading.Thread(target=foreign)
            t.start(); t.join()
            assert len(caught) == 1
        finally:
            p.close()


# -- entry-point ergonomics ---------------------------------------------------
class TestCLI:
    def test_no_baseline_reports_grandfathered(self, capsys):
        from paddle_tpu.analysis.__main__ import main

        rc = main(["--no-baseline", "--no-selfcheck"])
        out = capsys.readouterr().out
        assert rc == 1  # the grandfathered funnel findings resurface
        assert "host-sync" in out and "baseline" in out

    def test_selfcheck_rejects_seeded_cycle(self):
        from paddle_tpu.analysis.__main__ import _verifier_selfcheck

        assert _verifier_selfcheck() == 0
