"""Beam search decode (models/generation.py _build_beam_decode) vs a plain
python/numpy reference that re-scores every beam by full forward recompute.

Parity: reference ``operators/math/beam_search.cc`` semantics — top-k over
(beam score + log-prob) with beam reordering; finished beams extend only
with eos at no cost.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core.engine import no_grad
from paddle_tpu.models.gpt import GPTConfig, GPTForPretraining


def _tiny_model():
    paddle.seed(11)
    cfg = GPTConfig(
        vocab_size=37, hidden_size=32, num_layers=2, num_heads=4,
        max_position_embeddings=64, hidden_dropout=0.0, attention_dropout=0.0,
        use_mp_layers=False, fused_lm_loss=False,
    )
    m = GPTForPretraining(cfg)
    m.eval()
    return m, cfg


def _ref_beam(model, ids, steps, K, eos=None):
    """Reference: recompute full logits per step per beam (no KV cache)."""
    import jax

    B, T0 = ids.shape
    with no_grad():
        beams = [[(list(ids[b]), 0.0, False)] for b in range(B)]  # (toks, score, done)
        for _ in range(steps):
            new_beams = []
            for b in range(B):
                cands = []
                for toks, score, done in beams[b]:
                    x = paddle.to_tensor(np.asarray([toks], np.int64))
                    logits = model(x).numpy()[0, -1].astype(np.float64)
                    logp = logits - np.log(np.exp(logits - logits.max()).sum()) - logits.max()
                    # note: stable log-softmax
                    m = logits.max()
                    logp = (logits - m) - np.log(np.exp(logits - m).sum())
                    if done and eos is not None:
                        cands.append((toks + [eos], score, True))
                        continue
                    for v in range(len(logp)):
                        nd = done or (eos is not None and v == eos)
                        cands.append((toks + [v], score + logp[v], nd))
                cands.sort(key=lambda c: -c[1])
                new_beams.append(cands[:K])
            beams = new_beams
        out = []
        for b in range(B):
            best = max(beams[b], key=lambda c: c[1])
            out.append(best[0])
        return np.asarray(out)


class TestBeamSearch:
    def test_token_exact_vs_numpy_reference(self):
        model, cfg = _tiny_model()
        ids = np.array([[3, 1, 4], [2, 7, 2]], np.int64)
        steps, K = 5, 3
        got = model.generate(
            paddle.to_tensor(ids), max_new_tokens=steps, num_beams=K,
            do_sample=False,
        ).numpy()
        want = _ref_beam(model, ids, steps, K)
        np.testing.assert_array_equal(got, want)

    def test_beam_beats_or_matches_greedy_logprob(self):
        model, cfg = _tiny_model()
        ids = np.array([[5, 9]], np.int64)
        steps = 6

        def seq_logprob(seq):
            import jax.numpy as jnp

            with no_grad():
                x = paddle.to_tensor(seq[None, :-1].astype(np.int64))
                logits = model(x).numpy()[0].astype(np.float64)
            lp = 0.0
            for t in range(ids.shape[1] - 1, seq.shape[0] - 1):
                row = logits[t]
                m = row.max()
                row = (row - m) - np.log(np.exp(row - m).sum())
                lp += row[seq[t + 1]]
            return lp

        greedy = model.generate(
            paddle.to_tensor(ids), max_new_tokens=steps, do_sample=False
        ).numpy()[0]
        beam = model.generate(
            paddle.to_tensor(ids), max_new_tokens=steps, num_beams=4,
            do_sample=False,
        ).numpy()[0]
        assert seq_logprob(beam) >= seq_logprob(greedy) - 1e-6

    def test_eos_freezes_beam(self):
        model, cfg = _tiny_model()
        ids = np.array([[1, 2]], np.int64)
        out = model.generate(
            paddle.to_tensor(ids), max_new_tokens=8, num_beams=3,
            do_sample=False, eos_token_id=0,
        ).numpy()[0]
        gen = list(out[2:])
        if 0 in gen:
            i = gen.index(0)
            assert all(t == 0 for t in gen[i:])  # frozen after eos
