"""Training stability sentinel (ISSUE 13) — anomaly detection, batch
quarantine, and sample-exact auto-rollback.

Pins the acceptance criteria on CPU:
* an injected grad/loss spike at step k (eager/sync AND lazy-async, and —
  in test_stability_engine — through the engine with and without
  ``FLAGS_shard_weight_update``) is skipped or rolled back per the policy
  ladder, with final weights, optimizer moments, LR-scheduler state and
  sample order BIT-IDENTICAL to an uninterrupted run trained on the same
  data with the quarantined batch excluded;
* the quarantine log names the skipped sample indices + signal values;
* the PR 6 caveat is CLOSED: a non-finite trip surfacing ≤1 step late under
  ``FLAGS_lazy_async`` (the poisoned update has committed — asserted) is
  fully recovered by sentinel rollback instead of being only a documented
  window;
* ``AutoCheckpoint`` anchor pinning: ``protect``/``release`` keep the
  active rollback anchor out of GC's reach even with keep_last=1 and an
  anchor older than the retention window;
* the halt rung dumps a flight post-mortem naming the tripping signal;
* tier-1 inert tripwire: an unconfigured sentinel costs nothing — the
  detector is never called, no threads appear, no per-step host syncs, and
  the lazy drain tap stays None.
"""
import json
import os
import threading

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu import profiler
from paddle_tpu.core import lazy
from paddle_tpu.distributed.checkpoint import AutoCheckpoint
from paddle_tpu.fault import inject
from paddle_tpu.fault import sentinel as sentinel_mod
from paddle_tpu.fault.sentinel import (
    QuarantineLog, StabilityError, StabilitySentinel,
)
from paddle_tpu.profiler import flight

pytestmark = pytest.mark.faults


@pytest.fixture(autouse=True)
def _clean():
    yield
    inject.disarm()
    for s in list(sentinel_mod._active):
        s.close()
    paddle.set_flags({
        "FLAGS_lazy_async": True,
        "FLAGS_stability_enable": False,
        "FLAGS_check_nan_inf": False,
        "FLAGS_shard_weight_update": True,
    })
    lazy.set_lazy_mode(True)


# -- deterministic micro training loop ----------------------------------------
def _data_for(step):
    rng = np.random.RandomState(1000 + step)
    return rng.randn(8, 4).astype(np.float32), rng.randn(8, 1).astype(np.float32)


def _sentinel(anchor=None, **kw):
    cfg = dict(window=32, warmup=3, zmax=50.0, max_skips=2, max_rollbacks=2,
               cooldown=4)
    cfg.update(kw)
    return StabilitySentinel(anchor=anchor, **cfg)


def _run(steps=8, spike=None, pre_q=(), async_on=True, anchor_dir=None,
         sched=False, on_verdict=None, **sentinel_kw):
    """Sentinel-guarded loop over per-step deterministic data. ``pre_q``
    pre-quarantines positions — the reference "uninterrupted run trained on
    the same data with the quarantined batch excluded"."""
    paddle.set_flags({"FLAGS_lazy_async": async_on})
    inject.disarm()
    if spike:
        inject.arm(spike)
    w = paddle.to_tensor(np.full((4, 1), 0.5, np.float32))
    w.stop_gradient = False
    lr = paddle.optimizer.lr.StepDecay(0.05, step_size=3) if sched else 0.05
    opt = paddle.optimizer.Adam(learning_rate=lr, parameters=[w])
    anchor = (AutoCheckpoint(anchor_dir, interval_steps=1, keep_last=2)
              if anchor_dir else None)
    sent = _sentinel(anchor=anchor, **sentinel_kw)
    for pos in pre_q:
        sent.quarantine.add(-1, pos=pos, action="skip")
    state = {"w": w, "opt": opt}
    step = 0
    events = []
    try:
        while step < steps:
            if sent.is_quarantined(pos=(0, step)):
                step += 1
                continue
            x, y = _data_for(step)
            xt, yt = paddle.to_tensor(x), paddle.to_tensor(y)
            loss = ((paddle.matmul(xt, w) - yt) ** 2).mean()
            s = inject.spike("loss.spike", step=step)
            if s is not None:
                loss = loss * s
            loss.backward()
            s = inject.spike("grad.spike", step=step)
            if s is not None:
                w.grad._set_data((w.grad * s)._data)
            v = sent.observe(step, loss=loss, grads=[w.grad], params=[w],
                             lr=opt.get_lr(), pos=(0, step))
            if v is not None:
                events.append(v)
                opt.clear_grad()
                if on_verdict is not None:
                    on_verdict(v, w)
                if v.action == "skip" and v.step == step:
                    step += 1
                    continue
                if v.action == "rollback":
                    step = sent.rollback(v, state) + 1
                    continue
                sent.halt(v)
            opt.step()
            opt.clear_grad()
            if sched:
                opt._learning_rate.step()
            step += 1
            sent.maybe_anchor(step - 1, state)
    finally:
        sent.close()
        inject.disarm()
    moments = {k: np.asarray(lazy.concrete(v)).copy()
               for k, v in opt._accumulators[id(w)].items()}
    return {
        "events": events,
        "quarantine": sent.quarantine.entries(),
        "w": np.asarray(w.numpy()).copy(),
        "moments": moments,
        "opt_step": opt._step_count,
        "lr_state": (opt._learning_rate.state_dict() if sched else None),
        "sentinel": sent,
    }


def _assert_state_equal(a, b):
    np.testing.assert_array_equal(a["w"], b["w"])
    assert a["opt_step"] == b["opt_step"]
    for k in a["moments"]:
        np.testing.assert_array_equal(a["moments"][k], b["moments"][k])
    assert a["lr_state"] == b["lr_state"]


# -- robust statistics --------------------------------------------------------
class TestStats:
    def test_warmup_never_trips_and_folds(self):
        st = sentinel_mod._SignalStats(window=16, warmup=4, zmax=3.0)
        for v in (1.0, 100.0, 1.0, 50.0):  # wild warmup values: no trips
            assert st.judge(v) == (False, 0.0)
        assert len(st._ring) == 4

    def test_spike_trips_and_is_not_folded(self):
        st = sentinel_mod._SignalStats(window=16, warmup=4, zmax=8.0)
        for v in (1.0, 1.1, 0.9, 1.05, 1.0, 0.95):
            st.judge(v)
        n = len(st._ring)
        bad, z = st.judge(1000.0)
        assert bad and z > 8.0
        assert len(st._ring) == n  # the outlier must not shift the baseline
        bad, _ = st.judge(1.02)  # healthy values keep flowing
        assert not bad

    def test_nonfinite_always_anomalous(self):
        st = sentinel_mod._SignalStats(window=16, warmup=100, zmax=1e9)
        assert st.judge(float("nan"))[0] is True
        assert st.judge(float("inf"))[0] is True


# -- policy ladder: skip (synchronous detection) ------------------------------
class TestSkip:
    def test_sync_mode_skip_is_bit_identical_to_excluding_the_batch(self):
        spiked = _run(8, spike="grad.spike:step=4,scale=100000", async_on=False)
        ref = _run(8, pre_q=[(0, 4)], async_on=False)
        _assert_state_equal(spiked, ref)
        (v,) = spiked["events"]
        assert v.action == "skip" and v.step == 4 and not v.late
        # a gradient spike moves both gradient-derived signals; the verdict
        # names the worst-scoring one
        assert v.signal in ("grad_norm", "upd_ratio")

    def test_quarantine_log_names_signals_and_position(self):
        before = profiler.counters().get("stability_skips", 0)
        spiked = _run(8, spike="loss.spike:step=5,scale=1000000", async_on=False)
        (entry,) = spiked["quarantine"]
        assert entry["step"] == 5 and entry["pos"] == [0, 5]
        assert entry["action"] == "skip"
        assert entry["signals"]["loss"] > 1e3  # the condemning values ride along
        assert set(entry["signals"]) == set(sentinel_mod.SIGNALS)
        assert profiler.counters()["stability_skips"] == before + 1

    def test_quarantine_dir_flag_persists_jsonl(self, tmp_path):
        paddle.set_flags(
            {"FLAGS_stability_quarantine_dir": str(tmp_path / "q")})
        try:
            _run(8, spike="loss.spike:step=5,scale=1000000", async_on=False)
        finally:
            paddle.set_flags({"FLAGS_stability_quarantine_dir": ""})
        files = list((tmp_path / "q").glob("quarantine_*.jsonl"))
        assert files
        (rec,) = [json.loads(l) for l in files[0].read_text().splitlines()]
        assert rec["step"] == 5 and rec["action"] == "skip"
        assert rec["signals"]["loss"] > 1e3

    def test_skip_budget_exhaustion_escalates(self, tmp_path):
        # two spiked steps with max_skips=1: first skips, second rolls back
        out = _run(
            10,
            spike="grad.spike:step=4,scale=100000;loss.spike:step=5,scale=1000000",
            async_on=False, anchor_dir=str(tmp_path / "a"), max_skips=1,
        )
        actions = [v.action for v in out["events"]]
        assert actions == ["skip", "rollback"]
        assert {e["step"] for e in out["quarantine"]} == {4, 5}


# -- policy ladder: rollback (deferred detection — the PR 6 caveat closed) ----
class TestRollback:
    def test_lazy_async_nonfinite_trip_recovered_bit_identical(self, tmp_path):
        """PR 6 satellite: under FLAGS_lazy_async the non-finite trip
        surfaces ≤1 step late — the poisoned update has COMMITTED (asserted
        on the live weights at verdict time) — and sentinel rollback still
        recovers bit-identically to a run that skipped the batch up front."""
        poisoned_seen = []

        def on_verdict(v, w):
            if v.action == "rollback":
                poisoned_seen.append(
                    not np.isfinite(np.asarray(lazy.concrete(w._data))).all()
                )

        spiked = _run(
            8, spike="grad.spike:step=4,nonfinite=1", async_on=True,
            anchor_dir=str(tmp_path / "a"), on_verdict=on_verdict,
        )
        ref = _run(8, pre_q=[(0, 4)], async_on=True,
                   anchor_dir=str(tmp_path / "b"))
        _assert_state_equal(spiked, ref)
        (v,) = spiked["events"]
        assert v.action == "rollback" and v.step == 4 and v.late
        assert v.signal == "nonfinite"
        assert poisoned_seen == [True]  # the update really had committed
        (entry,) = spiked["quarantine"]
        assert entry["action"] == "rollback" and entry["pos"] == [0, 4]
        assert np.isfinite(spiked["w"]).all()

    def test_finite_spike_rolls_back_with_lr_scheduler_state(self, tmp_path):
        spiked = _run(9, spike="loss.spike:step=5,scale=1000000", async_on=True,
                      anchor_dir=str(tmp_path / "a"), sched=True)
        ref = _run(9, pre_q=[(0, 5)], async_on=True,
                   anchor_dir=str(tmp_path / "b"), sched=True)
        assert spiked["lr_state"] is not None
        _assert_state_equal(spiked, ref)

    def test_rollback_skips_anchor_saved_in_detection_window(self, tmp_path):
        """An anchor saved at the poisoned step itself carries the bad
        update; resume(max_step=...) must walk past it and the rollback must
        invalidate it (a quarantined step is never re-saved by the replay)."""
        out = _run(8, spike="grad.spike:step=4,scale=1000000", async_on=True,
                   anchor_dir=str(tmp_path / "a"))
        (v,) = out["events"]
        assert v.action == "rollback" and v.step == 4
        # the poisoned step-4 anchor was invalidated by the rollback (the
        # quarantined step is never replayed, so it would otherwise shadow
        # future rollbacks forever)
        assert not os.path.isdir(os.path.join(str(tmp_path / "a"), "step_4"))
        # the replay's clean anchors took over as the resume frontier
        ac = AutoCheckpoint(str(tmp_path / "a"), interval_steps=1)
        w2 = paddle.to_tensor(np.zeros((4, 1), np.float32))
        assert ac.resume({"w": w2}) == 7
        np.testing.assert_array_equal(w2.numpy(), out["w"])

    def test_no_anchor_degrades_to_halt(self):
        with pytest.raises(StabilityError, match="sentinel halt"):
            _run(8, spike="grad.spike:step=4,scale=1000000", async_on=True,
                 max_skips=0)


# -- policy ladder: halt ------------------------------------------------------
class TestHalt:
    def test_halt_dumps_flight_postmortem_naming_signal(self, tmp_path, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_FLIGHT_DIR", str(tmp_path))
        before = profiler.counters().get("stability_halts", 0)
        with pytest.raises(StabilityError) as ei:
            _run(8, spike="loss.spike:step=4,scale=1000000", async_on=False,
                 max_skips=0, max_rollbacks=0)
        assert ei.value.verdict.signal == "loss"
        assert ei.value.history  # signal history rides the exception
        doc = json.load(open(flight.last_dump()))
        assert doc["reason"] == "stability_halt"
        assert doc["extra"]["signal"] == "loss"
        assert doc["extra"]["verdict"]["step"] == 4
        assert len(doc["extra"]["history"]) >= 3
        # the registered context provider adds the sentinel view to any dump
        assert "stability" in doc["context"]
        assert profiler.counters()["stability_halts"] == before + 1


# -- anchor pinning (satellite 1) ---------------------------------------------
class TestAnchorPinning:
    def test_gc_never_collects_protected_anchor(self, tmp_path):
        """keep_last=1 + an anchor OLDER than the window: without the pin,
        GC collects the only checkpoint the sentinel could roll back to."""
        ac = AutoCheckpoint(str(tmp_path / "a"), interval_steps=1, keep_last=1)
        w = paddle.to_tensor(np.zeros(3, np.float32))
        w._set_data((w + 1.0)._data)
        ac.maybe_save(1, {"w": w})
        ac.protect(1)
        for s in (2, 3, 4):
            w._set_data((w + 1.0)._data)
            ac.maybe_save(s, {"w": w})
        assert os.path.isdir(ac._step_path(1))  # pinned: survived keep_last=1
        assert not os.path.isdir(ac._step_path(2))  # unpinned: collected
        w2 = paddle.to_tensor(np.zeros(3, np.float32))
        assert ac.resume({"w": w2}, max_step=1) == 1
        np.testing.assert_array_equal(w2.numpy(), np.full(3, 1.0))
        # release: the next save's GC drops it
        ac.release(1)
        w._set_data((w + 1.0)._data)
        ac.maybe_save(5, {"w": w})
        assert not os.path.isdir(ac._step_path(1))

    def test_invalidate_refuses_protected_anchor(self, tmp_path):
        ac = AutoCheckpoint(str(tmp_path / "a"), interval_steps=1, keep_last=2)
        w = paddle.to_tensor(np.ones(2, np.float32))
        ac.maybe_save(1, {"w": w})
        ac.protect(1)
        with pytest.raises(ValueError, match="protected"):
            ac.invalidate(1)
        ac.release(1)
        ac.invalidate(1)
        assert not os.path.isdir(ac._step_path(1))

    def test_sentinel_pins_only_judged_clean_anchors(self, tmp_path):
        """The pin trails the judgment horizon: an anchor saved at a step
        whose signals have not been judged clean yet is not pinned."""
        ac = AutoCheckpoint(str(tmp_path / "a"), interval_steps=1, keep_last=2)
        sent = _sentinel(anchor=ac)
        try:
            w = paddle.to_tensor(np.ones(2, np.float32))
            for step in range(1, 4):
                # committed observations defer judgment by one step — the
                # anchor at step N lands before step N's signals are judged
                sent.observe(step, loss=paddle.to_tensor(np.float32(1.0)),
                             committed=True)
                sent.maybe_anchor(step, {"w": w})
            assert sent._pinned == 2  # step 3's anchor saved BEFORE judgment
            sent.poll()  # judge the last deferred entry clean
            assert sent._pinned == 3
        finally:
            sent.close()


# -- spike injection points (satellite 3) -------------------------------------
class TestSpikePoints:
    def test_grammar_and_determinism(self):
        inject.arm("loss.spike:step=3,scale=7;grad.spike:at=2,nonfinite=1")
        assert inject.spike("loss.spike", step=2) is None
        assert inject.spike("loss.spike", step=3) == 7.0
        assert inject.spike("grad.spike") is None        # call 1
        assert inject.spike("grad.spike") == float("inf")  # call 2 == at
        inject.disarm()
        assert inject.spike("loss.spike", step=3) is None

    def test_non_spike_point_rejected(self):
        with pytest.raises(KeyError, match="spike"):
            inject.spike("ckpt.write")

    def test_unknown_point_name_rejected_by_arm(self):
        with pytest.raises(KeyError, match="loss.spike"):
            inject.arm({"loss.spke": {}})


# -- hapi.Model.fit wiring ----------------------------------------------------
class _XYDataset(paddle.io.Dataset):
    def __init__(self, n=32):
        rng = np.random.RandomState(0)
        self.x = rng.randn(n, 8).astype(np.float32)
        self.y = self.x.sum(axis=1, keepdims=True).astype(np.float32)

    def __len__(self):
        return len(self.x)

    def __getitem__(self, i):
        return self.x[i], self.y[i]


class TestFitIntegration:
    def _fit(self, tmp_path, tag, spike=None, pre_q=(), **sentinel_kw):
        inject.disarm()
        if spike:
            inject.arm(spike)
        paddle.seed(7)
        net = nn.Linear(8, 1)
        model = paddle.Model(net)
        model.prepare(
            optimizer=paddle.optimizer.Adam(
                learning_rate=0.05, parameters=net.parameters()),
            loss=lambda pred, y: F.mse_loss(pred, y),
        )
        loader = paddle.io.DataLoader(_XYDataset(), batch_size=4, shuffle=True,
                                      seed=99)
        anchor = AutoCheckpoint(str(tmp_path / tag), interval_steps=1,
                                keep_last=2)
        sent = _sentinel(anchor=anchor, zmax=60, **sentinel_kw)
        for pos in pre_q:
            sent.quarantine.add(-1, pos=pos, action="skip")
        try:
            model.fit(loader, epochs=2, verbose=0, stability=sent)
        finally:
            sent.close()
            inject.disarm()
        return sent, [np.asarray(p.numpy()).copy() for p in net.parameters()]

    def test_rollback_parity_and_index_level_skip(self, tmp_path):
        skips0 = profiler.counters().get("io_quarantine_skips", 0)
        s1, p1 = self._fit(tmp_path, "a", spike="grad.spike:step=5,scale=1000000")
        s2, p2 = self._fit(tmp_path, "b", pre_q=[(0, 5)])
        for a, b in zip(p1, p2):
            np.testing.assert_array_equal(a, b)
        (entry,) = s1.quarantine.entries()
        assert entry["pos"] == [0, 5] and entry["action"] == "rollback"
        # the quarantine log names the exact samples of the condemned batch
        # (reconstructed from the seeded sampler), and the replay skipped it
        # at the INDEX level (both runs exercise the skip path)
        assert len(entry["sample_indices"]) == 4
        assert profiler.counters()["io_quarantine_skips"] > skips0

    def test_flags_enable_builds_sentinel_and_disabled_is_default_loop(self, tmp_path):
        # FLAGS_stability_enable + ckpt dir: fit builds and closes its own
        # sentinel; without the flag, fit must not touch the sentinel module
        paddle.set_flags({
            "FLAGS_stability_enable": True,
            "FLAGS_stability_ckpt_dir": str(tmp_path / "fl"),
            "FLAGS_stability_anchor_interval": 4,
        })
        try:
            paddle.seed(7)
            net = nn.Linear(8, 1)
            model = paddle.Model(net)
            model.prepare(
                optimizer=paddle.optimizer.SGD(
                    learning_rate=0.05, parameters=net.parameters()),
                loss=lambda pred, y: F.mse_loss(pred, y),
            )
            before = profiler.counters().get("stability_observed", 0)
            model.fit(_XYDataset(), batch_size=4, epochs=1, shuffle=False,
                      verbose=0)
            assert profiler.counters()["stability_observed"] > before
            assert lazy._stability_tap is None  # fit closed its sentinel
            assert os.path.isdir(str(tmp_path / "fl"))  # anchors landed
        finally:
            paddle.set_flags({
                "FLAGS_stability_enable": False,
                "FLAGS_stability_ckpt_dir": "",
                "FLAGS_stability_anchor_interval": 25,
            })


# -- engine step path (with and without the ZeRO-1 sharded update) ------------
@pytest.mark.multichip
class TestEngineSentinel:
    """Acceptance: the sentinel works through the engine's donated fused
    step, where the update has COMMITTED by the time the loss is readable —
    every trip escalates to rollback, restoring the engine-resident ZeRO
    shards via engine_state_dict/engine_apply_state, with bit-identical
    parity against a run that excluded the batch, both with and without
    ``FLAGS_shard_weight_update``."""

    def _batch_for(self, step):
        rng = np.random.RandomState(500 + step)
        return rng.randn(8, 8).astype(np.float32), rng.randn(8, 4).astype(np.float32)

    def _run(self, wus, tmp_path, tag, spike=None, pre_q=(), steps=7):
        import jax
        from jax.sharding import Mesh

        from paddle_tpu.distributed.engine import HybridParallelEngine

        paddle.set_flags({"FLAGS_shard_weight_update": wus})
        inject.disarm()
        if spike:
            inject.arm(spike)
        paddle.seed(5)
        m = nn.Linear(8, 4)
        opt = paddle.optimizer.Adam(learning_rate=0.05, parameters=m.parameters())
        mesh = Mesh(np.asarray(jax.devices()[:8]), ("dp",))
        eng = HybridParallelEngine(
            m, opt, lambda mm, x, y: F.mse_loss(mm(x), y), mesh=mesh
        )
        anchor = AutoCheckpoint(str(tmp_path / tag), interval_steps=1,
                                keep_last=2)
        sent = StabilitySentinel.for_engine(
            eng, anchor, window=32, warmup=2, zmax=50, max_skips=0,
            max_rollbacks=2, cooldown=4,
        )
        for b in pre_q:
            sent.quarantine.add(-1, pos=(0, b), action="skip")
        ordinal = 0
        ordinal_at_anchor = {}
        rolled = []
        try:
            while ordinal < steps:
                if sent.is_quarantined(pos=(0, ordinal)):
                    ordinal += 1
                    continue
                x, y = self._batch_for(ordinal)
                sent.note_batch((0, ordinal))
                eng.train_step(x, y)
                v = sent.take_verdict()
                if v is not None:
                    assert v.late  # committed observations can never skip
                    if v.action == "rollback":
                        a = sent.rollback(v)
                        rolled.append((v.step, a))
                        ordinal = ordinal_at_anchor.get(a, -1) + 1
                        continue
                    sent.halt(v)
                if sent.maybe_anchor(opt._step_count):
                    ordinal_at_anchor[opt._step_count] = ordinal
                ordinal += 1
            sent.poll()
        finally:
            sent.close()
            inject.disarm()
        eng.sync_optimizer_state()
        params = [np.asarray(p.numpy()).copy() for p in m.parameters()]
        moms = [
            {k: np.asarray(lazy.concrete(v)).copy()
             for k, v in opt._accumulators[id(p)].items()}
            for p in m.parameters()
        ]
        return rolled, sent.quarantine.entries(), params, moms, opt._step_count

    @pytest.mark.parametrize("wus", [False, True])
    def test_spiked_batch_rolled_back_bit_identical(self, tmp_path, wus):
        r1, q1, p1, m1, s1 = self._run(
            wus, tmp_path, f"a{int(wus)}", spike="loss.spike:step=3,scale=1000000"
        )
        r2, q2, p2, m2, s2 = self._run(wus, tmp_path, f"b{int(wus)}", pre_q=[3])
        assert r1 and not r2  # the spiked run rolled back, the reference never
        (entry,) = q1
        assert entry["pos"] == [0, 3] and entry["action"] == "rollback"
        assert s1 == s2  # optimizer step counts agree (skipped batch absent)
        for a, b in zip(p1, p2):
            np.testing.assert_array_equal(a, b)
        for d1, d2 in zip(m1, m2):
            for k in d1:
                np.testing.assert_array_equal(d1[k], d2[k])


# -- tier-1 inert tripwire (satellite 6) --------------------------------------
class TestInertTripwire:
    def test_unconfigured_training_never_touches_the_detector(self, monkeypatch):
        """No sentinel configured → the detector is NEVER called (exploded
        here), the drain tap stays None, no new threads, no sentinel
        readbacks — the disabled path is attribute probes only."""
        def boom(*a, **k):
            raise AssertionError("stability detector called without a sentinel")

        monkeypatch.setattr(StabilitySentinel, "observe", boom)
        monkeypatch.setattr(StabilitySentinel, "_judge", boom)
        assert lazy._stability_tap is None
        threads0 = threading.active_count()
        reads0 = profiler.counters().get("stability_readbacks", 0)
        obs0 = profiler.counters().get("stability_observed", 0)

        # plain fit loop (flag off)
        paddle.seed(0)
        net = nn.Linear(8, 1)
        model = paddle.Model(net)
        model.prepare(
            optimizer=paddle.optimizer.SGD(
                learning_rate=0.05, parameters=net.parameters()),
            loss=lambda pred, y: F.mse_loss(pred, y),
        )
        model.fit(_XYDataset(16), batch_size=4, epochs=1, shuffle=False,
                  verbose=0)
        # plain lazy train steps (the tap probe in flush is all that runs)
        w = paddle.to_tensor(np.ones((4, 1), np.float32))
        w.stop_gradient = False
        for step in range(3):
            x, y = _data_for(step)
            loss = ((paddle.matmul(paddle.to_tensor(x), w) - paddle.to_tensor(y)) ** 2).mean()
            loss.backward()
            w._set_data((w - 0.1 * w.grad)._data)
            w.clear_grad()
            float(loss.item())

        assert lazy._stability_tap is None
        assert threading.active_count() == threads0
        assert profiler.counters().get("stability_readbacks", 0) == reads0
        assert profiler.counters().get("stability_observed", 0) == obs0

    def test_close_disarms_tap_and_provider(self):
        sent = _sentinel()
        assert lazy._stability_tap is not None
        sent.close()
        assert lazy._stability_tap is None
        # close is idempotent and the flight provider is gone
        sent.close()
        from paddle_tpu.profiler.flight import _context_providers

        assert "stability" not in _context_providers


# -- one-readback-per-step discipline -----------------------------------------
class TestReadbackBudget:
    def test_one_fused_readback_per_step(self):
        """The sentinel's entire per-step host traffic is ONE 4-float
        readback (the fused signal pack) riding the deferred drain."""
        out = _run(6, async_on=True)
        c = profiler.counters()
        # 6 observes; the final pending handle is dropped at close (≤1 step
        # late contract, nothing newer arrived) — so ≤1 readback per step
        assert c.get("stability_readbacks", 0) >= 1
        assert out["events"] == []

    def test_signal_pack_rides_the_step_flush(self):
        """In lazy mode the signal node fuses into the step's own flush —
        observing must not add a flush of its own."""
        paddle.set_flags({"FLAGS_lazy_async": True})
        sent = _sentinel()
        try:
            w = paddle.to_tensor(np.full((4, 1), 0.5, np.float32))
            w.stop_gradient = False
            opt = paddle.optimizer.Adam(learning_rate=0.05, parameters=[w])
            # warm one step so the loop below is the steady state
            x, y = _data_for(0)
            loss = ((paddle.matmul(paddle.to_tensor(x), w) - paddle.to_tensor(y)) ** 2).mean()
            loss.backward()
            sent.observe(0, loss=loss, grads=[w.grad], params=[w], lr=0.05)
            opt.step()
            opt.clear_grad()
            flushes0 = profiler.counters().get("lazy_flushes", 0)
            for step in range(1, 4):
                x, y = _data_for(step)
                loss = ((paddle.matmul(paddle.to_tensor(x), w) - paddle.to_tensor(y)) ** 2).mean()
                loss.backward()
                sent.observe(step, loss=loss, grads=[w.grad], params=[w], lr=0.05)
                opt.step()
                opt.clear_grad()
            assert profiler.counters()["lazy_flushes"] - flushes0 == 3
        finally:
            sent.close()
