"""Test harness config.

SURVEY.md §4 lesson: distributed tests run on a CPU-simulated multi-device
mesh — the TPU analogue of the reference's multiprocess-on-one-host trick
(test_dist_base.py:783). Must set XLA flags before jax import.
"""
import os

os.environ["JAX_PLATFORMS"] = "cpu"  # force: the shell may point at a TPU tunnel
# Lazy-graph IR verifier (analysis/verify_graph.py): default ON for the whole
# suite via the flags env pickup — every flush in every test re-checks the
# wiring/leaf-table/donation/signature invariants, so a record-time
# bookkeeping slip fails as a structured GraphInvariantError at its flush
# instead of as a wrong cached executable three tests later. Production
# default stays off (one flag probe per flush, pinned by a tripwire).
os.environ.setdefault("FLAGS_lazy_verify", "1")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = flags + " --xla_force_host_platform_device_count=8"

# sitecustomize may have imported jax already (TPU tunnel images), in which
# case the env var is too late — force the config directly before any backend
# is initialized.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _seed():
    import paddle_tpu

    paddle_tpu.seed(2024)
    np.random.seed(2024)
    yield


# Smoke tier: `pytest -m smoke` runs a <60s cross-section (tensor ops,
# autograd engine, lazy batching, regression pins) — the always-run gate;
# the full suite is the per-round regression sweep.
_SMOKE_MODULES = {
    "test_tensor_ops", "test_autograd", "test_lazy", "test_regressions",
    "test_lazy_donation",
}


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "smoke: fast cross-section of the suite (<60s total)"
    )
    config.addinivalue_line(
        "markers",
        "slow: excluded from the tier-1 run (-m 'not slow') to hold its "
        "time budget; redundant grid points and heavy cross-feature "
        "composes whose core contract is already pinned by a tier-1 test",
    )
    config.addinivalue_line(
        "markers",
        "faults: fault-injection / fault-tolerance tests (CPU-fast, tier-1)",
    )
    config.addinivalue_line(
        "markers",
        "multichip: N-device tests on the virtual CPU mesh (8-device DP "
        "perf/parity); auto-skipped when the environment provides fewer "
        "devices — the same skip discipline as the multiprocess-env tests",
    )
    config.addinivalue_line(
        "markers",
        "chaos: multi-process chaos-injection recovery tests (kill/hang a "
        "rank mid-run, assert bounded-time coordinated recovery); each "
        "worker is a fresh interpreter importing jax, so the suite needs a "
        "real multi-process budget — auto-skipped on the CPU tier unless "
        "PADDLE_TPU_CHAOS=1 opts in",
    )


def _chaos_world_available() -> bool:
    """The chaos suite spawns whole fresh-interpreter worlds (jax import per
    worker). The JAX_PLATFORMS=cpu CI tier lacks that process budget, so
    chaos runs only on explicit opt-in."""
    if os.environ.get("PADDLE_TPU_CHAOS") == "1":
        return True
    return os.environ.get("JAX_PLATFORMS", "cpu") != "cpu"


def pytest_collection_modifyitems(config, items):
    n_devices = jax.device_count()
    for item in items:
        if item.module.__name__ in _SMOKE_MODULES:
            item.add_marker(pytest.mark.smoke)
        if item.get_closest_marker("multichip") is not None and n_devices < 8:
            item.add_marker(pytest.mark.skip(
                reason=f"multichip tests need 8 devices, have {n_devices}"
            ))
        if item.get_closest_marker("chaos") is not None and not _chaos_world_available():
            item.add_marker(pytest.mark.skip(
                reason="chaos tests spawn fresh multi-process worlds; the "
                "JAX_PLATFORMS=cpu tier lacks the process budget "
                "(set PADDLE_TPU_CHAOS=1 to opt in)"
            ))
