"""YOLOv3 detection model + round-5 detection ops.

Reference: detection op family (paddle/fluid/operators/detection/) and the
PaddleDetection YOLO stack the BASELINE PP-YOLOE row comes from. Matrix NMS
properties are checked against its paper semantics (score decay), the hard
NMS against the host reference implementation.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.vision import ops as vops
from paddle_tpu.vision.models import YOLOv3, YOLOv3Postprocess


def T(a):
    return paddle.to_tensor(a)


class TestDetectionOps:
    def test_iou_similarity_values(self):
        a = np.array([[0, 0, 10, 10], [5, 5, 15, 15]], np.float32)
        b = np.array([[0, 0, 10, 10]], np.float32)
        iou = np.asarray(vops.iou_similarity(T(a), T(b))._data)
        assert abs(iou[0, 0] - 1.0) < 1e-6
        assert abs(iou[1, 0] - 25.0 / 175.0) < 1e-5

    def test_box_clip(self):
        b = np.array([[-5, -5, 20, 20]], np.float32)
        out = np.asarray(vops.box_clip(T(b), T(np.array([10.0, 12.0], np.float32)))._data)
        np.testing.assert_allclose(out[0], [0, 0, 11, 9])

    def test_anchor_generator_shapes_and_centers(self):
        x = T(np.zeros((1, 8, 4, 6), np.float32))
        a, v = vops.anchor_generator(x, [32.0], [1.0], [16, 16])
        a = np.asarray(a._data)
        assert a.shape == (4, 6, 1, 4)
        # first cell center at offset*stride = 8 -> box [8-16, 8-16, 8+16, 8+16]
        np.testing.assert_allclose(a[0, 0, 0], [-8, -8, 24, 24], atol=1e-5)

    def test_bipartite_match_greedy(self):
        d = np.array([[0.9, 0.1], [0.8, 0.7]], np.float32)
        idx, val = vops.bipartite_match(T(d))
        # greedy: (0,0)=0.9 first, then (1,1)=0.7
        assert list(np.asarray(idx._data)) == [0, 1]
        np.testing.assert_allclose(np.asarray(val._data), [0.9, 0.7], atol=1e-6)

    def test_matrix_nms_decays_duplicates(self):
        boxes = np.array([[0, 0, 10, 10], [0.5, 0.5, 10.5, 10.5], [50, 50, 60, 60]], np.float32)
        scores = np.array([[0.9, 0.85, 0.8]], np.float32)
        out, idx, num = vops.matrix_nms(T(boxes), T(scores), score_threshold=0.05)
        out = np.asarray(out._data)
        # the far-away box must NOT be decayed: its score survives intact
        kept = {round(float(s), 4) for s in out[:3, 1] if s > 0}
        assert 0.9 in kept and 0.8 in kept
        # the near-duplicate decays well below its original 0.85
        dup = sorted(kept - {0.9, 0.8})
        assert dup and dup[0] < 0.3

    def test_multiclass_nms_matches_host_nms(self):
        rng = np.random.RandomState(0)
        base = rng.rand(8, 2) * 40
        boxes = np.concatenate([base, base + 20 + rng.rand(8, 2) * 10], 1).astype(np.float32)
        scores = rng.rand(1, 8).astype(np.float32)
        out, idx, num = vops.multiclass_nms(
            T(boxes), T(scores), score_threshold=0.0, nms_threshold=0.5)
        got = sorted(int(i) for i in np.asarray(idx._data) if i >= 0)
        keep_ref = np.asarray(vops.nms(T(boxes), 0.5, scores=T(scores[0]))._data)
        assert got == sorted(keep_ref.tolist())

    def test_target_assign(self):
        x = np.arange(12, dtype=np.float32).reshape(3, 4)
        mi = np.array([1, -1, 2])
        out, w = vops.target_assign(T(x), T(mi))
        np.testing.assert_allclose(np.asarray(out._data)[0], x[1])
        np.testing.assert_allclose(np.asarray(out._data)[1], 0)
        assert list(np.asarray(w._data)[:, 0]) == [1, 0, 1]


class TestYOLOv3:
    def _tiny(self):
        paddle.seed(0)
        return YOLOv3(num_classes=4, depths=(1, 1, 1, 1, 1))

    def test_forward_shapes(self):
        m = self._tiny()
        m.eval()
        x = T(np.random.RandomState(0).randn(2, 3, 64, 64).astype(np.float32))
        outs = m(x)
        assert [tuple(o.shape) for o in outs] == [
            (2, 27, 2, 2), (2, 27, 4, 4), (2, 27, 8, 8)]

    def test_postprocess_static_shape(self):
        m = self._tiny()
        m.eval()
        post = YOLOv3Postprocess(m, img_hw=(64, 64), keep_top_k=20)
        x = T(np.random.RandomState(0).randn(2, 3, 64, 64).astype(np.float32))
        dets = post(x)
        assert tuple(dets.shape) == (2, 20, 6)

    def test_loss_trains(self):
        m = self._tiny()
        m.train()
        opt = paddle.optimizer.Adam(learning_rate=1e-3, parameters=m.parameters())
        rng = np.random.RandomState(0)
        x = T(rng.randn(2, 3, 64, 64).astype(np.float32) * 0.1)
        gt = np.zeros((2, 3, 4), np.float32)
        gt[:, 0] = [0.5, 0.5, 0.25, 0.4]
        gl = np.full((2, 3), -1, np.int64)
        gl[:, 0] = 1
        losses = []
        for _ in range(5):
            loss = m.loss(x, T(gt), T(gl))
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss.item()))
        assert np.isfinite(losses).all()
        assert losses[-1] < losses[0]

    def test_aot_roundtrip_through_predictor(self, tmp_path):
        from paddle_tpu.static import InputSpec
        from paddle_tpu.inference import Config, create_predictor

        m = self._tiny()
        m.eval()
        post = YOLOv3Postprocess(m, img_hw=(64, 64), keep_top_k=10)
        prefix = str(tmp_path / "yolo")
        paddle.static.save_inference_model(
            prefix, [InputSpec([1, 3, 64, 64], "float32", name="image")], post)
        pred = create_predictor(Config(prefix))
        x = np.random.RandomState(0).randn(1, 3, 64, 64).astype(np.float32)
        h = pred.get_input_handle(pred.get_input_names()[0])
        h.copy_from_cpu(x)
        pred.run()
        out = pred.get_output_handle(pred.get_output_names()[0]).copy_to_cpu()
        assert out.shape == (1, 10, 6)
        want = np.asarray(post(T(x))._data)
        np.testing.assert_allclose(out, want, atol=2e-3)
