"""Component tests: sparse tensors, SelectedRows, quantization (QAT/PTQ),
custom-op plugin, DLPack, ASP 2:4, sharded checkpoint + auto-resume,
auto-parallel completion + XLA cost model.
"""
import os

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn


class TestSparse:
    def test_coo_roundtrip_and_ops(self):
        dense = np.array([[0, 1.0, 0], [2.0, 0, 3.0]], np.float32)
        sp = paddle.sparse.to_sparse_coo(paddle.to_tensor(dense))
        assert sp.is_sparse and sp.nnz() == 3
        assert sp.shape == [2, 3]
        np.testing.assert_allclose(sp.to_dense().numpy(), dense)
        r = paddle.sparse.relu(paddle.sparse.to_sparse_coo(paddle.to_tensor(-dense)))
        np.testing.assert_allclose(r.to_dense().numpy(), np.maximum(-dense, 0))

    def test_coo_construction_and_csr(self):
        idx = np.array([[0, 1, 1], [2, 0, 2]])
        vals = np.array([4.0, 5.0, 6.0], np.float32)
        sp = paddle.sparse.sparse_coo_tensor(idx, vals, shape=[2, 3])
        dense = np.zeros((2, 3), np.float32)
        dense[idx[0], idx[1]] = vals
        np.testing.assert_allclose(sp.to_dense().numpy(), dense)
        csr = sp.to_sparse_csr()
        np.testing.assert_allclose(csr.to_dense().numpy(), dense)
        back = csr.to_sparse_coo()
        np.testing.assert_allclose(back.to_dense().numpy(), dense)

    def test_sparse_matmul_and_add(self):
        a = np.array([[0, 2.0], [3.0, 0]], np.float32)
        d = np.random.RandomState(0).rand(2, 4).astype(np.float32)
        sp = paddle.sparse.to_sparse_coo(paddle.to_tensor(a))
        out = paddle.sparse.matmul(sp, paddle.to_tensor(d))
        np.testing.assert_allclose(out.numpy(), a @ d, rtol=1e-5)
        s2 = paddle.sparse.add(sp, sp)
        np.testing.assert_allclose(s2.to_dense().numpy(), 2 * a)

    def test_sparse_softmax(self):
        a = np.array([[1.0, 0, 2.0], [0, 3.0, 0]], np.float32)
        sp = paddle.sparse.to_sparse_coo(paddle.to_tensor(a))
        sm = paddle.sparse.softmax(sp).to_dense().numpy()
        # row 0: softmax over {1, 2} at their positions
        e = np.exp(np.array([1.0, 2.0]) - 2.0)
        np.testing.assert_allclose(sm[0, [0, 2]], e / e.sum(), rtol=1e-5)
        np.testing.assert_allclose(sm[1, 1], 1.0, rtol=1e-6)

    def test_selected_rows_merge(self):
        sr = paddle.sparse.SelectedRows(
            rows=np.array([1, 3, 1]), value=np.ones((3, 4), np.float32), height=5
        )
        merged = sr.merge()
        dense = merged.to_dense().numpy()
        np.testing.assert_allclose(dense[1], 2 * np.ones(4))
        np.testing.assert_allclose(dense[3], np.ones(4))
        assert dense[0].sum() == 0


class TestQuantization:
    def test_fake_quant_ste_grad(self):
        x = paddle.to_tensor(np.array([0.5, -0.25, 0.9], np.float32), stop_gradient=False)
        y = paddle.quantization.fake_quantize_dequantize_abs_max(x)
        # quantized values lie on the int8 grid scaled by max|x|
        scale = 0.9
        np.testing.assert_allclose(
            y.numpy(), np.round(x.numpy() / scale * 127) * scale / 127, rtol=1e-5
        )
        y.sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), np.ones(3), rtol=1e-6)  # STE

    def test_qat_wraps_and_trains(self):
        paddle.seed(0)
        model = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
        paddle.quantization.ImperativeQuantAware().quantize(model)
        from paddle_tpu.quantization import QuantedLayer

        assert isinstance(model[0], QuantedLayer)
        opt = paddle.optimizer.Adam(learning_rate=0.01, parameters=model.parameters())
        rng = np.random.RandomState(0)
        x = paddle.to_tensor(rng.rand(16, 8).astype(np.float32))
        y = paddle.to_tensor(rng.rand(16, 4).astype(np.float32))
        losses = []
        for _ in range(12):
            loss = ((model(x) - y) ** 2).mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss.item()))
        assert losses[-1] < losses[0] * 0.8, losses

    def test_ptq_calibrates_and_quantizes(self):
        paddle.seed(1)
        model = nn.Sequential(nn.Linear(4, 8), nn.Tanh(), nn.Linear(8, 2))
        rng = np.random.RandomState(1)
        data = [(paddle.to_tensor(rng.rand(4, 4).astype(np.float32)),) for _ in range(4)]
        w_before = model[0].weight.numpy().copy()
        ptq = paddle.quantization.PostTrainingQuantization(model, data_loader=data, batch_nums=4)
        ptq.quantize()
        assert ptq.act_scales and ptq.weight_scales
        w_after = model[0].weight.numpy()
        # weights now on the int8 grid: 255 distinct levels max
        assert len(np.unique(w_after)) <= 255
        assert np.abs(w_after - w_before).max() < np.abs(w_before).max() / 32


class TestCustomOp:
    def test_register_and_grad(self):
        import jax.numpy as jnp
        from paddle_tpu.incubate import register_custom_op

        op = register_custom_op("my_softsign", lambda x: x / (1 + jnp.abs(x)))
        x = paddle.to_tensor(np.array([1.0, -2.0], np.float32), stop_gradient=False)
        y = paddle.my_softsign(x)
        np.testing.assert_allclose(y.numpy(), x.numpy() / (1 + np.abs(x.numpy())), rtol=1e-6)
        y.sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), 1 / (1 + np.abs(x.numpy())) ** 2, rtol=1e-5)

    def test_custom_vjp(self):
        import jax.numpy as jnp
        from paddle_tpu.incubate import register_custom_op

        def f(x):
            return jnp.square(x)

        def fwd(x):
            return jnp.square(x), x

        def bwd(res, g):
            return (g * 100.0,)  # deliberately wrong grad proves the vjp is used

        register_custom_op("sq_weird", f, vjp=(fwd, bwd))
        x = paddle.to_tensor(np.array([3.0], np.float32), stop_gradient=False)
        paddle.sq_weird(x).sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), [100.0])


class TestDLPack:
    def test_torch_roundtrip(self):
        import torch

        x = paddle.to_tensor(np.arange(6, dtype=np.float32).reshape(2, 3))
        cap = paddle.utils.dlpack.to_dlpack(x)
        t = torch.from_dlpack(cap)
        assert t.shape == (2, 3)
        back = paddle.utils.dlpack.from_dlpack(torch.arange(4).float())
        np.testing.assert_allclose(back.numpy(), [0, 1, 2, 3])


class TestASP:
    def test_prune_and_guarantee(self):
        from paddle_tpu.incubate import asp

        paddle.seed(3)
        model = nn.Sequential(nn.Linear(16, 8))
        asp.prune_model(model, n=2, m=4)
        w = model[0].weight.numpy()
        assert asp.check_mask_nm(w, 2, 4)
        assert (w == 0).mean() >= 0.5 - 1e-6
        opt = asp.decorate(paddle.optimizer.SGD(learning_rate=0.1, parameters=model.parameters()))
        x = paddle.to_tensor(np.random.rand(4, 16).astype(np.float32))
        y = paddle.to_tensor(np.random.rand(4, 8).astype(np.float32))
        loss = ((model(x) - y) ** 2).mean()
        loss.backward()
        opt.step()
        # sparsity survives the update
        assert asp.check_mask_nm(model[0].weight.numpy(), 2, 4)


class TestShardedCheckpoint:
    def test_sharded_save_restore(self, tmp_path):
        import jax
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
        from paddle_tpu.distributed.checkpoint import load_state_dict, save_state_dict

        mesh = Mesh(np.asarray(jax.devices()).reshape(8), ("x",))
        arr = np.arange(64, dtype=np.float32).reshape(8, 8)
        t = paddle.to_tensor(arr)
        import jax.numpy as jnp

        t._set_data(jax.device_put(t._data, NamedSharding(mesh, P("x", None))))
        state = {"w": t, "nested": {"b": paddle.to_tensor(np.ones(3, np.float32))}}
        save_state_dict(state, str(tmp_path / "ck"))
        # wipe and restore: sharding must be re-applied
        t._set_data(jax.device_put(jnp.zeros((8, 8), jnp.float32), NamedSharding(mesh, P("x", None))))
        load_state_dict(state, str(tmp_path / "ck"))
        np.testing.assert_allclose(np.asarray(t._data), arr)
        assert t._data.sharding.spec == P("x", None)

    def test_auto_checkpoint_resume(self, tmp_path):
        from paddle_tpu.distributed.checkpoint import AutoCheckpoint

        w = paddle.to_tensor(np.zeros(4, np.float32))
        ac = AutoCheckpoint(str(tmp_path / "auto"), interval_steps=2, keep_last=2)
        for step in range(6):
            w._set_data(w._data + 1)
            ac.maybe_save(step, {"w": w})
        ac.wait()
        # fresh state resumes from the last saved step (4: steps 0,2,4 saved)
        w2 = paddle.to_tensor(np.zeros(4, np.float32))
        step = ac.resume({"w": w2})
        assert step == 4
        np.testing.assert_allclose(w2.numpy(), np.full(4, 5.0))  # after step 4's update


class TestAutoParallel:
    def test_completion_assigns_megatron_specs(self):
        import jax
        from jax.sharding import Mesh, PartitionSpec as P
        from paddle_tpu.distributed.auto_parallel import complete_annotations

        mesh = Mesh(np.asarray(jax.devices()).reshape(2, 4), ("dp", "mp"))
        paddle.seed(0)
        model = nn.Sequential(nn.Linear(16, 64), nn.ReLU(), nn.Linear(64, 16))
        complete_annotations(model, mesh)
        specs = [p.pspec for p in model.parameters() if p.ndim == 2]
        assert specs[0] is not None and specs[1] is not None
        assert specs[0] != specs[1]  # column then row (Megatron alternation)

    def test_engine_fit_and_cost(self):
        import jax
        from jax.sharding import Mesh
        from paddle_tpu.distributed.auto_parallel import Engine, estimate_cost

        mesh = Mesh(np.asarray(jax.devices()).reshape(2, 4), ("dp", "mp"))
        paddle.seed(1)
        model = nn.Sequential(nn.Linear(16, 64), nn.Tanh(), nn.Linear(64, 16))
        opt = paddle.optimizer.Adam(learning_rate=0.01, parameters=model.parameters())
        mse = lambda pred, y: ((pred - y) ** 2).mean()
        eng = Engine(model, loss=mse, optimizer=opt, mesh=mesh).prepare()
        rng = np.random.RandomState(0)
        data = [
            (paddle.to_tensor(rng.rand(8, 16).astype(np.float32)),
             paddle.to_tensor(rng.rand(8, 16).astype(np.float32)))
            for _ in range(6)
        ]
        hist = eng.fit(data, epochs=2)
        assert hist[-1] < hist[0]

        import jax.numpy as jnp

        cost = estimate_cost(lambda a, b: a @ b, jnp.ones((64, 64)), jnp.ones((64, 64)))
        assert cost["flops"] >= 2 * 64 * 64 * 64 * 0.9


class TestDistributions:
    """Beta/Dirichlet/Multinomial + registered KL — parity vs torch.distributions."""

    def test_beta(self):
        import torch
        from paddle_tpu.distribution import Beta

        pb, tb = Beta(2.5, 1.5), torch.distributions.Beta(2.5, 1.5)
        np.testing.assert_allclose(
            float(pb.log_prob(paddle.to_tensor(0.3)).numpy()),
            float(tb.log_prob(torch.tensor(0.3))), rtol=1e-5,
        )
        np.testing.assert_allclose(float(pb.entropy().numpy()), float(tb.entropy()), rtol=1e-5)
        s = pb.sample([200])
        assert 0 < float(s.numpy().mean()) < 1

    def test_dirichlet_and_multinomial(self):
        import torch
        from paddle_tpu.distribution import Dirichlet, Multinomial

        c = np.array([1.5, 2.0, 3.0], np.float32)
        pd, td = Dirichlet(c), torch.distributions.Dirichlet(torch.tensor(c))
        v = np.array([0.2, 0.3, 0.5], np.float32)
        np.testing.assert_allclose(
            float(pd.log_prob(paddle.to_tensor(v)).numpy()),
            float(td.log_prob(torch.tensor(v))), rtol=1e-5,
        )
        pm = Multinomial(10, np.array([0.2, 0.3, 0.5], np.float32))
        assert (pm.sample([4]).numpy().sum(-1) == 10).all()

    def test_registered_kl(self):
        import torch
        from paddle_tpu.distribution import Beta, kl_divergence

        p, q = Beta(2.5, 1.5), Beta(1.2, 2.2)
        tp, tq = torch.distributions.Beta(2.5, 1.5), torch.distributions.Beta(1.2, 2.2)
        np.testing.assert_allclose(
            float(kl_divergence(p, q).numpy()),
            float(torch.distributions.kl_divergence(tp, tq)), rtol=1e-5,
        )


class TestCallbacks:
    def test_reduce_lr_on_plateau_and_visualdl(self, tmp_path):
        import json

        from paddle_tpu.hapi.callbacks import ReduceLROnPlateau, VisualDL

        paddle.seed(0)
        net = nn.Sequential(nn.Linear(4, 8), nn.Tanh(), nn.Linear(8, 2))
        model = paddle.Model(net)
        opt = paddle.optimizer.Adam(learning_rate=0.05, parameters=net.parameters())
        model.prepare(opt, nn.MSELoss())
        rng = np.random.RandomState(0)
        X, Y = rng.rand(32, 4).astype(np.float32), rng.rand(32, 2).astype(np.float32)

        class DS(paddle.io.Dataset):
            def __getitem__(self, i):
                return X[i], Y[i]

            def __len__(self):
                return 32

        rl = ReduceLROnPlateau(monitor="loss", factor=0.5, patience=1,
                               min_delta=10.0, verbose=0)  # forced plateau
        model.fit(DS(), epochs=3, batch_size=16, verbose=0,
                  callbacks=[rl, VisualDL(str(tmp_path))])
        assert float(opt.get_lr()) < 0.05
        recs = [json.loads(l) for l in open(tmp_path / "scalars.jsonl")]
        assert any(r["tag"] == "train_epoch" for r in recs)
