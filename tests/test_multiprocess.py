"""Multi-process bootstrap + elastic tests.

Reference methodology: ``test_dist_base.py:783`` — spawn real worker
processes on one host, rendezvous, run a collective, compare. Here: 2
processes, CPU backend (Gloo collectives), our init_parallel_env →
jax.distributed.initialize path, TCPStore rendezvous, and the elastic
heartbeat manager.
"""
import json
import os
import subprocess
import sys
import textwrap
import time

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = textwrap.dedent(
    """
    import os, sys, json
    os.environ["JAX_PLATFORMS"] = "cpu"
    rank = int(os.environ["PADDLE_TRAINER_ID"])
    import paddle_tpu as paddle
    from paddle_tpu.distributed import parallel_env

    env = parallel_env.init_parallel_env()
    assert env.rank == rank, (env.rank, rank)
    assert env.world_size == 2, env.world_size

    # TCPStore rendezvous: exchange values through the native KV store
    from paddle_tpu.distributed import TCPStore
    store = TCPStore(port=int(os.environ["STORE_PORT"]), is_master=(rank == 0))
    store.set(f"hello/{rank}", str(rank * 10))
    n = store.add("barrier", 1)
    while store.add("barrier", 0) < 2:
        pass
    other = store.get(f"hello/{1 - rank}")
    assert other == str((1 - rank) * 10).encode(), other

    # cross-process collective through the XLA CPU (Gloo) backend
    import jax, jax.numpy as jnp
    out = jax.pmap(lambda x: jax.lax.psum(x, "i"), axis_name="i")(
        jnp.ones((jax.local_device_count(),)) * (rank + 1)
    )
    assert float(out[0]) == 3.0, out
    print(json.dumps({"rank": rank, "psum": float(out[0])}), flush=True)
    """
)


def _spawn(rank, port, store_port, extra_env=None):
    env = {k: v for k, v in os.environ.items() if k not in ("PYTHONPATH", "XLA_FLAGS")}
    env.update(
        {
            "PYTHONPATH": REPO,
            "JAX_PLATFORMS": "cpu",
            "PADDLE_TRAINER_ID": str(rank),
            "PADDLE_TRAINERS_NUM": "2",
            "PADDLE_TPU_COORDINATOR": f"127.0.0.1:{port}",
            "STORE_PORT": str(store_port),
        }
    )
    env.update(extra_env or {})
    return subprocess.Popen(
        [sys.executable, "-c", WORKER], env=env,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
    )


# This jaxlib's CPU client rejects cross-process collectives outright
# ("INVALID_ARGUMENT: Multiprocess computations aren't implemented on the
# CPU backend") — the rendezvous works, the psum doesn't. An environment
# limit of the CPU test tier, not a distributed-runtime regression: the
# tests stay as non-strict xfails so a jaxlib that CAN run them shows up
# as XPASS instead of being silently skipped.
_CPU_MULTIPROC_XFAIL = pytest.mark.xfail(
    os.environ.get("JAX_PLATFORMS", "cpu") == "cpu",
    reason="environment limit: jaxlib CPU backend does not implement "
    "multiprocess computations (XlaRuntimeError INVALID_ARGUMENT in the "
    "worker's collective)",
    strict=False,
)


class TestMultiProcessBootstrap:
    @_CPU_MULTIPROC_XFAIL
    def test_two_process_rendezvous_and_collective(self):
        port, store_port = 9931, 9932
        p0 = _spawn(0, port, store_port)
        p1 = _spawn(1, port, store_port)
        out0, _ = p0.communicate(timeout=180)
        out1, _ = p1.communicate(timeout=180)
        assert p0.returncode == 0, out0.decode()[-2000:]
        assert p1.returncode == 0, out1.decode()[-2000:]
        r0 = json.loads(out0.decode().strip().splitlines()[-1])
        assert r0["psum"] == 3.0


class TestElastic:
    def _store(self, port):
        from paddle_tpu.distributed import TCPStore

        return TCPStore(port=port, is_master=True)

    def test_heartbeat_scale_down_detection(self):
        from paddle_tpu.distributed.fleet.elastic import ElasticManager, ElasticStatus

        store = self._store(9941)
        w0 = ElasticManager(store, 2, worker_id="w0", heartbeat_interval=0.2, timeout=1.0).register()
        w1 = ElasticManager(store, 2, worker_id="w1", heartbeat_interval=0.2, timeout=1.0).register()
        watcher = ElasticManager(store, 2, heartbeat_interval=0.2, timeout=1.0)
        ids = ["w0", "w1"]
        assert watcher.watch(ids) == ElasticStatus.HOLD
        assert sorted(watcher.alive_workers(ids)) == ids
        # w1 dies: heartbeats stop, watcher must flag the fault
        w1.deregister()
        deadline = time.time() + 5
        status = None
        while time.time() < deadline:
            status = watcher.watch(ids)
            if status in (ElasticStatus.ERROR, ElasticStatus.RESTART):
                break
            time.sleep(0.2)
        assert status == ElasticStatus.ERROR  # below min_np floor
        w0.deregister()
        store.close()

    def test_scale_tolerant_hold_with_min_np(self):
        from paddle_tpu.distributed.fleet.elastic import ElasticManager, ElasticStatus

        store = self._store(9942)
        w0 = ElasticManager(store, 2, worker_id="a", heartbeat_interval=0.2, timeout=1.0).register()
        w1 = ElasticManager(store, 2, worker_id="b", heartbeat_interval=0.2, timeout=1.0).register()
        watcher = ElasticManager(store, 2, min_np=1, heartbeat_interval=0.2, timeout=1.0)
        ids = ["a", "b"]
        assert watcher.watch(ids) == ElasticStatus.HOLD
        w1.deregister()
        deadline = time.time() + 5
        status = None
        while time.time() < deadline:
            status = watcher.watch(ids)
            if status == ElasticStatus.RESTART:
                break
            time.sleep(0.2)
        # min_np=1 permits running with 1 worker -> membership-change RESTART
        assert status == ElasticStatus.RESTART
        assert watcher.world() == ["a"]
        w0.deregister()
        store.close()

    def test_elastic_launcher_restarts_crashed_worker(self, tmp_path):
        from paddle_tpu.distributed.fleet.elastic import ElasticLauncher, ElasticManager

        store = self._store(9943)
        watcher = ElasticManager(store, 1, heartbeat_interval=0.2, timeout=3.0)
        marker = tmp_path / "attempt"

        def spawn(ids):
            # crash on first attempt, succeed on second (reference: fault -> relaunch)
            code = (
                "import os, sys\n"
                f"m = {str(marker)!r}\n"
                "first = not os.path.exists(m)\n"
                "open(m, 'a').write('x')\n"
                "sys.exit(1 if first else 0)\n"
            )
            env = {k: v for k, v in os.environ.items() if k != "PYTHONPATH"}
            env["PYTHONPATH"] = REPO
            return {
                "w0": subprocess.Popen([sys.executable, "-c", code], env=env)
            }

        launcher = ElasticLauncher(spawn, watcher, watch_interval=0.3, max_restarts=2)
        rc = launcher.run(["w0"])
        assert rc == 0
        assert marker.read_text() == "xx"  # exactly one restart
        store.close()


def _spawn_worker(out_dir):
    # runs in a fresh spawn()ed process: one CPU device per rank so the
    # cross-process psum result is just sum(rank+1)
    import os

    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    from paddle_tpu.distributed import parallel_env

    env = parallel_env.init_parallel_env()
    import jax
    import jax.numpy as jnp

    out = jax.pmap(lambda x: jax.lax.psum(x, "i"), axis_name="i")(
        jnp.ones((jax.local_device_count(),)) * (env.rank + 1)
    )
    expected = sum(r + 1 for r in range(env.world_size))
    assert float(out[0]) == expected, (float(out[0]), expected)
    with open(os.path.join(out_dir, f"rank_{env.rank}"), "w") as f:
        f.write(f"{env.world_size}")


class TestSpawn:
    @_CPU_MULTIPROC_XFAIL
    def test_spawn_two_process_collective(self, tmp_path):
        from paddle_tpu.distributed.spawn import spawn

        spawn(_spawn_worker, args=(str(tmp_path),), nprocs=2)
        assert (tmp_path / "rank_0").read_text() == "2"
        assert (tmp_path / "rank_1").read_text() == "2"

    def test_spawn_inline_single(self, tmp_path):
        from paddle_tpu.distributed.spawn import spawn

        marker = []
        spawn(lambda: marker.append(1), nprocs=1)
        assert marker == [1]

    def test_spawn_propagates_worker_failure(self):
        from paddle_tpu.distributed.spawn import spawn

        with pytest.raises(RuntimeError, match="rank"):
            spawn(_failing_worker, nprocs=2)


def _failing_worker():
    import sys

    sys.exit(3)


def _bind_flaky_worker(marker):
    # first attempt: simulate the coordinator losing the probed port to
    # another process (the _free_port TOCTOU); later attempts succeed
    import os

    first = not os.path.exists(marker)
    with open(marker, "a") as f:
        f.write("x")
    if first:
        raise RuntimeError("Failed to bind: Address already in use (port 9999)")


class TestSpawnPortRetry:
    def test_spawn_retries_on_coordinator_bind_failure(self, tmp_path):
        """ADVICE r5 (_free_port TOCTOU): a worker dying on a bind error
        exits with the dedicated retry code and spawn relaunches the whole
        world on a fresh probe port instead of failing the job."""
        from paddle_tpu.distributed.spawn import spawn

        marker = str(tmp_path / "attempt")
        spawn(_bind_flaky_worker, args=(marker,), nprocs=2)
        # attempt 1 wrote >=1 'x' then died on the bind error; attempt 2's
        # two ranks both ran clean
        assert len((tmp_path / "attempt").read_text()) >= 3

    def test_non_bind_failure_does_not_retry(self, tmp_path):
        from paddle_tpu.distributed.spawn import spawn

        t0 = time.time()
        with pytest.raises(RuntimeError, match="code 3"):
            spawn(_failing_worker, nprocs=2)
        # a single launch, not bind_retries relaunches
        assert time.time() - t0 < 120


class TestLauncher:
    def test_cluster_topology(self):
        from paddle_tpu.distributed.launch_mod import get_cluster

        c = get_cluster(["10.0.0.1", "10.0.0.2"], 2, 9000)
        assert c.world_size == 4
        assert c.pods[1].trainers[0].rank == 2
        assert c.trainer_endpoints()[0] == "10.0.0.1:9001"
        assert c.pod_by_addr("10.0.0.2").node_rank == 1

    def test_launch_two_workers_on_node(self, tmp_path):
        from paddle_tpu.distributed.launch_mod import launch

        script = tmp_path / "w.py"
        script.write_text(
            "import os, pathlib\n"
            "d = pathlib.Path(os.environ['OUT_DIR'])\n"
            "(d / ('rank_' + os.environ['PADDLE_TRAINER_ID'])).write_text(\n"
            "    os.environ['PADDLE_TRAINER_ENDPOINTS'])\n"
        )
        os.environ["OUT_DIR"] = str(tmp_path)
        try:
            rc = launch(str(script), nproc_per_node=2, coordinator_port=9960,
                        log_dir=str(tmp_path / "logs"))
        finally:
            os.environ.pop("OUT_DIR", None)
        assert rc == 0
        eps = (tmp_path / "rank_0").read_text().split(",")
        assert len(eps) == 2
        assert (tmp_path / "rank_1").exists()
        assert (tmp_path / "logs" / "worker.0.log").exists()
