"""Flash attention Pallas kernel — parity vs exact attention.

The reference's fused attention kernels are validated numerically against an
unfused formulation (test style: unittests/op_test.py check_output/check_grad);
here the Pallas forward AND both Pallas backward kernels (dq, dkv) run in
interpret mode on CPU and must match the XLA exact path for values and all
three input gradients, causal and non-causal, fp32 and bf16.
"""
import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.ops.pallas.flash_attention import flash_attention_array


def exact_attention(q, k, v, causal):
    qh, kh, vh = [jnp.swapaxes(x, 1, 2) for x in (q, k, v)]
    s = jnp.einsum("bhqd,bhkd->bhqk", qh.astype(jnp.float32), kh.astype(jnp.float32))
    s = s / math.sqrt(q.shape[-1])
    if causal:
        tq, tk = s.shape[-2], s.shape[-1]
        s = jnp.where(jnp.tril(jnp.ones((tq, tk), bool)), s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhqk,bhkd->bhqd", p, vh.astype(jnp.float32))
    return jnp.swapaxes(out, 1, 2).astype(q.dtype)


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("shape", [(2, 256, 4, 64), (1, 384, 2, 32)])
def test_forward_parity(causal, shape):
    rng = np.random.RandomState(0)
    q, k, v = [jnp.asarray(rng.randn(*shape).astype(np.float32)) for _ in range(3)]
    if causal is False and shape[1] % 128 != 0:
        pytest.skip("non-causal requires block-aligned T")
    got = flash_attention_array(q, k, v, causal=causal, block_q=128, block_k=128, interpret=True)
    want = exact_attention(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [True, False])
def test_grad_parity(causal):
    rng = np.random.RandomState(1)
    shape = (2, 256, 4, 64)
    q, k, v = [jnp.asarray(rng.randn(*shape).astype(np.float32)) for _ in range(3)]
    co = jnp.asarray(rng.randn(*shape).astype(np.float32))

    def loss_flash(q, k, v):
        return (flash_attention_array(q, k, v, causal=causal, block_q=128, block_k=128, interpret=True) * co).sum()

    def loss_exact(q, k, v):
        return (exact_attention(q, k, v, causal) * co).sum()

    g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_exact, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-4, rtol=1e-3)


def test_grad_parity_bf16():
    rng = np.random.RandomState(2)
    shape = (1, 256, 2, 64)
    q, k, v = [jnp.asarray(rng.randn(*shape), jnp.bfloat16) for _ in range(3)]
    co = jnp.asarray(rng.randn(*shape), jnp.bfloat16)

    def loss_flash(q, k, v):
        return (flash_attention_array(q, k, v, causal=True, block_q=128, block_k=128, interpret=True) * co).sum().astype(jnp.float32)

    def loss_exact(q, k, v):
        return (exact_attention(q, k, v, True) * co).sum().astype(jnp.float32)

    g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_exact, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b, np.float32), atol=0.15, rtol=0.1
        )


def test_unpadded_causal_tail():
    # T not a multiple of the block: causal path pads queries and keys.
    rng = np.random.RandomState(3)
    shape = (1, 200, 2, 32)
    q, k, v = [jnp.asarray(rng.randn(*shape).astype(np.float32)) for _ in range(3)]
    got = flash_attention_array(q, k, v, causal=True, block_q=128, block_k=128, interpret=True)
    want = exact_attention(q, k, v, True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5)


def test_functional_exact_path():
    # Short sequence on CPU: the gate must route to the XLA exact path.
    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F

    rng = np.random.RandomState(4)
    x = [paddle.to_tensor(rng.randn(2, 128, 2, 32).astype(np.float32)) for _ in range(3)]
    out = F.scaled_dot_product_attention(*x, is_causal=True)
    want = exact_attention(x[0]._data, x[1]._data, x[2]._data, True)
    np.testing.assert_allclose(np.asarray(out._data), np.asarray(want), atol=1e-4, rtol=1e-4)


def test_functional_flash_routing(monkeypatch):
    # Force the gate open so the Tensor-level Pallas route
    # (scaled_dot_product_attention → flash_attention_tpu → eager_call,
    # interpret mode on CPU) actually runs and matches the exact path.
    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F
    from paddle_tpu.nn.functional import attention as attention_mod

    monkeypatch.setattr(attention_mod, "_flash_eligible", lambda *a: True)
    # assert the Pallas route actually ran — the silent except/fallback in
    # scaled_dot_product_attention would otherwise make this test vacuous
    from paddle_tpu.ops.pallas import flash_attention as fa_mod

    calls = []
    real = fa_mod.flash_attention_tpu

    def recording(*a, **kw):
        calls.append(1)
        return real(*a, **kw)

    monkeypatch.setattr(fa_mod, "flash_attention_tpu", recording)
    rng = np.random.RandomState(5)
    x = [paddle.to_tensor(rng.randn(1, 512, 2, 32).astype(np.float32)) for _ in range(3)]
    out = F.scaled_dot_product_attention(*x, is_causal=True)
    assert calls, "flash route did not run (silently fell back to exact path)"
    want = exact_attention(x[0]._data, x[1]._data, x[2]._data, True)
    np.testing.assert_allclose(np.asarray(out._data), np.asarray(want), atol=1e-4, rtol=1e-4)

    # grads flow through the custom_vjp route at the Tensor level
    for t in x:
        t.stop_gradient = False
    out = F.scaled_dot_product_attention(*x, is_causal=True)
    out.sum().backward()
    g_flash = [np.asarray(t.grad._data) for t in x]

    x2 = [paddle.to_tensor(np.asarray(t._data)) for t in x]
    for t in x2:
        t.stop_gradient = False
    monkeypatch.setattr(attention_mod, "_flash_eligible", lambda *a: False)
    out2 = F.scaled_dot_product_attention(*x2, is_causal=True)
    out2.sum().backward()
    for a, b in zip(g_flash, [np.asarray(t.grad._data) for t in x2]):
        np.testing.assert_allclose(a, b, atol=5e-4, rtol=1e-3)


class TestStreamedPath:
    def test_streamed_kernels_match_resident(self, monkeypatch):
        """Force the streamed-grid kernels (the 32k+ path) at a small T and
        check fwd/bwd parity against the resident path."""
        import jax
        import jax.numpy as jnp
        import numpy as np
        from paddle_tpu.ops.pallas import flash_attention as fa

        rng = np.random.RandomState(0)
        B, T, H, D = 1, 256, 2, 32
        q = jnp.asarray(rng.randn(B, T, H, D), jnp.float32)
        k = jnp.asarray(rng.randn(B, T, H, D), jnp.float32)
        v = jnp.asarray(rng.randn(B, T, H, D), jnp.float32)

        def loss(q, k, v):
            return (fa.flash_attention_array(q, k, v, causal=True) ** 2).sum()

        ref_val, ref_grads = jax.value_and_grad(loss, argnums=(0, 1, 2))(q, k, v)
        monkeypatch.setattr(fa, "_RESIDENT_BYTES", 0)  # everything streams
        got_val, got_grads = jax.value_and_grad(loss, argnums=(0, 1, 2))(q, k, v)
        np.testing.assert_allclose(float(got_val), float(ref_val), rtol=1e-5)
        for g_ref, g_got in zip(ref_grads, got_grads):
            np.testing.assert_allclose(
                np.asarray(g_got), np.asarray(g_ref), rtol=1e-4, atol=1e-4
            )
