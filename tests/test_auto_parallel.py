"""Auto-parallel: candidate-plan derivation, cost-model selection, reshard,
and parity of the AUTO placement with the hand-written Megatron placement
(reference auto_parallel completion.py:111 + partitioner.py + reshard.py +
cost_model, collapsed GSPMD-first: plans pin parameters, XLA partitions ops
and inserts collectives; selection scores the real compiled step)."""
import numpy as np
import jax
from jax.sharding import Mesh, PartitionSpec as P

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.distributed.auto_parallel import (
    Engine,
    ShardingPlan,
    analyze_collectives,
    complete_annotations,
    derive_candidate_plans,
    plan_cost,
    reshard,
    select_plan,
)
from paddle_tpu.models.gpt import GPTConfig, GPTForPretraining


def _mesh(axes, shape):
    devs = np.array(jax.devices()[: int(np.prod(shape))]).reshape(shape)
    return Mesh(devs, axes)


def _tiny_cfg(use_mp):
    return GPTConfig(
        vocab_size=512, hidden_size=64, num_layers=2, num_heads=4,
        max_position_embeddings=32, hidden_dropout=0.0, attention_dropout=0.0,
        use_mp_layers=use_mp,
    )


def _collective_counts(hlo_text):
    return analyze_collectives(hlo_text)["counts"]


def _strip_pspecs(model):
    """The GPT model builds mp_layers with intrinsic pspecs; clearing them
    yields the unannotated model auto-parallel must handle."""
    for _, p in model.named_parameters():
        p.pspec = None
    return model


class TestCompletion:
    def test_megatron_plan_pairs_col_row_per_parent(self):
        mesh = _mesh(("mp",), (8,))
        model = GPTForPretraining(_tiny_cfg(use_mp=False))
        _strip_pspecs(model)  # model must be genuinely unannotated
        complete_annotations(model, mesh)
        specs = {n: getattr(p, "pspec", None) for n, p in model.named_parameters()}
        # qkv/up are column (out-dim over mp, bias sharded); proj/down are row
        for n, s in specs.items():
            if ".qkv.weight" in n or ".up.weight" in n:
                assert s == P(None, "mp"), (n, s)
            if ".qkv.bias" in n or ".up.bias" in n:
                assert s == P("mp"), (n, s)
            if ".proj.weight" in n or ".down.weight" in n:
                assert s == P("mp", None), (n, s)
            if "word_embeddings" in n and n.endswith("weight"):
                assert s == P("mp", None), (n, s)
            if ".proj.bias" in n or ".down.bias" in n or "ln" in n:
                assert s is None, (n, s)

    def test_interleaved_params_cannot_desync_pairing(self):
        # the round-3 heuristic alternated a GLOBAL flip counter; a sibling
        # module with an odd number of 2-D weights desynchronized everything
        # after it. The structure-aware pass pairs per parent.
        class Odd(nn.Layer):
            def __init__(self):
                super().__init__()
                self.solo = nn.Linear(32, 32)  # odd single weight

        class Block(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc1 = nn.Linear(32, 128)
                self.fc2 = nn.Linear(128, 32)

        class Net(nn.Layer):
            def __init__(self):
                super().__init__()
                self.odd = Odd()
                self.block = Block()

        mesh = _mesh(("mp",), (8,))
        model = Net()
        complete_annotations(model, mesh)
        named = dict(model.named_parameters())
        assert named["block.fc1.weight"].pspec == P(None, "mp")
        assert named["block.fc2.weight"].pspec == P("mp", None)

    def test_user_annotation_wins(self):
        mesh = _mesh(("mp",), (8,))
        model = GPTForPretraining(_tiny_cfg(use_mp=False))
        named = dict(model.named_parameters())
        some = next(n for n in named if n.endswith("qkv.weight"))
        named[some].pspec = P()  # user says: replicate this one
        complete_annotations(model, mesh)
        assert named[some].pspec == P()


class TestAutoVsHandMegatron:
    def _loss(self, model, ids, labels):
        return model.loss(ids, labels)

    def _lower(self, model, mesh):
        from paddle_tpu.distributed.engine import HybridParallelEngine

        opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=model.parameters())
        eng = HybridParallelEngine(model, opt, self._loss, mesh=mesh, dp_axes=())
        rng = np.random.RandomState(0)
        ids = paddle.to_tensor(rng.randint(0, 512, (2, 32)))
        labels = paddle.to_tensor(rng.randint(0, 512, (2, 32)))
        args = eng._prepare(ids, labels)
        return eng._jit.lower(*args).compile().as_text()

    def test_auto_placement_matches_hand_megatron_collectives(self):
        mesh = _mesh(("mp",), (8,))

        # hand: the intrinsic mp_layers pspecs (Megatron placement); auto:
        # identical python model with ALL pspecs stripped, re-derived by
        # completion. Same forward path → the comparison isolates placement.
        paddle.seed(0)
        hand = GPTForPretraining(_tiny_cfg(use_mp=True))
        hand_specs = {
            n: getattr(p, "pspec", None) for n, p in hand.named_parameters()
        }
        hand_counts = _collective_counts(self._lower(hand, mesh))

        paddle.seed(0)
        auto = GPTForPretraining(_tiny_cfg(use_mp=True))
        _strip_pspecs(auto)
        complete_annotations(auto, mesh)
        auto_specs = {
            n: getattr(p, "pspec", None) for n, p in auto.named_parameters()
        }
        auto_counts = _collective_counts(self._lower(auto, mesh))

        # completion re-derives the hand placement param-for-param (P() and
        # None both mean replicated)
        def norm(s):
            return None if s is None or s == P() else s

        for n in hand_specs:
            assert norm(auto_specs[n]) == norm(hand_specs[n]), (
                n, auto_specs[n], hand_specs[n],
            )
        # … and therefore GSPMD emits the same collectives
        assert auto_counts == hand_counts, (auto_counts, hand_counts)


class TestPlanSelection:
    def test_select_plan_prefers_sharded_compute(self):
        mesh = _mesh(("mp",), (8,))
        paddle.seed(1)
        model = GPTForPretraining(_tiny_cfg(use_mp=False))
        _strip_pspecs(model)
        opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=model.parameters())
        eng = Engine(model, loss=None, optimizer=opt, mesh=mesh)
        eng.loss = None

        rng = np.random.RandomState(0)
        ids = paddle.to_tensor(rng.randint(0, 512, (2, 32)))
        labels = paddle.to_tensor(rng.randint(0, 512, (2, 32)))

        def loss(model, ids, labels):
            return model.loss(ids, labels)

        # drive selection through the public engine path
        eng.loss = None
        eng_loss = loss
        from paddle_tpu.distributed.engine import HybridParallelEngine
        from paddle_tpu.distributed.auto_parallel import derive_candidate_plans

        plans = derive_candidate_plans(model, mesh)
        assert [p.name for p in plans][:2] == ["megatron", "replicated"]

        def build_compiled():
            e = HybridParallelEngine(model, opt, eng_loss, mesh=mesh, dp_axes=(), donate=False)
            args = e._prepare(ids, labels)
            return e._jit.lower(*args).compile()

        best = select_plan(model, plans, build_compiled)
        assert best.report["comm_counts"], "winning plan should communicate"
        # the sharded plan must beat full replication on the roofline score
        rep = next(p for p in plans if p.name == "replicated")
        if rep.score is not None:
            assert best.score <= rep.score
        # per-device flops of the winner ≲ replicated's (compute partitioned)
        if rep.report:
            assert best.report["flops"] < rep.report["flops"]

    def test_plan_cost_reports_comm_and_memory(self):
        mesh = _mesh(("mp",), (8,))
        w = np.ones((64, 64), np.float32)

        def f(x, w):
            y = x @ w
            return jax.lax.with_sharding_constraint(
                y, jax.sharding.NamedSharding(mesh, P())
            ).sum()

        xs = jax.ShapeDtypeStruct((8, 64), np.float32)
        ws = jax.ShapeDtypeStruct((64, 64), np.float32)
        with mesh:
            compiled = (
                jax.jit(f, in_shardings=(jax.sharding.NamedSharding(mesh, P("mp", None)),
                                         jax.sharding.NamedSharding(mesh, P(None, "mp"))))
                .lower(xs, ws).compile()
            )
        rep = plan_cost(compiled)
        assert rep["peak_memory_bytes"] > 0
        assert rep["time_proxy"] > 0


class TestReshard:
    def test_reshard_eager_changes_placement(self):
        mesh = _mesh(("x",), (8,))
        t = paddle.to_tensor(np.arange(64, dtype=np.float32).reshape(8, 8))
        t2 = reshard(t, P("x", None), mesh=mesh)
        shard_shapes = {s.data.shape for s in t2._data.addressable_shards}
        assert shard_shapes == {(1, 8)}
        np.testing.assert_array_equal(t2.numpy(), t.numpy())

    def test_reshard_traced_inserts_constraint(self):
        mesh = _mesh(("x",), (8,))

        def f(a):
            return reshard(a, P("x", None), mesh=mesh) * 2

        text = jax.jit(f).lower(jax.ShapeDtypeStruct((8, 8), np.float32)).as_text()
        assert "@Sharding" in text or "sdy.sharding_constraint" in text
