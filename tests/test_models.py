"""Model zoo smoke + correctness tests."""
import numpy as np
import pytest

import paddle_tpu as paddle


class TestLanguageModels:
    def test_gpt_forward_loss_grads(self):
        from paddle_tpu.models.gpt import GPTForPretraining, gpt_tiny

        paddle.seed(0)
        cfg = gpt_tiny(hidden_dropout=0.0, attention_dropout=0.0)
        m = GPTForPretraining(cfg)
        ids = paddle.to_tensor(np.random.randint(0, cfg.vocab_size, (2, 16)))
        logits = m(ids)
        assert logits.shape == [2, 16, cfg.vocab_size]
        loss = m.loss(ids, ids)
        assert np.isfinite(float(loss.item()))
        loss.backward()
        assert m.gpt.embeddings.word_embeddings.weight.grad is not None
        # causal: prefix logits must not depend on future tokens
        m.eval()
        ids_np = np.random.randint(0, cfg.vocab_size, (1, 8))
        with paddle.no_grad():
            l1 = m(paddle.to_tensor(ids_np)).numpy()[0, :4]
            ids2 = ids_np.copy()
            ids2[0, 6:] = (ids2[0, 6:] + 1) % cfg.vocab_size
            l2 = m(paddle.to_tensor(ids2)).numpy()[0, :4]
        np.testing.assert_allclose(l1, l2, atol=1e-4)

    def test_llama_forward_loss(self):
        from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny

        paddle.seed(0)
        cfg = llama_tiny()
        m = LlamaForCausalLM(cfg)
        ids = paddle.to_tensor(np.random.randint(0, cfg.vocab_size, (2, 12)))
        out = m(ids)
        assert out.shape == [2, 12, cfg.vocab_size]
        loss = m.loss(ids, ids)
        loss.backward()
        assert np.isfinite(float(loss.item()))

    def test_llama_gqa(self):
        from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM

        cfg = LlamaConfig(vocab_size=128, hidden_size=64, num_layers=2, num_heads=8, num_kv_heads=2, max_position_embeddings=64)
        m = LlamaForCausalLM(cfg)
        ids = paddle.to_tensor(np.random.randint(0, 128, (1, 8)))
        assert m(ids).shape == [1, 8, 128]

    def test_rope_rotation_preserves_norm(self):
        from paddle_tpu.models.llama import apply_rope

        q = paddle.to_tensor(np.random.rand(1, 6, 2, 8).astype(np.float32))
        k = paddle.to_tensor(np.random.rand(1, 6, 2, 8).astype(np.float32))
        q2, k2 = apply_rope(q, k)
        np.testing.assert_allclose(
            np.linalg.norm(q.numpy(), axis=-1), np.linalg.norm(q2.numpy(), axis=-1), rtol=1e-5
        )

    def test_ernie_forward(self):
        from paddle_tpu.models.ernie import ErnieConfig, ErnieForPretraining

        cfg = ErnieConfig(vocab_size=256, hidden_size=64, num_layers=2, num_heads=4, intermediate_size=128, max_position_embeddings=64)
        m = ErnieForPretraining(cfg)
        ids = paddle.to_tensor(np.random.randint(0, 256, (2, 10)))
        mlm, nsp = m(ids)
        assert mlm.shape == [2, 10, 256] and nsp.shape == [2, 2]
        labels = np.full((2, 10), -100)
        labels[:, 3] = 5
        loss = m.loss(ids, paddle.to_tensor(labels))
        assert np.isfinite(float(loss.item()))

    def test_gpt_training_reduces_loss(self):
        from paddle_tpu.models.gpt import GPTForPretraining, GPTConfig

        paddle.seed(1)
        cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2, num_heads=2, max_position_embeddings=32, hidden_dropout=0.0, attention_dropout=0.0)
        m = GPTForPretraining(cfg)
        opt = paddle.optimizer.AdamW(1e-2, parameters=m.parameters())
        step = paddle.jit.compile_train_step(m, lambda mm, i, l: mm.loss(i, l), opt)
        ids = paddle.to_tensor(np.random.randint(0, 64, (4, 16)))
        losses = [float(step(ids, ids).item()) for _ in range(15)]
        assert losses[-1] < losses[0] * 0.7, losses


class TestVisionModels:
    def test_resnet18_tiny(self):
        from paddle_tpu.vision.models import resnet18

        m = resnet18(num_classes=10)
        m.eval()
        x = paddle.to_tensor(np.random.rand(1, 3, 64, 64).astype(np.float32))
        with paddle.no_grad():
            assert m(x).shape == [1, 10]

    def test_mobilenet_v2(self):
        from paddle_tpu.vision.models import mobilenet_v2

        m = mobilenet_v2(num_classes=5)
        m.eval()
        x = paddle.to_tensor(np.random.rand(1, 3, 64, 64).astype(np.float32))
        with paddle.no_grad():
            assert m(x).shape == [1, 5]

    def test_vit_tiny(self):
        from paddle_tpu.vision.models.vit import VisionTransformer

        m = VisionTransformer(img_size=32, patch_size=8, embed_dim=64, depth=2, num_heads=4, num_classes=7)
        m.eval()
        x = paddle.to_tensor(np.random.rand(2, 3, 32, 32).astype(np.float32))
        with paddle.no_grad():
            assert m(x).shape == [2, 7]

    def test_lenet_grads_flow(self):
        from paddle_tpu.vision.models import LeNet

        m = LeNet()
        x = paddle.to_tensor(np.random.rand(2, 1, 28, 28).astype(np.float32))
        m(x).sum().backward()
        for p in m.parameters():
            assert p.grad is not None


class TestNewVisionFamilies:
    """The six families added for reference parity (vision/models/): forward
    shape + backward gradient flow on small inputs."""

    def _check(self, ctor, size=64):
        paddle.seed(0)
        m = ctor(num_classes=7)
        m.train()
        x = paddle.to_tensor(np.random.rand(1, 3, size, size).astype(np.float32))
        out = m(x)
        assert list(out.shape) == [1, 7]
        out.sum().backward()
        grads = [p.grad is not None for p in m.parameters() if not p.stop_gradient]
        assert all(grads)

    def test_squeezenet(self):
        from paddle_tpu.vision.models import squeezenet1_1

        self._check(squeezenet1_1)

    def test_densenet(self):
        from paddle_tpu.vision.models import densenet121

        self._check(densenet121)

    def test_mobilenet_v1(self):
        from paddle_tpu.vision.models import mobilenet_v1

        self._check(mobilenet_v1)

    def test_shufflenet(self):
        from paddle_tpu.vision.models import shufflenet_v2_x0_25

        self._check(shufflenet_v2_x0_25)

    def test_resnext(self):
        from paddle_tpu.vision.models import resnext50_32x4d

        self._check(resnext50_32x4d)

    def test_inception(self):
        from paddle_tpu.vision.models import inception_v3

        self._check(inception_v3, size=128)


class TestGeneration:
    """KV-cached compiled decode (models/generation.py)."""

    def _model(self):
        from paddle_tpu.models.gpt import GPTForPretraining, gpt_tiny

        paddle.seed(0)
        cfg = gpt_tiny(hidden_dropout=0.0, attention_dropout=0.0)
        m = GPTForPretraining(cfg)
        m.eval()
        return m, cfg

    def test_greedy_matches_full_forward(self):
        m, cfg = self._model()
        prompt = paddle.to_tensor(np.random.RandomState(0).randint(0, cfg.vocab_size, (2, 8)))
        out = m.generate(prompt, max_new_tokens=5, do_sample=False)
        ids = prompt.numpy().astype(np.int64)
        for _ in range(5):
            nxt = m(paddle.to_tensor(ids)).numpy()[:, -1].argmax(-1)
            ids = np.concatenate([ids, nxt[:, None]], axis=1)
        np.testing.assert_array_equal(out.numpy(), ids)

    def test_topk1_equals_greedy_and_eos(self):
        m, cfg = self._model()
        prompt = paddle.to_tensor(np.random.RandomState(1).randint(0, cfg.vocab_size, (1, 6)))
        greedy = m.generate(prompt, max_new_tokens=4, do_sample=False)
        topk1 = m.generate(prompt, max_new_tokens=4, do_sample=True, top_k=1)
        np.testing.assert_array_equal(greedy.numpy(), topk1.numpy())
        eos = int(greedy.numpy()[0, 6])
        out = m.generate(prompt, max_new_tokens=4, do_sample=False, eos_token_id=eos)
        row = out.numpy()[0, 6:]
        first = list(row).index(eos)
        assert all(t == eos for t in row[first:])

    def test_top_k_top_p_filtering(self):
        import jax.numpy as jnp
        from paddle_tpu.models.generation import top_k_top_p_filtering

        logits = jnp.asarray(np.log(np.array([[0.5, 0.3, 0.15, 0.05]], np.float32)))
        k2 = top_k_top_p_filtering(logits, top_k=2)
        assert np.isfinite(np.asarray(k2)[0, :2]).all()
        assert np.isinf(np.asarray(k2)[0, 2:]).all()
        p8 = top_k_top_p_filtering(logits, top_p=0.8)
        kept = np.isfinite(np.asarray(p8)[0])
        assert kept[:2].all() and not kept[3]

    def test_llama_greedy_matches_full_forward_gqa(self):
        from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny

        paddle.seed(0)
        cfg = llama_tiny(num_kv_heads=2)  # GQA path
        m = LlamaForCausalLM(cfg)
        m.eval()
        prompt = paddle.to_tensor(np.random.RandomState(0).randint(0, cfg.vocab_size, (2, 7)))
        out = m.generate(prompt, max_new_tokens=4, do_sample=False)
        ids = prompt.numpy().astype(np.int64)
        for _ in range(4):
            nxt = m(paddle.to_tensor(ids)).numpy()[:, -1].argmax(-1)
            ids = np.concatenate([ids, nxt[:, None]], axis=1)
        np.testing.assert_array_equal(out.numpy(), ids)
