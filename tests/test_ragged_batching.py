"""Variable-length batching policy (SURVEY §7 hard part (c)): BucketSampler
+ pad-to-bucket collate bound the number of compiled executables to the
bucket count, and masked loss over bucketed padding matches dense padding
(the reference's LoD/sequence_ops capability, shape-quantized for XLA)."""
import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.io import (
    BucketSampler,
    DataLoader,
    Dataset,
    bucket_boundaries,
    pad_to_bucket_collate,
)


class RaggedText(Dataset):
    """Token sequences with lengths 3..41."""

    def __init__(self, n=64, vocab=50, seed=0):
        rng = np.random.RandomState(seed)
        self.seqs = [
            rng.randint(1, vocab, (int(L),)).astype(np.int64)
            for L in rng.randint(3, 42, n)
        ]

    def __len__(self):
        return len(self.seqs)

    def __getitem__(self, i):
        ids = self.seqs[i]
        return ids, ids  # next-token style: labels = ids (shifted in model)


class TinyLM(nn.Layer):
    def __init__(self, vocab=50, dim=32):
        super().__init__()
        self.emb = nn.Embedding(vocab, dim)
        self.fc = nn.Linear(dim, vocab)

    def forward(self, ids):
        return self.fc(self.emb(ids))


class TestBucketSampler:
    def test_boundaries_cover_and_align(self):
        lengths = np.random.RandomState(0).randint(3, 100, 500)
        bounds = bucket_boundaries(lengths, num_buckets=6)
        assert bounds == sorted(bounds)
        assert all(b % 8 == 0 for b in bounds)
        assert bounds[-1] >= lengths.max()

    def test_batches_are_single_bucket(self):
        ds = RaggedText(64)
        lengths = [len(s) for s in ds.seqs]
        bs = BucketSampler(lengths, batch_size=4, num_buckets=4)
        seen = set()
        count = 0
        for batch in bs:
            widths = {
                next(b for b in bs.boundaries if len(ds.seqs[i]) <= b) for i in batch
            }
            assert len(widths) == 1, "mixed buckets in one batch"
            seen.update(widths)
            count += len(batch)
        assert count == len(ds)  # every sample batched exactly once
        assert len(seen) <= len(bs.boundaries)

    def test_compile_budget_bounded_by_buckets(self):
        """The ragged loader yields at most len(boundaries) distinct padded
        shapes → at most that many executables for a shape-keyed jit."""
        ds = RaggedText(64)
        lengths = [len(s) for s in ds.seqs]
        bs = BucketSampler(lengths, batch_size=8, num_buckets=4, drop_last=False)
        collate = pad_to_bucket_collate(bs.boundaries, returns_label=True)
        loader = DataLoader(
            ds, batch_sampler=bs, collate_fn=lambda b: collate(b), num_workers=0,
            use_shared_memory=False,
        )
        shapes = set()
        for ids, labels, lens in loader:
            arr = ids.numpy() if hasattr(ids, "numpy") else np.asarray(ids)
            shapes.add(arr.shape[1])
        assert len(shapes) <= len(bs.boundaries), (shapes, bs.boundaries)
        assert shapes <= set(bs.boundaries)

    def test_masked_loss_parity_bucketed_vs_dense_padding(self):
        """Per-token CE over bucket-padded batches == the same sequences
        padded to the global max (ignore_index masks pads either way)."""
        paddle.seed(3)
        model = TinyLM()
        lossf = nn.CrossEntropyLoss(ignore_index=-100)

        ds = RaggedText(16, seed=5)
        seqs = ds.seqs
        bounds = bucket_boundaries([len(s) for s in seqs], num_buckets=3)
        collate = pad_to_bucket_collate(bounds, returns_label=True)

        def token_loss(ids_np, lab_np):
            logits = model(paddle.to_tensor(ids_np))
            return lossf(
                paddle.reshape(logits, [-1, 50]),
                paddle.to_tensor(lab_np.reshape(-1)),
            )

        # bucketed: batch of 4 short sequences
        batch = [ (seqs[i], seqs[i]) for i in range(4) ]
        ids_b, lab_b, _ = collate(batch)

        # dense: same 4 sequences padded to the GLOBAL max width
        width = max(len(s) for s in seqs)
        ids_d = np.zeros((4, width), np.int64)
        lab_d = np.full((4, width), -100, np.int64)
        for i in range(4):
            ids_d[i, : len(seqs[i])] = seqs[i]
            lab_d[i, : len(seqs[i])] = seqs[i]

        lb = float(token_loss(ids_b, lab_b).numpy())
        ld = float(token_loss(ids_d, lab_d).numpy())
        np.testing.assert_allclose(lb, ld, rtol=1e-5)

    def test_ragged_training_descends(self):
        paddle.seed(1)
        model = TinyLM()
        opt = paddle.optimizer.Adam(learning_rate=0.01, parameters=model.parameters())
        lossf = nn.CrossEntropyLoss(ignore_index=-100)

        ds = RaggedText(32, seed=2)
        lengths = [len(s) for s in ds.seqs]
        bs = BucketSampler(lengths, batch_size=8, num_buckets=3, shuffle=True)
        collate = pad_to_bucket_collate(bs.boundaries, returns_label=True)
        loader = DataLoader(
            ds, batch_sampler=bs, collate_fn=lambda b: collate(b), num_workers=0,
            use_shared_memory=False,
        )

        losses = []
        for _ in range(4):
            for ids, labels, lens in loader:
                logits = model(paddle.to_tensor(np.asarray(ids._data if hasattr(ids, '_data') else ids)))
                loss = lossf(
                    paddle.reshape(logits, [-1, 50]),
                    paddle.reshape(paddle.to_tensor(np.asarray(labels._data if hasattr(labels, '_data') else labels)), [-1]),
                )
                loss.backward()
                opt.step()
                opt.clear_grad()
                losses.append(float(loss.numpy()))
        assert losses[-1] < losses[0], losses[:3] + losses[-3:]
