"""nn layer tests (reference: per-layer unittests in fluid/tests/unittests)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
import paddle_tpu.nn.functional as F


class TestLinear:
    def test_forward_matches_manual(self):
        layer = nn.Linear(4, 3)
        x = paddle.to_tensor(np.random.rand(2, 4).astype(np.float32))
        out = layer(x)
        ref = x.numpy() @ layer.weight.numpy() + layer.bias.numpy()
        np.testing.assert_allclose(out.numpy(), ref, rtol=1e-5)

    def test_no_bias(self):
        layer = nn.Linear(4, 3, bias_attr=False)
        assert layer.bias is None
        assert len(layer.parameters()) == 1

    def test_param_registration(self):
        layer = nn.Linear(4, 3)
        names = [n for n, _ in layer.named_parameters()]
        assert names == ["weight", "bias"]


class TestConvPool:
    def test_conv2d_shape(self):
        conv = nn.Conv2D(3, 8, 3, stride=2, padding=1)
        x = paddle.to_tensor(np.random.rand(2, 3, 16, 16).astype(np.float32))
        assert conv(x).shape == [2, 8, 8, 8]

    def test_conv2d_groups(self):
        conv = nn.Conv2D(4, 8, 3, padding=1, groups=2)
        x = paddle.to_tensor(np.random.rand(1, 4, 8, 8).astype(np.float32))
        assert conv(x).shape == [1, 8, 8, 8]

    def test_conv_transpose_inverts_shape(self):
        down = nn.Conv2D(3, 8, 4, stride=2, padding=1)
        up = nn.Conv2DTranspose(8, 3, 4, stride=2, padding=1)
        x = paddle.to_tensor(np.random.rand(1, 3, 16, 16).astype(np.float32))
        assert up(down(x)).shape == [1, 3, 16, 16]

    def test_maxpool_vs_manual(self):
        x = np.random.rand(1, 1, 4, 4).astype(np.float32)
        out = F.max_pool2d(paddle.to_tensor(x), 2, 2)
        ref = x.reshape(1, 1, 2, 2, 2, 2).max(axis=(3, 5))
        np.testing.assert_allclose(out.numpy(), ref)

    def test_avgpool_vs_manual(self):
        x = np.random.rand(1, 1, 4, 4).astype(np.float32)
        out = F.avg_pool2d(paddle.to_tensor(x), 2, 2)
        ref = x.reshape(1, 1, 2, 2, 2, 2).mean(axis=(3, 5))
        np.testing.assert_allclose(out.numpy(), ref, rtol=1e-6)

    def test_adaptive_avg_pool(self):
        x = paddle.to_tensor(np.random.rand(1, 2, 7, 7).astype(np.float32))
        out = F.adaptive_avg_pool2d(x, 1)
        np.testing.assert_allclose(out.numpy().reshape(2), x.numpy().mean((0, 2, 3)), rtol=1e-5)
        out = F.adaptive_avg_pool2d(x, 3)  # non-divisible path
        assert out.shape == [1, 2, 3, 3]


class TestNorm:
    def test_batchnorm_train_uses_batch_stats(self):
        bn = nn.BatchNorm2D(3)
        x = np.random.rand(4, 3, 5, 5).astype(np.float32) * 3 + 1
        out = bn(paddle.to_tensor(x))
        m = out.numpy().mean(axis=(0, 2, 3))
        v = out.numpy().var(axis=(0, 2, 3))
        np.testing.assert_allclose(m, 0, atol=1e-5)
        np.testing.assert_allclose(v, 1, atol=1e-3)

    def test_batchnorm_updates_running_stats(self):
        bn = nn.BatchNorm2D(2, momentum=0.0)  # running = batch stats directly
        x = np.random.rand(8, 2, 4, 4).astype(np.float32) * 2 + 3
        bn(paddle.to_tensor(x))
        np.testing.assert_allclose(bn._mean.numpy(), x.mean((0, 2, 3)), rtol=1e-4)

    def test_batchnorm_eval_uses_running(self):
        bn = nn.BatchNorm2D(2)
        bn.eval()
        x = np.random.rand(4, 2, 3, 3).astype(np.float32)
        out = bn(paddle.to_tensor(x))
        ref = (x - 0.0) / np.sqrt(1.0 + 1e-5)
        np.testing.assert_allclose(out.numpy(), ref, rtol=1e-4)

    def test_layernorm_matches_manual(self):
        ln = nn.LayerNorm(6)
        x = np.random.rand(3, 6).astype(np.float32)
        out = ln(paddle.to_tensor(x))
        mu = x.mean(-1, keepdims=True)
        ref = (x - mu) / np.sqrt(x.var(-1, keepdims=True) + 1e-5)
        np.testing.assert_allclose(out.numpy(), ref, rtol=1e-4)

    def test_groupnorm(self):
        gn = nn.GroupNorm(2, 4)
        x = paddle.to_tensor(np.random.rand(2, 4, 3, 3).astype(np.float32))
        out = gn(x)
        grouped = out.numpy().reshape(2, 2, 2, 3, 3)
        np.testing.assert_allclose(grouped.mean((2, 3, 4)), 0, atol=1e-4)


class TestDropout:
    def test_train_scales(self):
        paddle.seed(0)
        d = nn.Dropout(0.5)
        x = paddle.ones([1000])
        out = d(x).numpy()
        kept = out[out > 0]
        np.testing.assert_allclose(kept, 2.0)
        assert 300 < (out > 0).sum() < 700

    def test_eval_identity(self):
        d = nn.Dropout(0.5)
        d.eval()
        x = paddle.ones([10])
        np.testing.assert_array_equal(d(x).numpy(), x.numpy())


class TestEmbeddingRNN:
    def test_embedding_lookup(self):
        emb = nn.Embedding(10, 4)
        idx = paddle.to_tensor(np.array([[1, 3], [5, 1]]))
        out = emb(idx)
        np.testing.assert_allclose(out.numpy()[0, 0], emb.weight.numpy()[1])
        np.testing.assert_allclose(out.numpy()[1, 1], emb.weight.numpy()[1])

    def test_embedding_padding_idx(self):
        emb = nn.Embedding(10, 4, padding_idx=0)
        out = emb(paddle.to_tensor(np.array([0, 1])))
        np.testing.assert_allclose(out.numpy()[0], 0.0)

    def test_lstm_matches_cell_loop(self):
        paddle.seed(1)
        lstm = nn.LSTM(3, 5)
        x = paddle.to_tensor(np.random.rand(2, 4, 3).astype(np.float32))
        y, (h, c) = lstm(x)
        assert y.shape == [2, 4, 5] and h.shape == [1, 2, 5]
        # manual recompute with the same weights
        w_ih = lstm._all_weights[0][0].numpy()
        w_hh = lstm._all_weights[0][1].numpy()
        b_ih = lstm._all_weights[0][2].numpy()
        b_hh = lstm._all_weights[0][3].numpy()

        def sig(a):
            return 1 / (1 + np.exp(-a))

        hh = np.zeros((2, 5), np.float32)
        cc = np.zeros((2, 5), np.float32)
        for t in range(4):
            g = x.numpy()[:, t] @ w_ih.T + b_ih + hh @ w_hh.T + b_hh
            i, f, gg, o = np.split(g, 4, -1)
            cc = sig(f) * cc + sig(i) * np.tanh(gg)
            hh = sig(o) * np.tanh(cc)
        np.testing.assert_allclose(y.numpy()[:, -1], hh, rtol=1e-4, atol=1e-5)

    def test_bidirectional_gru(self):
        gru = nn.GRU(3, 4, direction="bidirect")
        x = paddle.to_tensor(np.random.rand(2, 5, 3).astype(np.float32))
        y, h = gru(x)
        assert y.shape == [2, 5, 8] and h.shape == [2, 2, 4]


class TestTransformer:
    def test_encoder_shapes(self):
        enc_layer = nn.TransformerEncoderLayer(16, 4, 32, dropout=0.0)
        enc = nn.TransformerEncoder(enc_layer, 2)
        x = paddle.to_tensor(np.random.rand(2, 6, 16).astype(np.float32))
        assert enc(x).shape == [2, 6, 16]

    def test_full_transformer(self):
        model = nn.Transformer(d_model=16, nhead=4, num_encoder_layers=1, num_decoder_layers=1, dim_feedforward=32, dropout=0.0)
        src = paddle.to_tensor(np.random.rand(2, 5, 16).astype(np.float32))
        tgt = paddle.to_tensor(np.random.rand(2, 3, 16).astype(np.float32))
        assert model(src, tgt).shape == [2, 3, 16]

    def test_attention_mask_blocks(self):
        mha = nn.MultiHeadAttention(8, 2, dropout=0.0)
        x = paddle.to_tensor(np.random.rand(1, 4, 8).astype(np.float32))
        mask = np.zeros((1, 1, 4, 4), np.float32)
        mask[..., 2:] = -1e9  # block attention to positions 2,3
        out_masked = mha(x, x, x, attn_mask=paddle.to_tensor(mask))
        x2 = x.numpy().copy()
        x2[0, 2:] = 0.0  # perturbing masked positions must not change output pos 0..1
        out_masked2 = mha(paddle.to_tensor(x2), paddle.to_tensor(x2), paddle.to_tensor(x2), attn_mask=paddle.to_tensor(mask))
        # only compare query positions 0..1 (keys 2.. are masked; values at q>=2 differ)
        np.testing.assert_allclose(out_masked.numpy()[:, :2], out_masked2.numpy()[:, :2], atol=1e-5)


class TestContainersStateDict:
    def test_sequential_layerlist(self):
        seq = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
        x = paddle.to_tensor(np.random.rand(3, 4).astype(np.float32))
        assert seq(x).shape == [3, 2]
        assert len(seq) == 3
        ll = nn.LayerList([nn.Linear(2, 2) for _ in range(3)])
        assert len(list(ll.parameters())) == 6

    def test_state_dict_roundtrip(self):
        m1 = nn.Sequential(nn.Linear(4, 8), nn.BatchNorm1D(8), nn.Linear(8, 2))
        m2 = nn.Sequential(nn.Linear(4, 8), nn.BatchNorm1D(8), nn.Linear(8, 2))
        m2.set_state_dict(m1.state_dict())
        for (_, p1), (_, p2) in zip(m1.named_parameters(), m2.named_parameters()):
            np.testing.assert_array_equal(p1.numpy(), p2.numpy())
        # buffers included
        sd = m1.state_dict()
        assert any("_mean" in k for k in sd)

    def test_forward_hooks(self):
        layer = nn.Linear(2, 2)
        calls = []
        h = layer.register_forward_post_hook(lambda l, i, o: calls.append(1))
        layer(paddle.to_tensor(np.zeros((1, 2), np.float32)))
        assert calls == [1]
        h.remove()
        layer(paddle.to_tensor(np.zeros((1, 2), np.float32)))
        assert calls == [1]

    def test_apply_and_modes(self):
        model = nn.Sequential(nn.Linear(2, 2), nn.Dropout(0.5))
        model.eval()
        assert not model[1].training
        model.train()
        assert model[1].training


class TestClip:
    def test_global_norm_clip(self):
        p1 = paddle.Parameter(np.ones(4, np.float32) * 3)
        g1 = paddle.to_tensor(np.ones(4, np.float32) * 3)
        clip = nn.ClipGradByGlobalNorm(1.0)
        out = clip([(p1, g1)])
        norm = np.linalg.norm(out[0][1].numpy())
        np.testing.assert_allclose(norm, 1.0, rtol=1e-5)

    def test_clip_by_value(self):
        p = paddle.Parameter(np.zeros(3, np.float32))
        g = paddle.to_tensor(np.array([-2.0, 0.5, 2.0], np.float32))
        out = nn.ClipGradByValue(1.0)([(p, g)])
        np.testing.assert_allclose(out[0][1].numpy(), [-1.0, 0.5, 1.0])


class TestLosses:
    def test_cross_entropy_matches_manual(self):
        logits = np.random.rand(4, 5).astype(np.float32)
        labels = np.array([0, 2, 4, 1])
        loss = F.cross_entropy(paddle.to_tensor(logits), paddle.to_tensor(labels))
        e = np.exp(logits - logits.max(-1, keepdims=True))
        p = e / e.sum(-1, keepdims=True)
        ref = -np.log(p[np.arange(4), labels]).mean()
        np.testing.assert_allclose(float(loss.item()), ref, rtol=1e-5)

    def test_cross_entropy_ignore_index(self):
        logits = np.random.rand(4, 5).astype(np.float32)
        labels = np.array([0, -100, 4, -100])
        loss = F.cross_entropy(paddle.to_tensor(logits), paddle.to_tensor(labels), ignore_index=-100)
        e = np.exp(logits - logits.max(-1, keepdims=True))
        p = e / e.sum(-1, keepdims=True)
        ref = -np.log(p[[0, 2], [0, 4]]).mean()
        np.testing.assert_allclose(float(loss.item()), ref, rtol=1e-5)

    def test_soft_label(self):
        logits = np.random.rand(3, 4).astype(np.float32)
        soft = np.random.dirichlet(np.ones(4), 3).astype(np.float32)
        loss = F.cross_entropy(paddle.to_tensor(logits), paddle.to_tensor(soft), soft_label=True)
        logp = logits - logits.max(-1, keepdims=True)
        logp = logp - np.log(np.exp(logp).sum(-1, keepdims=True))
        np.testing.assert_allclose(float(loss.item()), -(soft * logp).sum(-1).mean(), rtol=1e-5)

    def test_bce_with_logits_stable(self):
        x = np.array([100.0, -100.0], np.float32)
        y = np.array([1.0, 0.0], np.float32)
        loss = F.binary_cross_entropy_with_logits(paddle.to_tensor(x), paddle.to_tensor(y))
        assert float(loss.item()) < 1e-5

    def test_mse_l1(self):
        a, b = np.random.rand(5).astype(np.float32), np.random.rand(5).astype(np.float32)
        np.testing.assert_allclose(
            float(F.mse_loss(paddle.to_tensor(a), paddle.to_tensor(b)).item()), ((a - b) ** 2).mean(), rtol=1e-5
        )
        np.testing.assert_allclose(
            float(F.l1_loss(paddle.to_tensor(a), paddle.to_tensor(b)).item()), np.abs(a - b).mean(), rtol=1e-5
        )

    def test_kl_div(self):
        logp = np.log(np.random.dirichlet(np.ones(4), 2)).astype(np.float32)
        target = np.random.dirichlet(np.ones(4), 2).astype(np.float32)
        loss = F.kl_div(paddle.to_tensor(logp), paddle.to_tensor(target), reduction="sum")
        ref = (target * (np.log(target) - logp)).sum()
        np.testing.assert_allclose(float(loss.item()), ref, rtol=1e-4)
