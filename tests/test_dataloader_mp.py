"""Multiprocess DataLoader workers (io/_MultiprocessIter).

Parity: reference ``fluid/dataloader/dataloader_iter.py:326`` —
num_workers>0 forks worker PROCESSES (GIL-free preprocessing) feeding the
consumer; order is preserved; worker exceptions surface on the consumer.
"""
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.io import DataLoader, Dataset


class SlowSquares(Dataset):
    """CPU-heavy __getitem__ — the workload the GIL serializes on threads."""

    def __init__(self, n=64, work=20000):
        self.n = n
        self.work = work

    def __len__(self):
        return self.n

    def __getitem__(self, i):
        # pure-python work: holds the GIL on the thread path
        acc = 0
        for j in range(self.work):
            acc = (acc + i * j) % 1000003
        return np.asarray([i * i + (acc % 1)], dtype=np.float32), np.int64(i)


class Failing(Dataset):
    def __len__(self):
        return 8

    def __getitem__(self, i):
        if i == 5:
            raise ValueError("boom at 5")
        return np.zeros(2, np.float32)


class TestMultiprocessWorkers:
    def test_order_and_values(self):
        ds = SlowSquares(n=32, work=10)
        loader = DataLoader(ds, batch_size=4, num_workers=3, shuffle=False)
        seen = []
        for x, y in loader:
            assert x.shape == [4, 1]
            seen.extend(int(v) for v in y.numpy())
        assert seen == list(range(32))  # ordered despite parallel workers

    def test_values_match_single_worker(self):
        ds = SlowSquares(n=16, work=10)
        a = [x.numpy() for x, _ in DataLoader(ds, batch_size=4, num_workers=0)]
        b = [x.numpy() for x, _ in DataLoader(ds, batch_size=4, num_workers=2)]
        for u, v in zip(a, b):
            np.testing.assert_array_equal(u, v)

    def test_worker_exception_surfaces(self):
        loader = DataLoader(Failing(), batch_size=2, num_workers=2)
        with pytest.raises(RuntimeError, match="boom at 5"):
            for _ in loader:
                pass

    def test_workers_are_distinct_processes(self):
        # true process workers (reference forks; threads would all report the
        # parent pid and serialize python work on the GIL)
        import os

        class PidDataset(Dataset):
            def __len__(self):
                return 16

            def __getitem__(self, i):
                # slow items: under CI load one fast worker could otherwise
                # drain the whole index queue before the others even start,
                # collapsing pids to a single value and flaking the test
                time.sleep(0.05)
                return np.asarray([os.getpid()], dtype=np.int64)

        parent = os.getpid()
        loader = DataLoader(PidDataset(), batch_size=2, num_workers=4)
        pids = set()
        for batch in loader:
            pids.update(int(p) for p in batch.numpy().ravel())
        assert parent not in pids  # work happened off the main process
        assert len(pids) >= 2  # spread across multiple workers

    def test_parallel_speedup_on_gil_bound_work(self):
        import os

        if (os.cpu_count() or 1) < 4:
            pytest.skip("needs >=4 cores for wall-clock speedup")
        # threads can't scale pure-python __getitem__; processes can. Require
        # a conservative 1.5x at 4 workers to stay CI-stable.
        ds = SlowSquares(n=48, work=400000)

        def run(workers):
            loader = DataLoader(ds, batch_size=4, num_workers=workers)
            t0 = time.time()
            for _ in loader:
                pass
            return time.time() - t0

        t1 = run(0)
        t4 = run(4)
        assert t4 < t1 / 1.5, f"no speedup: 1w={t1:.2f}s 4w={t4:.2f}s"
