"""Ring & Ulysses sequence-parallel attention — the leapfrog feature
(SURVEY.md §2.3: absent in the reference). Parity vs exact attention on the
8-device CPU mesh, forward AND gradients, causal and non-causal; plus the
GPT sequence_parallel=True routing test.
"""
import math

import numpy as np
import jax
import jax.numpy as jnp
from paddle_tpu.core.compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

import paddle_tpu as paddle
from paddle_tpu.distributed.fleet.meta_parallel.sequence_parallel import (
    ring_attention,
    ulysses_attention,
)


def _mesh(axes, shape):
    devs = np.asarray(jax.devices()[: int(np.prod(shape))]).reshape(shape)
    return Mesh(devs, axes)


def exact_attention(q, k, v, causal):
    scale = 1.0 / math.sqrt(q.shape[-1])
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        T = s.shape[-1]
        s = jnp.where(jnp.tril(jnp.ones((T, T), bool)), s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


def _qkv(seed=0, B=2, T=32, H=4, D=8):
    rng = np.random.RandomState(seed)
    mk = lambda: rng.randn(B, T, H, D).astype(np.float32)
    return mk(), mk(), mk()


def _spmd(fn, sp=8):
    mesh = _mesh(("sp",), (sp,))
    spec = P(None, "sp", None, None)
    return jax.jit(
        shard_map(fn, mesh=mesh, in_specs=(spec,) * 3, out_specs=spec, check_vma=False)
    )


class TestRingAttention:
    def test_forward_parity_noncausal(self):
        q, k, v = _qkv(0)
        out = _spmd(lambda a, b, c: ring_attention(a, b, c, "sp", causal=False))(q, k, v)
        ref = exact_attention(q, k, v, causal=False)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)

    def test_forward_parity_causal(self):
        q, k, v = _qkv(1)
        out = _spmd(lambda a, b, c: ring_attention(a, b, c, "sp", causal=True))(q, k, v)
        ref = exact_attention(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)

    def test_grad_parity_causal(self):
        q, k, v = _qkv(2)
        ring = _spmd(lambda a, b, c: ring_attention(a, b, c, "sp", causal=True))

        def loss_ring(q, k, v):
            return (ring(q, k, v) ** 2).sum()

        def loss_ref(q, k, v):
            return (exact_attention(q, k, v, causal=True) ** 2).sum()

        g1 = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-4)


class TestUlyssesAttention:
    def test_forward_parity(self):
        for causal in (False, True):
            q, k, v = _qkv(3, H=8)  # H divisible by sp
            out = _spmd(lambda a, b, c: ulysses_attention(a, b, c, "sp", causal=causal))(q, k, v)
            ref = exact_attention(q, k, v, causal=causal)
            np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)

    def test_grad_parity(self):
        q, k, v = _qkv(4, H=8)
        uly = _spmd(lambda a, b, c: ulysses_attention(a, b, c, "sp", causal=True))
        g1 = jax.grad(lambda q, k, v: (uly(q, k, v) ** 2).sum(), argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(
            lambda q, k, v: (exact_attention(q, k, v, True) ** 2).sum(), argnums=(0, 1, 2)
        )(q, k, v)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-4)


class TestGPTSequenceParallel:
    def test_gpt_ring_attention_parity(self):
        """GPT with sequence_parallel=True on a (dp=2, sp=4) mesh must route
        attention through the ring and match dense single-device training."""
        from paddle_tpu.models.gpt import GPTForPretraining, gpt_tiny
        from paddle_tpu.distributed.engine import HybridParallelEngine
        from paddle_tpu.distributed import mesh as mesh_mod

        ids = np.random.RandomState(9).randint(0, 1024, (4, 32))
        labels = np.random.RandomState(10).randint(0, 1024, (4, 32))

        def make(sp_on):
            paddle.seed(23)
            cfg = gpt_tiny(
                hidden_dropout=0.0, attention_dropout=0.0,
                sequence_parallel=sp_on,
            )
            m = GPTForPretraining(cfg)
            o = paddle.optimizer.SGD(learning_rate=0.01, parameters=m.parameters())
            return m, o

        def loss_fn(m, i, l):
            return m.loss(i, l)

        # dense single-device
        m1, o1 = make(False)
        loss1 = loss_fn(m1, paddle.to_tensor(ids), paddle.to_tensor(labels))
        loss1.backward()
        o1.step()

        # sp mesh: route through ring attention
        mesh = _mesh(("dp", "sp"), (2, 4))
        prev = mesh_mod.global_mesh()
        mesh_mod.set_global_mesh(mesh)
        try:
            m2, o2 = make(True)
            # routing must be live on this mesh
            attn = m2.gpt.layers[0].attn
            assert attn._ring_mesh() is not None
            eng = HybridParallelEngine(m2, o2, loss_fn, mesh=mesh)
            loss2 = eng.train_step(paddle.to_tensor(ids), paddle.to_tensor(labels))
        finally:
            mesh_mod.set_global_mesh(prev)
        np.testing.assert_allclose(float(loss1.item()), float(loss2.item()), rtol=1e-4)
        np.testing.assert_allclose(
            m1.gpt.embeddings.word_embeddings.weight.numpy(),
            m2.gpt.embeddings.word_embeddings.weight.numpy(),
            rtol=1e-3, atol=1e-5,
        )


def test_ulysses_no_txt_materialization():
    """The Ulysses local step must not materialize a (.., T, T) score matrix
    (VERDICT r2 weak #4): check the lowered HLO of the local attention for a
    TxT-shaped tensor."""
    import re
    import jax
    import jax.numpy as jnp
    from paddle_tpu.distributed.fleet.meta_parallel.sequence_parallel import _local_attention

    T = 1024
    q = jnp.zeros((1, T, 2, 64), jnp.float32)
    txt = jax.jit(lambda a: _local_attention(a, a, a, True)).lower(q).as_text()
    assert not re.search(rf"{T}x{T}", txt), "TxT score tensor found in HLO"


class TestRingChunkedAndDtype:
    def test_chunked_q_path_parity(self):
        """T_local > _Q_CHUNK exercises the chunked score path (peak score
        block C x T_local, not T_local^2)."""
        q, k, v = _qkv(7, B=1, T=2048, H=2, D=8)  # sp=2 -> T_local=1024 > 512
        out = _spmd(
            lambda a, b, c: ring_attention(a, b, c, "sp", causal=True), sp=2
        )(q, k, v)
        ref = exact_attention(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=3e-5, atol=3e-5)

        # gradients through the chunked lax.map + per-chunk mask_fn path
        def ring_loss(a, b, c):
            o = _spmd(
                lambda x, y, z: ring_attention(x, y, z, "sp", causal=True), sp=2
            )(a, b, c)
            return (o.astype(jnp.float32) ** 2).sum()

        def exact_loss(a, b, c):
            return (exact_attention(a, b, c, causal=True).astype(jnp.float32) ** 2).sum()

        g_ring = jax.grad(ring_loss, argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(exact_loss, argnums=(0, 1, 2))(q, k, v)
        for gr, ge in zip(g_ring, g_ref):
            np.testing.assert_allclose(
                np.asarray(gr), np.asarray(ge), rtol=5e-4, atol=5e-4
            )

    def test_kv_rotate_in_input_dtype(self, monkeypatch):
        """bf16 K/V must ride the ring in bf16 (round-3 carried f32: 2x comm)."""
        from jax import lax as jlax
        from paddle_tpu.distributed.fleet.meta_parallel import sequence_parallel as spm

        q, k, v = _qkv(8)
        qb = jnp.asarray(q, jnp.bfloat16)
        kb = jnp.asarray(k, jnp.bfloat16)
        vb = jnp.asarray(v, jnp.bfloat16)
        seen = []
        orig = jlax.ppermute

        def spy(x, axis_name, perm):
            seen.append(x.dtype)
            return orig(x, axis_name, perm)

        class LaxProxy:
            def __getattr__(self, name):
                return spy if name == "ppermute" else getattr(jlax, name)

        monkeypatch.setattr(spm, "lax", LaxProxy())
        out = _spmd(lambda a, b, c: ring_attention(a, b, c, "sp", causal=False))(qb, kb, vb)
        assert seen and all(dt == jnp.bfloat16 for dt in seen), seen
        assert out.dtype == jnp.bfloat16
