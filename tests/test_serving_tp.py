"""Multi-chip serving — tensor-parallel paged decode + chunked prefill.

Pins the ISSUE-19 acceptance surface:

- ``FLAGS_serve_tp``/``EngineConfig(tp=...)`` shards attention heads, FFN
  columns, the LM head, and the KV ``PagePool`` over a ``tp`` mesh axis via
  shard_map, with every tp boundary a CONCAT-style all_gather of
  column-partitioned outputs — greedy decode must be **bit-identical** to
  the single-chip engine (GPT and Llama/GQA, ``FLAGS_serve_paged_kernel``
  on and off, prefix cache on and off, engine int8 on).
- ``FLAGS_serve_prefill_chunk`` splits prompt prefill into block-multiple
  chunks interleaved one per scheduler step with the live decode batch;
  the chunked path must be bit-identical to monolithic prefill (prefix
  cache composing through the same tail program).
- ``Engine.snapshot()``'s compat key carries the tp degree + KV shard
  layout: cross-mesh adoption is a structured ``SnapshotError`` with the
  re-prefill fallback, never a silent re-shard of live KV.
- The unconfigured engine (tp unset, chunking off) takes the EXACT prior
  code path: tp builders and the chunk splitter are monkeypatch-exploded
  and never called.

Cross-feature gap (same ISSUE): preemption (evict + re-prefill) and
snapshot/adopt pinned bit-identical with ``FLAGS_serve_paged_kernel=1``.
"""
import time

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.models.generation as G
from paddle_tpu import profiler
from paddle_tpu.framework import flags
from paddle_tpu.serving import Engine, ServeError, SnapshotError
from serving_util import ENGINE_KW, make_prompts, tiny_gpt

jnp = pytest.importorskip("jax.numpy")
import jax  # noqa: E402

needs2 = pytest.mark.skipif(
    jax.device_count() < 2,
    reason="tensor-parallel serving tests need >= 2 devices")


@pytest.fixture(scope="module")
def model():
    return tiny_gpt()


def _llama_gqa():
    from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny

    paddle.seed(0)
    m = LlamaForCausalLM(llama_tiny(num_kv_heads=2))
    m.eval()
    return m


def _run_engine(model, prompt_seed=3, n=4, max_new=8, vocab=211,
                prompts=None, flag_overrides=None, **kw):
    """Greedy token outputs of a fresh engine under flag + config
    overrides."""
    fl = dict(flag_overrides or {})
    old = {k: flags._FLAGS.get(k) for k in fl}
    flags._FLAGS.update(fl)
    try:
        with Engine(model, **dict(ENGINE_KW, **kw)) as eng:
            if prompts is None:
                rng = np.random.RandomState(prompt_seed)
                prompts = [rng.randint(0, vocab, (int(rng.randint(3, 24)),))
                           .tolist() for _ in range(n)]
            handles = [eng.submit(p, max_new_tokens=max_new, temperature=0.0)
                       for p in prompts]
            return [h.result(timeout=600) for h in handles]
    finally:
        for k, v in old.items():
            if v is None:
                flags._FLAGS.pop(k, None)
            else:
                flags._FLAGS[k] = v


# ------------------------------------------------------------- tp bit-identity
@needs2
class TestTpBitIdentity:
    # tier-1 runs the two ends of the grid (plain gather and the deepest
    # compose, prefix+kernel); the mixed combos are slow-marked — same
    # contract, kept out of the tier-1 time budget
    @pytest.mark.parametrize(
        "prefix, kernel",
        [pytest.param(False, False, id="plain-gather"),
         pytest.param(False, True, id="plain-paged_kernel",
                      marks=pytest.mark.slow),
         pytest.param(True, False, id="prefix_cache-gather",
                      marks=pytest.mark.slow),
         pytest.param(True, True, id="prefix_cache-paged_kernel")])
    def test_gpt_tokens_identical(self, model, kernel, prefix):
        fl = {"FLAGS_serve_paged_kernel": kernel,
              "FLAGS_serve_prefix_cache": prefix}
        base = _run_engine(model, flag_overrides=fl)
        tp2 = _run_engine(model, flag_overrides=fl, tp=2)
        assert base == tp2

    @pytest.mark.slow
    @pytest.mark.parametrize("kernel", [False, True],
                             ids=["gather", "paged_kernel"])
    def test_llama_gqa_tokens_identical(self, kernel):
        m = _llama_gqa()
        fl = {"FLAGS_serve_paged_kernel": kernel,
              "FLAGS_serve_prefix_cache": True}
        base = _run_engine(m, vocab=1024, flag_overrides=fl)
        tp2 = _run_engine(m, vocab=1024, flag_overrides=fl, tp=2)
        assert base == tp2

    @pytest.mark.slow
    def test_tp_composes_with_engine_int8(self, model):
        """The int8-tagged weight tree shards on its int8 bytes (per-tensor
        scales make slice-then-dequantize bitwise exact), so a quantized
        engine's tokens must not change with tp."""
        base = _run_engine(model, int8=True)
        tp2 = _run_engine(model, int8=True, tp=2)
        assert base == tp2

    def test_flag_configures_tp(self, model, monkeypatch):
        """FLAGS_serve_tp must really route to the shard_map builders."""
        called = {"n": 0}
        real = G.build_tp_paged_decode

        def spy(*a, **k):
            called["n"] += 1
            return real(*a, **k)

        monkeypatch.setattr(G, "build_tp_paged_decode", spy)
        out = _run_engine(model, flag_overrides={"FLAGS_serve_tp": 2})
        assert called["n"] >= 1
        assert out == _run_engine(model)

    def test_tp_int8_wire_is_lossy_but_serves(self, model):
        """EQuARX-style quantized collectives are opt-in and LOSSY: the
        engine must complete every stream (right lengths), with no
        bit-identity promise."""
        rng = np.random.RandomState(4)
        prompts = [rng.randint(0, 211, (int(rng.randint(3, 24)),)).tolist()
                   for _ in range(4)]
        outs = _run_engine(model, prompts=prompts, tp=2, tp_int8=True)
        assert [len(o) for o in outs] == [len(p) + 8 for p in prompts]

    def test_tp_validation(self, model):
        with pytest.raises(ValueError, match="divide"):
            Engine(model, **dict(ENGINE_KW, tp=8))  # 8 does not divide H=2
        ndev = len(jax.devices())
        with pytest.raises(ValueError, match="exceeds"):
            Engine(model, **dict(ENGINE_KW, tp=2 * ndev))
        with pytest.raises(ValueError, match="speculative"):
            Engine(model, **dict(ENGINE_KW, tp=2, spec_k=2))


# ------------------------------------------------------------ chunked prefill
class TestChunkedPrefill:
    def test_chunked_bitwise_vs_monolithic(self, model):
        """Long prompts through FLAGS_serve_prefill_chunk-sized chunks land
        the same first token and the same greedy continuation as one
        monolithic prefill pass."""
        rng = np.random.RandomState(11)
        prompts = [rng.randint(0, 211, (int(n),)).tolist()
                   for n in (40, 61, 17, 33, 7, 64)]
        base = _run_engine(model, prompts=prompts)
        assert _run_engine(model, prompts=prompts, prefill_chunk=8) == base

    @pytest.mark.slow
    def test_chunked_bitwise_at_wider_chunk(self, model):
        rng = np.random.RandomState(11)
        prompts = [rng.randint(0, 211, (int(n),)).tolist()
                   for n in (40, 61, 17, 33, 7, 64)]
        base = _run_engine(model, prompts=prompts)
        assert _run_engine(model, prompts=prompts, prefill_chunk=16) == base

    @pytest.mark.slow
    def test_chunked_composes_with_prefix_cache(self, model):
        """A prefix-cached tail is itself chunked (the cursor starts at the
        cached-block boundary) and must stay bit-identical."""
        rng = np.random.RandomState(12)
        stem = rng.randint(0, 211, (32,)).tolist()
        prompts = [stem + rng.randint(0, 211, (int(n),)).tolist()
                   for n in (24, 30, 5)]
        fl = {"FLAGS_serve_prefix_cache": True}
        base = _run_engine(model, prompts=prompts, flag_overrides=fl)
        chunked = _run_engine(model, prompts=prompts, flag_overrides=fl,
                              prefill_chunk=8)
        assert chunked == base
        assert profiler.counters().get("serve_prefill_chunks", 0) > 0

    @needs2
    @pytest.mark.slow
    def test_chunked_composes_with_tp(self, model):
        rng = np.random.RandomState(13)
        prompts = [rng.randint(0, 211, (int(n),)).tolist()
                   for n in (48, 9, 25)]
        base = _run_engine(model, prompts=prompts)
        assert _run_engine(model, prompts=prompts, tp=2,
                           prefill_chunk=16) == base

    def test_chunk_must_be_block_multiple(self, model):
        with pytest.raises(ValueError, match="multiple of block_size"):
            Engine(model, **dict(ENGINE_KW, prefill_chunk=12))

    def test_decode_interleaves_with_chunked_prefill(self, model):
        """The scheduler-step interleave: while a long prompt prefills
        chunk by chunk, an already-running short stream keeps producing
        tokens — its output matches an unconcurrent run (determinism), and
        the chunk counter proves the long admit really took the
        incremental path."""
        rng = np.random.RandomState(14)
        short = rng.randint(0, 211, (5,)).tolist()
        long_p = rng.randint(0, 211, (64,)).tolist()
        alone = _run_engine(model, prompts=[short], max_new=16)
        c0 = profiler.counters().get("serve_prefill_chunks", 0)
        with Engine(model, **dict(ENGINE_KW, prefill_chunk=8,
                                  prefill_batch=1)) as eng:
            h_short = eng.submit(short, max_new_tokens=16, temperature=0.0)
            # wait for the short stream to be decoding, then admit the long
            deadline = time.monotonic() + 30
            while eng.stats()["decode_steps"] < 1 \
                    and time.monotonic() < deadline:
                time.sleep(0.005)
            h_long = eng.submit(long_p, max_new_tokens=4, temperature=0.0)
            outs = [h_short.result(timeout=600), h_long.result(timeout=600)]
        assert outs[0] == alone[0]
        assert len(outs[1]) == len(long_p) + 4
        assert profiler.counters().get("serve_prefill_chunks", 0) >= c0 + 8


# ------------------------------------------------------- snapshot geometry
@needs2
class TestSnapshotMeshGeometry:
    def test_cross_mesh_adopt_is_structured_refusal(self, model):
        """A tp=2 snapshot's KV pool is sharded state: adopting it on a
        different mesh shape must be a SnapshotError (raise mode) or the
        whole-capture re-prefill fallback — never a silent re-shard."""
        rng = np.random.RandomState(21)
        prompts = [rng.randint(0, 211, (int(rng.randint(3, 24)),)).tolist()
                   for _ in range(4)]
        baseline = _run_engine(model, prompts=prompts, max_new=10)
        old = Engine(model, **dict(ENGINE_KW, tp=2))
        try:
            hs = [old.submit(p, max_new_tokens=10, temperature=0.0)
                  for p in prompts]
            deadline = time.monotonic() + 30
            while old.stats()["decode_steps"] < 2 \
                    and time.monotonic() < deadline:
                time.sleep(0.005)
            snap = old.handoff()
            with Engine(model, **ENGINE_KW) as single:
                with pytest.raises(SnapshotError, match="geometry"):
                    single.adopt(snap, fallback="raise")
            with Engine(model, **ENGINE_KW) as single:
                info = single.adopt(snap)  # default: re-prefill fallback
                assert info["mode"] == "reprefill"
                assert "reject_reason" in info
                outs = [h.result(timeout=600) for h in hs]
            assert outs == baseline
        finally:
            old.close()

    @pytest.mark.slow
    def test_same_mesh_adopt_reattaches(self, model):
        """tp=2 -> tp=2 handoff stays the zero-re-prefill reattach path,
        and the sharded KV survives the move bit-identically."""
        rng = np.random.RandomState(22)
        prompts = [rng.randint(0, 211, (int(rng.randint(3, 24)),)).tolist()
                   for _ in range(4)]
        baseline = _run_engine(model, prompts=prompts, max_new=10)
        old = Engine(model, **dict(ENGINE_KW, tp=2))
        try:
            hs = [old.submit(p, max_new_tokens=10, temperature=0.0)
                  for p in prompts]
            deadline = time.monotonic() + 30
            while old.stats()["decode_steps"] < 2 \
                    and time.monotonic() < deadline:
                time.sleep(0.005)
            snap = old.handoff()
            with Engine(model, **dict(ENGINE_KW, tp=2)) as new:
                info = new.adopt(snap)
                assert info["mode"] == "reattach"
                outs = [h.result(timeout=600) for h in hs]
            assert outs == baseline
        finally:
            old.close()


# ---------------------------------------------- paged kernel cross-feature
class TestPagedKernelCrossFeature:
    """ISSUE-19 satellite: preemption and snapshot/adopt had no coverage
    with FLAGS_serve_paged_kernel=1."""

    PREEMPT_KW = dict(block_size=8, num_blocks=10, max_batch=4,
                      max_seq_len=72)

    def _preempt_run(self, model, kernel):
        old = flags._FLAGS.get("FLAGS_serve_paged_kernel")
        flags._FLAGS["FLAGS_serve_paged_kernel"] = kernel
        try:
            rng = np.random.RandomState(7)
            with Engine(model, **self.PREEMPT_KW) as eng:
                hs = [eng.submit(rng.randint(0, 211, (8,)).tolist(),
                                 max_new_tokens=24, temperature=0.0)
                      for _ in range(4)]
                return [h.result(timeout=600) for h in hs]
        finally:
            if old is None:
                flags._FLAGS.pop("FLAGS_serve_paged_kernel", None)
            else:
                flags._FLAGS["FLAGS_serve_paged_kernel"] = old

    @pytest.mark.slow
    def test_preemption_bit_identical_with_kernel(self, model):
        """A pool too small for the batch forces evict + re-prefill; the
        kernel path must ride it to the same greedy tokens."""
        c0 = profiler.counters().get("serve_preempted", 0)
        base = self._preempt_run(model, False)
        assert profiler.counters().get("serve_preempted", 0) > c0, \
            "config did not actually preempt"
        kern = self._preempt_run(model, True)
        assert base == kern
        assert all(len(o) == 32 for o in base)

    @pytest.mark.slow
    def test_handoff_adopt_bit_identical_with_kernel(self, model):
        old_fl = flags._FLAGS.get("FLAGS_serve_paged_kernel")
        flags._FLAGS["FLAGS_serve_paged_kernel"] = True
        try:
            rng = np.random.RandomState(23)
            prompts = [rng.randint(0, 211,
                                   (int(rng.randint(3, 24)),)).tolist()
                       for _ in range(4)]
            with Engine(model, **ENGINE_KW) as eng:
                baseline = [eng.submit(p, max_new_tokens=10,
                                       temperature=0.0).result(timeout=600)
                            for p in prompts]
            old = Engine(model, **ENGINE_KW)
            try:
                hs = [old.submit(p, max_new_tokens=10, temperature=0.0)
                      for p in prompts]
                deadline = time.monotonic() + 30
                while old.stats()["decode_steps"] < 2 \
                        and time.monotonic() < deadline:
                    time.sleep(0.005)
                snap = old.handoff()
                with Engine(model, **ENGINE_KW) as new:
                    info = new.adopt(snap)
                    assert info["mode"] == "reattach"
                    outs = [h.result(timeout=600) for h in hs]
                assert outs == baseline
            finally:
                old.close()
        finally:
            if old_fl is None:
                flags._FLAGS.pop("FLAGS_serve_paged_kernel", None)
            else:
                flags._FLAGS["FLAGS_serve_paged_kernel"] = old_fl


# ------------------------------------------------------------ inert tripwire
class TestInertTripwire:
    def test_unconfigured_engine_never_touches_tp_or_chunking(
            self, model, monkeypatch):
        """tp unset + chunking off => the exact PR 18 code path: every
        shard_map builder and both chunk-scheduler hooks explode if
        reached, and plain traffic (prefix cache + paged kernel armed, the
        busiest prior configuration) never reaches them."""
        import paddle_tpu.serving.engine as E

        def boom(*a, **k):
            raise AssertionError(
                "tp/chunked-prefill machinery ran on the unconfigured path")

        for name in ("build_tp_paged_decode", "build_tp_paged_prefill",
                     "build_tp_paged_tail_prefill", "tp_pack_params"):
            monkeypatch.setattr(G, name, boom)
        monkeypatch.setattr(E.Engine, "_chunk_divert", boom)
        monkeypatch.setattr(E.Engine, "_chunk_step", boom)
        rng = np.random.RandomState(4)
        prompts = [rng.randint(0, 211, (int(rng.randint(3, 24)),)).tolist()
                   for _ in range(4)]
        out = _run_engine(model, prompts=prompts, flag_overrides={
            "FLAGS_serve_prefix_cache": True,
            "FLAGS_serve_paged_kernel": True})
        assert [len(o) for o in out] == [len(p) + 8 for p in prompts]
        eng = Engine(model, **ENGINE_KW)
        try:
            assert eng.config.tp == 0
            assert eng.config.prefill_chunk == 0
            assert eng._tp == 0 and eng._chunk == 0
        finally:
            eng.close()
