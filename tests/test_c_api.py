"""C inference API (runtime_cpp/paddle_tpu_c.{h,cc}) — smoke test via ctypes.

Parity: reference ``inference/capi_exp/pd_inference_api.h`` lifecycle
(create → set input → run → get output) over the StableHLO AOT Predictor.
"""
import ctypes
import os
import subprocess
import tempfile

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.static import InputSpec

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LIB = os.path.join(ROOT, "runtime_cpp", "libpaddle_tpu_c.so")


def _build_lib():
    if not os.path.exists(LIB):
        subprocess.run(["make", "-C", os.path.join(ROOT, "runtime_cpp")], check=True)
    return os.path.exists(LIB)


@pytest.fixture(scope="module")
def capi():
    if not _build_lib():
        pytest.skip("C API library unavailable")
    lib = ctypes.CDLL(LIB)
    lib.PD_PredictorCreate.restype = ctypes.c_void_p
    lib.PD_PredictorCreate.argtypes = [ctypes.c_char_p]
    lib.PD_PredictorSetInputFloat.restype = ctypes.c_int
    lib.PD_PredictorSetInputFloat.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.POINTER(ctypes.c_float),
        ctypes.POINTER(ctypes.c_int64), ctypes.c_int,
    ]
    lib.PD_PredictorRun.restype = ctypes.c_int
    lib.PD_PredictorRun.argtypes = [ctypes.c_void_p]
    lib.PD_PredictorOutputNumel.restype = ctypes.c_int64
    lib.PD_PredictorOutputNumel.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.PD_PredictorGetOutputFloat.restype = ctypes.c_int
    lib.PD_PredictorGetOutputFloat.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.POINTER(ctypes.c_float),
        ctypes.c_int64,
    ]
    lib.PD_PredictorInputName.restype = ctypes.c_char_p
    lib.PD_PredictorInputName.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.PD_PredictorOutputName.restype = ctypes.c_char_p
    lib.PD_PredictorOutputName.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.PD_PredictorDestroy.argtypes = [ctypes.c_void_p]
    lib.PD_LastError.restype = ctypes.c_char_p
    return lib


class TestCAPI:
    def test_create_run_get_output(self, capi, tmp_path):
        paddle.seed(0)
        model = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 3))
        model.eval()
        prefix = str(tmp_path / "mlp")
        paddle.static.save_inference_model(
            prefix, [InputSpec([2, 4], "float32", name="x")], model
        )

        p = capi.PD_PredictorCreate(prefix.encode())
        assert p, capi.PD_LastError().decode()

        in_name = capi.PD_PredictorInputName(p, 0)
        out_name = capi.PD_PredictorOutputName(p, 0)
        assert in_name and out_name

        x = np.random.RandomState(0).randn(2, 4).astype(np.float32)
        shape = (ctypes.c_int64 * 2)(2, 4)
        rc = capi.PD_PredictorSetInputFloat(
            p, in_name, x.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), shape, 2
        )
        assert rc == 0, capi.PD_LastError().decode()
        assert capi.PD_PredictorRun(p) == 0, capi.PD_LastError().decode()

        n = capi.PD_PredictorOutputNumel(p, out_name)
        assert n == 6
        buf = (ctypes.c_float * n)()
        rc = capi.PD_PredictorGetOutputFloat(p, out_name, buf, n)
        assert rc == 0, capi.PD_LastError().decode()
        got = np.frombuffer(buf, np.float32).reshape(2, 3)

        want = model(paddle.to_tensor(x)).numpy()
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)
        capi.PD_PredictorDestroy(p)
