"""Profiler hot-path wiring: dispatch / lazy flush / compiled train step all
emit named host events while a Profiler is active (reference records every
traced op — imperative/tracer.cc:177 RecordEvent)."""
import json

import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu import profiler


def _train_loop(steps=3):
    model = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
    opt = paddle.optimizer.SGD(learning_rate=0.01, parameters=model.parameters())
    lossf = nn.CrossEntropyLoss()
    x = paddle.to_tensor(np.random.RandomState(0).randn(4, 8).astype(np.float32))
    y = paddle.to_tensor(np.random.RandomState(1).randint(0, 4, (4,)))
    for _ in range(steps):
        loss = lossf(model(x), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
    return float(loss.item())


class TestProfilerWiring:
    def test_eager_train_loop_emits_op_events(self):
        p = profiler.Profiler(timer_only=True)
        p.start()
        _train_loop()
        p.stop()
        names = [e.name for e in profiler._events]
        op_events = [n for n in names if n.startswith("op::")]
        assert len(op_events) > 10, f"dispatch not instrumented: {names[:20]}"
        # the lazy engine flushed at least once (loss.item materializes)
        assert any(n.startswith("lazy::flush") for n in names), names[:20]

    def test_compiled_train_step_emits_event(self):
        model = nn.Linear(8, 4)
        opt = paddle.optimizer.SGD(learning_rate=0.01, parameters=model.parameters())
        step = paddle.jit.compile_train_step(
            model, lambda m, x, y: nn.functional.mse_loss(m(x), y), opt
        )
        x = paddle.to_tensor(np.zeros((2, 8), np.float32))
        y = paddle.to_tensor(np.zeros((2, 4), np.float32))
        with profiler.Profiler(timer_only=True):
            step(x, y)
            step(x, y)
        names = [e.name for e in profiler._events]
        assert names.count("jit::train_step") == 2, names

    def test_chrome_export_contains_named_spans(self, tmp_path):
        p = profiler.Profiler(timer_only=True)
        p.start()
        _train_loop(steps=1)
        p.stop()
        out = tmp_path / "trace.json"
        p.export(str(out))
        trace = json.loads(out.read_text())
        events = trace["traceEvents"]
        assert len(events) >= 5
        assert all("name" in e and "dur" in e for e in events)
        assert any(e["name"].startswith("op::") for e in events)

    def test_summary_aggregates(self):
        p = profiler.Profiler(timer_only=True)
        p.start()
        _train_loop(steps=1)
        p.stop()
        s = p.summary()
        assert "op::" in s and "calls" in s

    def test_disabled_profiler_records_nothing(self):
        profiler._events.clear()
        _train_loop(steps=1)
        assert profiler._events == []
