"""Structured runtime telemetry.

Covers the observability subsystem end to end:
* hot-path wiring — dispatch / lazy flush / compiled train step emit events
  and spans while a Profiler is active (reference imperative/tracer.cc:177);
* span tracer — correct ``train_step`` → ``lazy_flush`` →
  ``trace``/``donate``/``compile``/``execute`` nesting with cache hit/miss
  and donation attributes;
* scheduler — make_scheduler state transitions driving ``Profiler.step()``;
* exporters — chrome trace (merged sinks + metadata snapshot), JSON-lines
  round-trip, Prometheus text metrics;
* memory accounting — per-flush ``jax.live_arrays()`` census + peak gauge;
* flight recorder — always-on ring, crash dumps;
* overhead guard — the CLOSED profiler (flight recorder included) must not
  tax the hot dispatch loop.
"""
import json
import time

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu import profiler
from paddle_tpu.profiler import ProfilerState, flight, make_scheduler


def _train_loop(steps=3, span_per_step=False):
    model = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
    opt = paddle.optimizer.SGD(learning_rate=0.01, parameters=model.parameters())
    lossf = nn.CrossEntropyLoss()
    x = paddle.to_tensor(np.random.RandomState(0).randn(4, 8).astype(np.float32))
    y = paddle.to_tensor(np.random.RandomState(1).randint(0, 4, (4,)))
    for step in range(steps):
        if span_per_step:
            with profiler.span("train_step", step=step):
                loss = lossf(model(x), y)
                loss.backward()
                opt.step()
                opt.clear_grad()
                loss.item()  # materialize INSIDE the step span
        else:
            loss = lossf(model(x), y)
            loss.backward()
            opt.step()
            opt.clear_grad()
            loss.item()
    return float(loss.item())


class TestProfilerWiring:
    def test_eager_train_loop_emits_op_events(self):
        p = profiler.Profiler(timer_only=True)
        p.start()
        _train_loop()
        p.stop()
        names = [e.name for e in profiler.events()]
        op_events = [n for n in names if n.startswith("op::")]
        assert len(op_events) > 10, f"dispatch not instrumented: {names[:20]}"
        # the lazy engine flushed at least once (loss.item materializes)
        spans = [s["name"] for s in profiler.span_events()]
        assert "lazy_flush" in spans, spans[:20]

    def test_compiled_train_step_emits_span(self):
        model = nn.Linear(8, 4)
        opt = paddle.optimizer.SGD(learning_rate=0.01, parameters=model.parameters())
        step = paddle.jit.compile_train_step(
            model, lambda m, x, y: nn.functional.mse_loss(m(x), y), opt
        )
        x = paddle.to_tensor(np.zeros((2, 8), np.float32))
        y = paddle.to_tensor(np.zeros((2, 4), np.float32))
        with profiler.Profiler(timer_only=True):
            step(x, y)
            step(x, y)
        spans = [
            s for s in profiler.span_events()
            if s["name"] == "train_step" and s["attrs"].get("kind") == "jit"
        ]
        assert len(spans) == 2, profiler.span_events()

    def test_chrome_export_contains_named_spans(self, tmp_path):
        p = profiler.Profiler(timer_only=True)
        p.start()
        _train_loop(steps=1)
        p.stop()
        out = tmp_path / "trace.json"
        p.export(str(out))
        trace = json.loads(out.read_text())
        events = trace["traceEvents"]
        assert len(events) >= 5
        assert all("name" in e and "dur" in e for e in events)
        assert any(e["name"].startswith("op::") for e in events)
        assert any(e.get("cat") == "span" for e in events)

    def test_summary_aggregates_and_sorts(self):
        p = profiler.Profiler(timer_only=True)
        p.start()
        _train_loop(steps=1)
        p.stop()
        s = p.summary()
        assert "op::" in s and "calls" in s
        assert "avg_ms" in s and "min_ms" in s and "max_ms" in s
        by_calls = p.summary(sorted_by="calls").splitlines()[1:]
        counts = [int(line.split()[-5]) for line in by_calls]
        assert counts == sorted(counts, reverse=True)
        by_name = p.summary(sorted_by="name").splitlines()[1:]
        names = [line.split()[0] for line in by_name]
        assert names == sorted(names)
        with pytest.raises(ValueError, match="sorted_by"):
            p.summary(sorted_by="bogus")

    def test_disabled_profiler_records_nothing(self):
        before_ev = len(profiler.events())
        before_sp = len(profiler.span_events())
        _train_loop(steps=1)
        # session sinks untouched; the always-on flight ring still observes
        assert len(profiler.events()) == before_ev
        assert len(profiler.span_events()) == before_sp


class TestSpanTracer:
    def test_nesting_and_cache_attribution(self):
        p = profiler.Profiler(timer_only=True)
        p.start()
        _train_loop(steps=3, span_per_step=True)
        p.stop()
        spans = profiler.span_events()
        by_id = {s["span_id"]: s for s in spans}
        steps = [s for s in spans if s["name"] == "train_step"]
        flushes = [s for s in spans if s["name"] == "lazy_flush"]
        assert len(steps) == 3 and len(flushes) >= 3
        # the per-step flushes nest under their train_step span (model-init
        # flushes, if any, legitimately sit at the root)
        nested = [
            f for f in flushes
            if by_id.get(f["parent_id"], {}).get("name") == "train_step"
        ]
        assert len(nested) >= 3, flushes
        # compile on the first (cache-miss) flush; cache hits then DISPATCH
        # the executable without blocking (async runtime; the "execute" name
        # survives only on the FLAGS_lazy_async=0 path and eager fallbacks)
        kids = [s for s in spans if s["name"] in ("compile", "execute", "dispatch")]
        assert any(s["name"] == "compile" for s in kids)
        assert any(
            s["name"] in ("dispatch", "execute") and s["attrs"].get("cache") == "hit"
            for s in kids
        )
        for s in kids:
            assert by_id[s["parent_id"]]["name"] == "lazy_flush"
        # hit/miss is recorded on the flush span itself too, and a hit's key
        # matches the miss that compiled its executable
        assert {f["attrs"]["cache"] for f in flushes} == {"hit", "miss"}
        hit = next(f for f in flushes if f["attrs"]["cache"] == "hit")
        miss_keys = {
            f["attrs"]["cache_key"] for f in flushes if f["attrs"]["cache"] == "miss"
        }
        assert hit["attrs"]["cache_key"] in miss_keys
        # the steady-state step donated its rebound param/moment buffers
        assert any(f["attrs"].get("donated_buffers", 0) > 0 for f in flushes)
        assert any(f["attrs"].get("donated_bytes", 0) > 0 for f in flushes)

    def test_trace_and_donate_child_spans(self):
        p = profiler.Profiler(timer_only=True)
        p.start()
        _train_loop(steps=2)
        p.stop()
        spans = profiler.span_events()
        by_id = {s["span_id"]: s for s in spans}
        for name in ("trace", "donate"):
            sub = [s for s in spans if s["name"] == name]
            assert sub, f"no {name} spans in {[s['name'] for s in spans]}"
            assert all(by_id[s["parent_id"]]["name"] == "lazy_flush" for s in sub)

    def test_memory_accounting_census(self):
        p = profiler.Profiler(timer_only=True, profile_memory=True)
        p.start()
        _train_loop(steps=2)
        p.stop()
        flushes = [
            s for s in profiler.span_events() if s["name"] == "lazy_flush"
        ]
        assert flushes
        assert all("live_bytes" in f["attrs"] for f in flushes)
        assert all("delta_bytes" in f["attrs"] for f in flushes)
        stats = profiler.memory_stats()
        assert stats["peak_live_bytes"] >= stats["live_bytes"] > 0
        assert stats["censuses"] >= 2

    def test_span_records_error_attr(self):
        with pytest.raises(ValueError):
            with profiler.span("doomed"):
                raise ValueError("boom")
        sp = flight.recent_spans()[-1]
        assert sp.name == "doomed" and sp.attrs["error"] == "ValueError"


class TestScheduler:
    def test_make_scheduler_state_sequence(self):
        sched = make_scheduler(closed=1, ready=1, record=2, skip_first=1)
        got = [sched(s) for s in range(9)]
        C, R, REC, RAR = (
            ProfilerState.CLOSED, ProfilerState.READY,
            ProfilerState.RECORD, ProfilerState.RECORD_AND_RETURN,
        )
        assert got == [C, C, R, REC, RAR, C, R, REC, RAR]

    def test_repeat_bounds_cycles(self):
        sched = make_scheduler(closed=0, ready=0, record=1, repeat=2)
        assert sched(0) == ProfilerState.RECORD_AND_RETURN
        assert sched(1) == ProfilerState.RECORD_AND_RETURN
        assert sched(2) == ProfilerState.CLOSED
        assert sched(100) == ProfilerState.CLOSED

    def test_make_scheduler_validates(self):
        with pytest.raises(ValueError):
            make_scheduler(record=0)
        with pytest.raises(ValueError):
            make_scheduler(closed=-1)

    def test_profiler_step_drives_recording_windows(self):
        traces = []
        p = profiler.Profiler(
            timer_only=True,
            scheduler=make_scheduler(closed=1, ready=1, record=2),
            on_trace_ready=lambda prof: traces.append(prof.step_num),
        )
        p.start()
        seen = []
        for _ in range(8):
            seen.append((p.current_state, profiler._enabled))
            p.step()
        p.stop()
        C, R, REC, RAR = (
            ProfilerState.CLOSED, ProfilerState.READY,
            ProfilerState.RECORD, ProfilerState.RECORD_AND_RETURN,
        )
        assert [s for s, _ in seen] == [C, R, REC, RAR, C, R, REC, RAR]
        # recording is enabled exactly for RECORD/RECORD_AND_RETURN steps
        assert [e for _, e in seen] == [
            st in (REC, RAR) for st, _ in seen
        ]
        # each completed RECORD_AND_RETURN window handed a trace over
        assert traces == [4, 8]

    def test_scheduled_window_scopes_events(self):
        p = profiler.Profiler(
            timer_only=True, scheduler=make_scheduler(closed=2, record=1)
        )
        p.start()
        assert p.current_state == ProfilerState.CLOSED
        _train_loop(steps=1)
        assert profiler.events() == [] and profiler.span_events() == []
        p.step()  # -> CLOSED
        p.step()  # -> RECORD_AND_RETURN
        _train_loop(steps=1)
        assert any(e.name.startswith("op::") for e in profiler.events())
        p.stop()


class TestExporters:
    def test_jsonl_roundtrip(self, tmp_path):
        p = profiler.Profiler(timer_only=True)
        p.start()
        _train_loop(steps=2, span_per_step=True)
        p.stop()
        out = tmp_path / "trace.jsonl"
        p.export(str(out), format="jsonl")
        lines = [json.loads(l) for l in out.read_text().splitlines()]
        kinds = {l["type"] for l in lines}
        assert kinds == {"span", "event", "metrics"}
        flushes = [
            l for l in lines if l["type"] == "span" and l["name"] == "lazy_flush"
        ]
        assert flushes and all("cache" in f["attrs"] for f in flushes)
        metrics = [l for l in lines if l["type"] == "metrics"][-1]
        assert metrics["counters"].get("lazy_flushes", 0) > 0
        assert "memory" in metrics and "flags" in metrics

    def test_chrome_metadata_self_describing(self, tmp_path):
        p = profiler.Profiler(timer_only=True)
        p.start()
        _train_loop(steps=1)
        p.stop()
        out = tmp_path / "trace.json"
        p.export(str(out))
        trace = json.loads(out.read_text())
        meta = trace["metadata"]
        assert meta["counters"].get("lazy_flushes", 0) > 0
        assert "FLAGS_check_nan_inf" in meta["flags"]
        assert "peak_live_bytes" in meta["memory"]

    def test_prometheus_text_format(self):
        profiler.counter_inc("lazy_flushes", 0)  # key exists
        text = profiler.export_metrics(format="prometheus")
        assert "# TYPE paddle_tpu_lazy_flushes counter" in text
        assert "# TYPE paddle_tpu_memory_peak_live_bytes gauge" in text
        for line in text.splitlines():
            if not line.startswith("#"):
                name, val = line.rsplit(" ", 1)
                float(val)  # every sample parses as a number...
                if name.startswith(("paddle_tpu_lazy", "paddle_tpu_memory_")):
                    int(val)  # ...counters and memory gauges as integers
                    # (provider lines — serving SLO histograms, drift/rate
                    # gauges — are legitimately floats)

    def test_export_metrics_json_file(self, tmp_path):
        out = tmp_path / "metrics.json"
        text = profiler.export_metrics(str(out), format="json")
        doc = json.loads(out.read_text())
        assert doc == json.loads(text)
        assert "counters" in doc and "memory" in doc

    def test_unknown_formats_raise(self, tmp_path):
        p = profiler.Profiler(timer_only=True)
        with pytest.raises(ValueError):
            p.export(str(tmp_path / "x"), format="xml")
        with pytest.raises(ValueError):
            profiler.export_metrics(format="xml")


class TestFlightRecorder:
    def test_ring_observes_without_profiler(self):
        flight.clear()
        _train_loop(steps=1)
        names = [sp.name for sp in flight.recent_spans()]
        assert "lazy_flush" in names  # always-on, profiler closed

    def test_ring_is_bounded(self):
        flight.clear()
        for i in range(flight.capacity() + 50):
            with profiler.span("tick", i=i):
                pass
        spans = flight.recent_spans()
        assert len(spans) == flight.capacity()
        assert spans[-1].attrs["i"] == flight.capacity() + 49

    def test_manual_dump_contents(self, tmp_path, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_FLIGHT_DIR", str(tmp_path))
        _train_loop(steps=1)
        path = flight.dump("manual", extra={"note": "hello"})
        doc = json.loads(open(path).read())
        assert doc["reason"] == "manual" and doc["extra"]["note"] == "hello"
        assert any(s["name"] == "lazy_flush" for s in doc["recent_spans"])
        assert doc["counters"].get("lazy_flushes", 0) > 0
        assert "pending_graph" in doc and "flags" in doc
        assert flight.last_dump() == path
        assert profiler.counters().get("flight_dumps", 0) > 0

    def test_on_crash_guard_dumps(self, tmp_path, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_FLIGHT_DIR", str(tmp_path))
        with pytest.raises(RuntimeError):
            with flight.on_crash():
                _train_loop(steps=1)
                raise RuntimeError("train loop died")
        doc = json.loads(open(flight.last_dump()).read())
        assert doc["reason"] == "uncaught_exception"
        assert "train loop died" in doc["extra"]["exception"]


class TestOverheadGuard:
    def test_closed_profiler_does_not_tax_dispatch(self):
        """Tier-1 tripwire: the disabled path (profiler constructed but
        CLOSED, flight recorder running) must stay within noise of no
        profiler at all on a hot record+flush loop. bench.py measures the
        precise number; this guard uses interleaved min-of-N so CI noise
        can't fail it while a real regression (a per-op allocation, an
        unconditional census) still trips."""

        def loop(n):
            t = paddle.to_tensor(np.ones(64, np.float32))
            for _ in range(n):
                t = t + 1.0
                t.numpy()  # flush per iteration: span path included

        loop(30)  # warm the flush executable cache

        def timed():
            t0 = time.perf_counter()
            loop(50)
            return time.perf_counter() - t0

        absent = [timed() for _ in range(5)]
        p = profiler.Profiler(timer_only=True)
        p.start()
        p.stop()  # CLOSED again; session existed (flight recorder still on)
        closed = [timed() for _ in range(5)]
        assert min(closed) < min(absent) * 1.5, (absent, closed)
