"""Shared helpers for the serving test files (test_serving.py,
test_serving_resilience.py, test_serving_chaos.py): ONE tiny-GPT config,
one prompt generator, one engine-kwargs base — change the model here and
all three suites move together instead of silently diverging."""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu.models.gpt import GPTConfig, GPTForPretraining

# 64 usable blocks of 8 tokens, 8-wide decode, 128-token sequences — small
# enough that pool pressure is easy to provoke, big enough for real batching
ENGINE_KW = dict(block_size=8, num_blocks=64, max_batch=8, max_seq_len=128)


def tiny_gpt(seed=0):
    paddle.seed(seed)
    cfg = GPTConfig(
        vocab_size=211, hidden_size=32, num_layers=2, num_heads=2,
        max_position_embeddings=128, hidden_dropout=0.0,
        attention_dropout=0.0,
    )
    m = GPTForPretraining(cfg)
    m.eval()
    return m


def make_prompts(n, rng, lo=3, hi=24):
    return [rng.randint(0, 211, (int(rng.randint(lo, hi)),)).tolist()
            for _ in range(n)]
