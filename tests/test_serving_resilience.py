"""Serving resilience — deadlines, priorities, load shedding, supervision.

Pins the ISSUE-12 acceptance surface on the tier-1 (in-process, CPU-fast)
side: per-request deadlines shed expired/doomed work with structured
``DeadlineExceeded``; eviction is priority-then-youngest; the overload
policy fast-fails with ``Overloaded`` + a Retry-After hint instead of
unbounded queueing; ``close(timeout)`` on a wedged scheduler thread fails
outstanding handles instead of stranding clients (the PR-11 bugfix);
``ServingSupervisor`` detects an injected crash/wedge within the watchdog
deadline, restarts the engine, and requeued greedy streams complete
BIT-IDENTICAL to an uninterrupted run; ``health()``/``ready()`` +
``close(drain=True)`` support rolling restarts; and the whole layer is
inert when unconfigured (zero extra threads, the deadline sweep never
runs). The multi-round storm variants live in tests/test_serving_chaos.py
(``chaos`` marker).
"""
import threading
import time

import numpy as np
import pytest

from paddle_tpu import profiler
from paddle_tpu.fault import inject
from paddle_tpu.serving import (
    DeadlineExceeded, Engine, Overloaded, ServeError, ServingSupervisor,
)
from serving_util import ENGINE_KW, make_prompts as _prompts, tiny_gpt

_KW = dict(ENGINE_KW)


@pytest.fixture(scope="module")
def model():
    return tiny_gpt()


@pytest.fixture(autouse=True)
def _disarm():
    yield
    inject.disarm()


class TestDeadlines:
    def test_doomed_queued_request_rejected_at_admission(self, model):
        """A queued request that provably cannot meet its deadline (full
        token budget at the decode-step EMA) fails EARLY with a structured
        DeadlineExceeded — before any prefill is paid for it."""
        rng = np.random.RandomState(0)
        c0 = profiler.counters().get("serve_deadline_shed", 0)
        with Engine(model, **_KW) as eng:
            eng.generate(rng.randint(0, 211, (5,)).tolist(), max_new_tokens=4)
            # pin the EMA high so the doom verdict is deterministic on any
            # box: 11 steps at ~1s/step can never fit a 0.5s deadline
            eng._ema_step_s = 1.0
            h = eng.submit(rng.randint(0, 211, (5,)).tolist(),
                           max_new_tokens=10, deadline_s=0.5)
            with pytest.raises(DeadlineExceeded) as ei:
                h.result(timeout=60)
            assert ei.value.request_id == h.request_id
            assert eng.stats()["pages_used"] == 0
            # the engine is healthy and still serves deadline-free traffic
            out = eng.generate(rng.randint(0, 211, (4,)).tolist(),
                               max_new_tokens=3)
            assert len(out) == 7
        assert profiler.counters().get("serve_deadline_shed", 0) > c0

    def test_running_request_expires_mid_decode(self, model):
        """An admitted request whose real step time blows past the EMA-based
        admission estimate (injected serve.slow_step straggler) is shed at a
        step boundary once its deadline passes — bounded latency, blocks
        freed, the stream fails structurally instead of running to the end
        of its budget."""
        rng = np.random.RandomState(1)
        c0 = profiler.counters().get("serve_deadline_expired", 0)
        inject.arm("serve.slow_step:from=1,ms=60")
        with Engine(model, **_KW) as eng:
            t0 = time.monotonic()
            h = eng.submit(rng.randint(0, 211, (5,)).tolist(),
                           max_new_tokens=100, deadline_s=0.4)
            with pytest.raises(DeadlineExceeded, match="expired"):
                h.result(timeout=60)
            # shed near the deadline, nowhere near the 100-step runtime (>6s)
            assert time.monotonic() - t0 < 4.0
            assert eng.stats()["pages_used"] == 0
        assert profiler.counters().get("serve_deadline_expired", 0) > c0

    def test_deadline_validation(self, model):
        with Engine(model, **_KW) as eng:
            with pytest.raises(ValueError, match="deadline_s"):
                eng.submit([1, 2], max_new_tokens=2, deadline_s=0.0)

    def test_deadline_met_request_unaffected(self, model):
        rng = np.random.RandomState(2)
        p = rng.randint(0, 211, (7,)).tolist()
        with Engine(model, **_KW) as eng:
            plain = eng.generate(p, max_new_tokens=5)
            timed = eng.submit(p, max_new_tokens=5,
                               deadline_s=300.0).result(timeout=300)
        assert timed == plain


class TestPriorities:
    def test_eviction_is_priority_then_youngest(self, model):
        """Under pool pressure the LOWEST-priority peer is evicted first —
        the high-priority stream is never preempted (observed through the
        evict spans' request ids)."""
        rng = np.random.RandomState(3)
        with profiler.Profiler():
            with Engine(model, block_size=8, num_blocks=10, max_batch=4,
                        max_seq_len=72) as eng:
                hi = eng.submit(rng.randint(0, 211, (8,)).tolist(),
                                max_new_tokens=24, priority=5)
                los = [eng.submit(rng.randint(0, 211, (8,)).tolist(),
                                  max_new_tokens=24) for _ in range(3)]
                outs = [h.result(timeout=600) for h in [hi] + los]
            evicted = {s["attrs"]["request"]
                       for s in profiler.span_events() if s["name"] == "evict"}
        assert all(len(o) == 32 for o in outs)  # everyone still completes
        assert evicted, "pool pressure never forced an eviction"
        assert hi.request_id not in evicted

    def test_admission_prefers_priority(self, model):
        """With the engine saturated, a high-priority latecomer is admitted
        before earlier-queued low-priority requests."""
        rng = np.random.RandomState(4)
        with Engine(model, block_size=8, num_blocks=64, max_batch=1,
                    max_seq_len=128) as eng:
            hog = eng.submit(rng.randint(0, 211, (4,)).tolist(),
                             max_new_tokens=60)
            lo = eng.submit(rng.randint(0, 211, (4,)).tolist(),
                            max_new_tokens=3)
            hi = eng.submit(rng.randint(0, 211, (4,)).tolist(),
                            max_new_tokens=3, priority=9)
            hi.result(timeout=300)
            assert not lo.done  # hi jumped the (still-hogged) queue
            hog.result(timeout=600)
            lo.result(timeout=600)


class TestLoadShedding:
    def test_overload_fast_fails_with_retry_hint(self, model):
        rng = np.random.RandomState(5)
        c0 = profiler.counters().get("serve_shed", 0)
        with Engine(model, block_size=8, num_blocks=64, max_batch=1,
                    max_seq_len=128, max_queue=2, shed=True) as eng:
            hog = eng.submit(rng.randint(0, 211, (4,)).tolist(),
                             max_new_tokens=80)
            queued = []
            shed = None
            t0 = time.monotonic()
            for _ in range(50):
                try:
                    queued.append(eng.submit(
                        rng.randint(0, 211, (4,)).tolist(), max_new_tokens=3))
                except Overloaded as e:
                    shed = e
                    break
            # fast-fail: the shed submit returned immediately, it did not
            # wait out the hog's 80 decode steps
            assert time.monotonic() - t0 < 5.0
            assert shed is not None and shed.retry_after_s > 0.0
            assert not eng.ready()  # readiness reflects the full queue
            # the engine is healthy: everything admitted still completes
            hog.result(timeout=600)
            for h in queued:
                h.result(timeout=600)
            assert eng.stats()["pages_used"] == 0
            assert eng.ready()
        assert profiler.counters().get("serve_shed", 0) > c0

    def test_unbounded_queue_without_shed_flag(self, model):
        """shed=False (the default) keeps PR-11 semantics: the queue grows
        and everything completes."""
        rng = np.random.RandomState(6)
        with Engine(model, block_size=8, num_blocks=64, max_batch=1,
                    max_seq_len=128, max_queue=2) as eng:
            hs = [eng.submit(rng.randint(0, 211, (4,)).tolist(),
                             max_new_tokens=3) for _ in range(8)]
            for h in hs:
                h.result(timeout=600)


class TestWedgedClose:
    def test_close_timeout_on_wedged_thread_fails_handles(self, model):
        """The PR-11 bug: close(timeout) whose join times out returned with
        pending handles never failed — clients blocked forever in result().
        Now a timed-out join marks the engine broken and fails every
        outstanding handle with ServeError."""
        rng = np.random.RandomState(7)
        c0 = profiler.counters().get("serve_wedged_close", 0)
        inject.arm("serve.wedge:at=1,ms=20000")  # wedge on the first step
        eng = Engine(model, **_KW)
        h = eng.submit(rng.randint(0, 211, (5,)).tolist(), max_new_tokens=50)
        deadline = time.monotonic() + 30
        while not inject.fired_counts().get("serve.wedge") \
                and time.monotonic() < deadline:
            time.sleep(0.01)
        assert inject.fired_counts().get("serve.wedge") == 1
        t0 = time.monotonic()
        eng.close(timeout=0.5)
        assert time.monotonic() - t0 < 5.0  # close() itself returned promptly
        with pytest.raises(ServeError):
            h.result(timeout=5)  # structured failure, NOT a hang
        assert not eng.health()["ok"]
        assert profiler.counters().get("serve_wedged_close", 0) > c0


class TestSupervisor:
    def test_crash_mid_decode_restart_bit_identical(self, model):
        """THE acceptance pin: an injected engine-loop crash mid-decode is
        detected, the engine restarts over the same config, queued and
        mid-decode sequences requeue through the accumulated-tokens
        re-prefill path, and every greedy stream completes bit-identical to
        an uninterrupted run."""
        rng = np.random.RandomState(8)
        prompts = _prompts(6, rng)
        with Engine(model, **_KW) as eng:
            baseline = [eng.submit(p, max_new_tokens=8).result(timeout=300)
                        for p in prompts]
        c0 = profiler.counters()
        inject.arm("serve.crash:at=4")  # 4th scheduler step: mid-decode
        with ServingSupervisor(model, watchdog_s=4.0, **_KW) as sup:
            hs = [sup.submit(p, max_new_tokens=8) for p in prompts]
            outs = [h.result(timeout=600) for h in hs]
            assert sup.restarts == 1
            assert sup.health()["ok"] and sup.ready()
        assert outs == baseline
        c1 = profiler.counters()
        assert c1.get("serve_crash_detected", 0) > c0.get("serve_crash_detected", 0)
        assert c1.get("serve_restarts", 0) > c0.get("serve_restarts", 0)
        assert c1.get("serve_requeued", 0) > c0.get("serve_requeued", 0)

    def test_crash_recovery_keeps_stream_contiguous(self, model):
        """A streamed request interrupted by the crash keeps yielding: the
        relay stitches the continuation's tokens onto the original handle,
        and the full stream equals the uninterrupted generation."""
        rng = np.random.RandomState(9)
        p = rng.randint(0, 211, (6,)).tolist()
        with Engine(model, **_KW) as eng:
            ref = eng.submit(p, max_new_tokens=10).result(timeout=300)
        inject.arm("serve.crash:at=5")
        with ServingSupervisor(model, watchdog_s=4.0, **_KW) as sup:
            h = sup.submit(p, max_new_tokens=10, stream=True)
            got = list(h)
            assert sup.restarts == 1
        assert p + got == ref

    def test_wedge_fails_inflight_structurally_and_restarts(self, model):
        """A wedged scheduler thread is detected within the watchdog
        deadline; in-flight handles fail with a structured ServeError
        (never hang — the abandoned thread may still own them), and the
        restarted engine serves new traffic."""
        rng = np.random.RandomState(10)
        c0 = profiler.counters().get("serve_wedge_detected", 0)
        with ServingSupervisor(model, watchdog_s=3.0, **_KW) as sup:
            # warm first so compile pauses can't imitate a wedge; at=2 puts
            # the wedge AFTER the admitting step, so the request is
            # in-flight (a queued request would be requeued, not failed)
            sup.generate(rng.randint(0, 211, (5,)).tolist(), max_new_tokens=3)
            inject.arm("serve.wedge:at=2,ms=60000")
            t0 = time.monotonic()
            h = sup.submit(rng.randint(0, 211, (5,)).tolist(),
                           max_new_tokens=50)
            with pytest.raises(ServeError, match="wedged"):
                h.result(timeout=30)
            # detection within the watchdog deadline (+ scheduling slack)
            assert time.monotonic() - t0 < 3.0 + 2.0
            inject.disarm()
            assert sup.restarts == 1
            out = sup.generate(rng.randint(0, 211, (4,)).tolist(),
                               max_new_tokens=3)
            assert len(out) == 7
        assert profiler.counters().get("serve_wedge_detected", 0) > c0

    def test_requeue_bypasses_shed_policy(self, model):
        """Recovery must not shed work the engine already accepted: with
        shed armed and a queue cap smaller than the harvested set, every
        pre-crash request still completes bit-identically instead of
        failing Overloaded mid-restart."""
        rng = np.random.RandomState(18)
        prompts = _prompts(4, rng)
        kw = dict(_KW, max_batch=2, max_queue=2, shed=True)
        with Engine(model, **kw) as eng:
            baseline = [eng.submit(p, max_new_tokens=10).result(timeout=300)
                        for p in prompts]
        with ServingSupervisor(model, watchdog_s=4.0, **kw) as sup:
            first = [sup.submit(p, max_new_tokens=10) for p in prompts[:2]]
            deadline = time.monotonic() + 30
            while sup.stats()["queue_depth"] and time.monotonic() < deadline:
                time.sleep(0.005)  # both admitted: queue has room again
            rest = [sup.submit(p, max_new_tokens=10) for p in prompts[2:]]
            # 2 running + 2 queued accepted; the crash harvests all four
            # into a fresh engine whose cap (2) is SMALLER than the set
            inject.arm("serve.crash:at=1")
            outs = [h.result(timeout=600) for h in first + rest]
            assert sup.restarts == 1
        assert outs == baseline

    def test_max_restarts_exhaustion_breaks_supervisor(self, model):
        rng = np.random.RandomState(11)
        inject.arm("serve.crash:from=1")  # every step crashes
        with ServingSupervisor(model, watchdog_s=3.0, max_restarts=1,
                               **_KW) as sup:
            h = sup.submit(rng.randint(0, 211, (5,)).tolist(),
                           max_new_tokens=20)
            with pytest.raises(ServeError):
                h.result(timeout=60)
            deadline = time.monotonic() + 30
            while sup.health()["supervisor_ok"] \
                    and time.monotonic() < deadline:
                time.sleep(0.05)
            assert not sup.health()["supervisor_ok"]
            assert not sup.ready()
            inject.disarm()
            with pytest.raises(ServeError, match="broken"):
                sup.submit(rng.randint(0, 211, (4,)).tolist(),
                           max_new_tokens=2)


class TestHealthReadyDrain:
    def test_health_and_ready_probes(self, model):
        with Engine(model, **_KW) as eng:
            h = eng.health()
            assert h["ok"] and h["thread_alive"] and h["broken"] is None
            assert h["beat_age_s"] < 30.0
            assert eng.ready()
        assert not eng.health()["ok"]
        assert not eng.ready()

    def test_drain_completes_outstanding_then_stops(self, model):
        rng = np.random.RandomState(12)
        prompts = _prompts(5, rng)
        eng = Engine(model, **_KW)
        hs = [eng.submit(p, max_new_tokens=6) for p in prompts]
        eng.close(drain=True, timeout=300)
        outs = [h.result(timeout=5) for h in hs]  # completed, NOT failed
        for p, out in zip(prompts, outs):
            assert out[:len(p)] == p and len(out) == len(p) + 6
        with pytest.raises(ServeError):
            eng.submit([1, 2], max_new_tokens=2)

    def test_submit_during_drain_rejected(self, model):
        rng = np.random.RandomState(13)
        eng = Engine(model, **_KW)
        hog = eng.submit(rng.randint(0, 211, (4,)).tolist(),
                         max_new_tokens=40)
        closer = threading.Thread(
            target=lambda: eng.close(drain=True, timeout=300), daemon=True)
        closer.start()
        deadline = time.monotonic() + 30
        rejected = None
        while time.monotonic() < deadline:
            try:
                # raced before the drain flag landed: keep probing
                eng.submit(rng.randint(0, 211, (4,)).tolist(),
                           max_new_tokens=2).result(timeout=60)
            except ServeError as e:
                rejected = e
                break
        assert rejected is not None and not eng.ready()
        hog.result(timeout=600)  # pre-drain work still completed
        closer.join(timeout=300)

    def test_supervisor_drain_close(self, model):
        rng = np.random.RandomState(14)
        sup = ServingSupervisor(model, watchdog_s=5.0, **_KW)
        hs = [sup.submit(p, max_new_tokens=5) for p in _prompts(3, rng)]
        sup.close(drain=True, timeout=300)
        for h in hs:
            assert len(h.result(timeout=5)) >= 6


class TestWatchdogIntegration:
    def test_supervised_engine_publishes_serving_phase_records(
            self, model, tmp_path):
        """A supervised engine's scheduler thread rides the PR 8 progress
        table: `serve.step` phase records land under the rank's `units`
        sub-record (watchdog.publish(unit=...)) without clobbering the
        training step/phase — so cross-rank post-mortems show serving
        progress next to training progress."""
        from paddle_tpu.distributed import watchdog

        rng = np.random.RandomState(16)
        watchdog.configure(rank=0, world_size=1, store=None,
                           progress_dir=str(tmp_path))
        try:
            watchdog.publish(step=41, phase="train", force=True)
            train_ts = watchdog.local_progress()["ts"]
            with ServingSupervisor(model, watchdog_s=5.0, **_KW) as sup:
                sup.generate(rng.randint(0, 211, (5,)).tolist(),
                             max_new_tokens=8)
                deadline = time.monotonic() + 30
                units = {}
                while not units and time.monotonic() < deadline:
                    units = watchdog.progress_table().get(0, {}).get("units", {})
                    time.sleep(0.02)
            serving = [v for k, v in units.items() if k.startswith("serving_")]
            assert serving and serving[0]["phase"] == "serve.step"
            assert serving[0]["step"] >= 0
            # the training record survived untouched — INCLUDING its
            # timestamp: a live serving engine must not keep a hung training
            # loop looking fresh (suspect() ranks stalest-ts on step ties)
            rec = watchdog.progress_table()[0]
            assert rec["step"] == 41 and rec["phase"] == "train"
            assert rec["ts"] == train_ts
            assert serving[0]["ts"] >= train_ts
            # the closed engine's unit was pruned AND written through — no
            # phantom serving unit rides later dumps/heartbeats (the close
            # was the last publisher, so only a write-through can clear it)
            stale = [k for k in watchdog.progress_table()[0].get("units", {})
                     if k.startswith("serving_")]
            assert not stale, f"stale units persisted: {stale}"
        finally:
            watchdog.reset()


class TestInertTripwire:
    def test_unconfigured_path_adds_zero_threads_and_zero_sweeps(
            self, model, monkeypatch):
        """The resilience layer must cost NOTHING when unconfigured: no
        deadline sweep (monkeypatched to explode), no priority scan, no
        watchdog publish (monkeypatched to explode), and the only thread an
        engine adds is its own PR-11 scheduler thread — no supervisor
        monitor, no relays."""
        import paddle_tpu.serving.engine as E
        from paddle_tpu.distributed import watchdog

        def boom(*a, **k):
            raise AssertionError(
                "resilience machinery ran on the unconfigured path")

        monkeypatch.setattr(E.Engine, "_shed_sweep", boom)
        monkeypatch.setattr(watchdog, "publish", boom)
        rng = np.random.RandomState(15)
        before = {t.ident for t in threading.enumerate()}
        with Engine(model, **_KW) as eng:
            hs = [eng.submit(p, max_new_tokens=5) for p in _prompts(4, rng)]
            [h.result(timeout=300) for h in hs]
            new = [t for t in threading.enumerate() if t.ident not in before]
            serve_threads = [t for t in new
                             if t.name.startswith(("serving", "serve-relay",
                                                   "paddle-tpu-watchdog"))]
            assert [t.name for t in serve_threads] == [eng._provider]
            assert eng._deadline_seen is False and eng._has_prio is False
            assert eng._supervised is False and eng._watchdog is None
