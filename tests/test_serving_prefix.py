"""Prefix-cache KV sharing + speculative decoding (PR 16).

Pins the two serving throughput multipliers end to end: PagePool refcount /
share / copy-on-write invariants, longest-prefix admission matching with
tail-only prefill bit-identical to the full pass, cache eviction and
pinning under pool pressure, preemption of a sharer leaving its peer
intact, speculative greedy decode (n-gram and model drafters) bit-identical
to plain decode for GPT and Llama/GQA, the per-decode-bucket gather-width
satellite, and the inert tripwire: with both flags off every refcount /
drafter / tail-prefill path is monkeypatch-exploded and never called while
scheduler behavior stays byte-identical to PR 11/12.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import profiler
from paddle_tpu.serving import Engine
from paddle_tpu.serving.pool import PagePool
from serving_util import ENGINE_KW, make_prompts, tiny_gpt


@pytest.fixture(scope="module")
def model():
    return tiny_gpt()


def _counters_delta(c0):
    c1 = profiler.counters()
    return {k: c1.get(k, 0) - c0.get(k, 0) for k in set(c0) | set(c1)}


def _shared_prompts(rng, n, shared_len=40, lo=3, hi=10):
    shared = rng.randint(0, 211, (shared_len,)).tolist()
    return [shared + rng.randint(0, 211, (int(rng.randint(lo, hi)),)).tolist()
            for _ in range(n)]


# ---------------------------------------------------------------------------
# PagePool refcounting
# ---------------------------------------------------------------------------

class TestPoolRefcounts:
    def test_share_free_lifecycle(self):
        pool = PagePool(8)
        ids = pool.alloc(3)
        assert [pool.refcount(b) for b in ids] == [1, 1, 1]
        pool.share(ids)
        assert [pool.refcount(b) for b in ids] == [2, 2, 2]
        pool.free(ids)  # first reference drops, blocks stay owned
        assert pool.used_blocks == 3 and pool.free_blocks == 4
        pool.check()
        pool.free(ids)  # last reference: back to circulation
        assert pool.used_blocks == 0 and pool.free_blocks == 7
        pool.check()

    def test_free_past_last_reference_raises(self):
        pool = PagePool(4)
        ids = pool.alloc(1)
        pool.free(ids)
        with pytest.raises(RuntimeError, match="double-free"):
            pool.free(ids)

    def test_share_unowned_raises(self):
        pool = PagePool(4)
        with pytest.raises(RuntimeError, match="share of unowned"):
            pool.share([2])

    def test_park_never_takes_a_referenced_block(self):
        """PR 14's OOM pool-shrink draws ONLY from the free list, so a block
        with live references can structurally never be parked — even when
        asked for more than is free."""
        pool = PagePool(8)
        ids = pool.alloc(4)
        pool.share(ids[:2])
        assert pool.park(100) == 2  # free list minus the 1-block headroom
        assert all(pool.refcount(b) >= 1 for b in ids)
        pool.check()
        pool.unpark()
        pool.free(ids)
        pool.free(ids[:2])
        pool.check()
        assert pool.free_blocks == 7

    def test_check_catches_refcount_divergence(self):
        pool = PagePool(4)
        pool.alloc(1)
        pool._ref.clear()  # simulate corruption
        with pytest.raises(RuntimeError, match="refcount"):
            pool.check()


# ---------------------------------------------------------------------------
# Prefix cache
# ---------------------------------------------------------------------------

class TestPrefixCache:
    def test_cache_on_bit_identical_and_hits(self, model):
        rng = np.random.RandomState(0)
        prompts = _shared_prompts(rng, 6)
        with Engine(model, **ENGINE_KW) as eng:
            base = [eng.submit(p, max_new_tokens=8).result(timeout=600)
                    for p in prompts]
        c0 = profiler.counters()
        with Engine(model, prefix_cache=True, **ENGINE_KW) as eng:
            out = [eng.submit(p, max_new_tokens=8).result(timeout=600)
                   for p in prompts]
            # second wave hits the populated cache, batched this time
            hs = [eng.submit(p, max_new_tokens=8) for p in prompts]
            out2 = [h.result(timeout=600) for h in hs]
            st = eng.stats()
            assert st["pages_cached"] > 0
            # drained: every non-cache block is back (no leak under sharing)
            assert st["pages_used"] == st["pages_cached"]
            eng._pool.check()
        assert out == base and out2 == base
        d = _counters_delta(c0)
        assert d["serve_prefix_hits"] >= 5
        assert d["serve_prefix_blocks_shared"] >= 5 * (40 // 8)

    def test_cache_survives_retirement_across_waves(self, model):
        """The index holds its own reference: after every stream drains the
        shared prompt's blocks stay resident, and a later wave re-shares
        them instead of re-prefilling."""
        rng = np.random.RandomState(1)
        prompts = _shared_prompts(rng, 4, shared_len=32)
        with Engine(model, prefix_cache=True, **ENGINE_KW) as eng:
            [eng.submit(p, max_new_tokens=4).result(timeout=600)
             for p in prompts]
            cached = eng.stats()["pages_cached"]
            assert cached >= 32 // 8
            c0 = profiler.counters()
            [eng.submit(p, max_new_tokens=4).result(timeout=600)
             for p in prompts]
            d = _counters_delta(c0)
            assert d["serve_prefix_hits"] == 4
            assert d["serve_prefix_misses"] == 0

    def test_eviction_under_pool_pressure_respects_pins(self, model):
        """A cache-heavy pool must yield to live traffic: admission evicts
        unpinned LRU entries instead of declaring backpressure, conservation
        holds throughout, and pinned (shared) blocks survive."""
        rng = np.random.RandomState(2)
        # small pool: cacheable prompts + live traffic cannot both fit
        kw = dict(ENGINE_KW, num_blocks=24)
        with Engine(model, prefix_cache=True, **kw) as eng:
            for _ in range(4):
                p = rng.randint(0, 211, (32,)).tolist()
                eng.submit(p, max_new_tokens=4).result(timeout=600)
            filled = eng.stats()["pages_cached"]
            assert filled > 0
            c0 = profiler.counters()
            outs = [eng.submit(p, max_new_tokens=6)
                    for p in make_prompts(6, rng, lo=16, hi=24)]
            for h in outs:
                assert len(h.result(timeout=600)) > 0
            assert _counters_delta(c0)["serve_prefix_evicted"] > 0
            eng._pool.check()
            st = eng.stats()
            assert st["pages_used"] == st["pages_cached"]

    def test_preempting_a_sharer_leaves_peer_bit_intact(self, model):
        """Two streams share a cached prefix; pool pressure preempts one.
        The eviction decrements the shared blocks (never releases them from
        under the peer), the victim re-prefills, and BOTH outputs match the
        pressure-free run."""
        rng = np.random.RandomState(3)
        shared = rng.randint(0, 211, (40,)).tolist()
        prompts = [shared + rng.randint(0, 211, (6,)).tolist()
                   for _ in range(4)]
        with Engine(model, **ENGINE_KW) as eng:
            base = [eng.submit(p, max_new_tokens=24).result(timeout=600)
                    for p in prompts]
        # a pool too small for all four streams + cache: growth preempts
        # (4 streams need ~4 private blocks each past the 5 shared ones)
        kw = dict(ENGINE_KW, num_blocks=20)
        c0 = profiler.counters()
        with Engine(model, prefix_cache=True, **kw) as eng:
            hs = [eng.submit(p, max_new_tokens=24) for p in prompts]
            outs = [h.result(timeout=600) for h in hs]
            eng._pool.check()
            st = eng.stats()
            assert st["pages_used"] == st["pages_cached"]
        assert outs == base
        assert _counters_delta(c0)["serve_preempted"] > 0

    def test_sixty_four_stream_drain_no_leak(self, model):
        """The PR 11 64-stream soak under sharing: after the drain the only
        resident blocks are the index's own references — nothing leaked,
        nothing double-freed, conservation holds."""
        rng = np.random.RandomState(4)
        kw = dict(ENGINE_KW, num_blocks=128, max_batch=16)
        prompts = _shared_prompts(rng, 64, shared_len=24, lo=3, hi=12)
        with Engine(model, prefix_cache=True, **kw) as eng:
            hs = [eng.submit(p, max_new_tokens=6) for p in prompts]
            for h in hs:
                assert len(h.result(timeout=600)) > 0
            eng._pool.check()
            st = eng.stats()
            assert st["pages_used"] == st["pages_cached"] > 0

    def test_cow_guard_copies_a_shared_write_block(self, model):
        """Defense-in-depth copy-on-write: force a refcount > 1 onto a
        block in a live sequence's write range and step — the guard must
        copy it to a private block, leave the shared original bit-intact
        for its other holder, and count the copy."""
        rng = np.random.RandomState(5)
        with Engine(model, prefix_cache=True, **ENGINE_KW) as eng:
            h = eng.submit(rng.randint(0, 211, (9,)).tolist(),
                           max_new_tokens=16, stream=True)
            it = iter(h)
            next(it)  # sequence is admitted and decoding
            # engine-thread-unsafe poke is fine: the scheduler only touches
            # _running inside _step, and we only read + share
            import time as _t
            for _ in range(200):
                if eng._running:
                    break
                _t.sleep(0.01)
            seq = eng._running[0]
            wb = seq.blocks[seq.pos // eng.config.block_size]
            eng._pool.share([wb])  # simulate an aggressive sharer
            c0 = profiler.counters()
            out = h.result(timeout=600)
            assert len(out) == 9 + 16
            assert _counters_delta(c0)["serve_cow_copies"] >= 1
            assert eng._pool.refcount(wb) == 1  # our extra ref survives
            eng._pool.free([wb])
            eng._pool.check()


# ---------------------------------------------------------------------------
# Speculative decoding
# ---------------------------------------------------------------------------

class TestSpeculative:
    def test_ngram_greedy_bit_identical_batched_and_sequential(self, model):
        rng = np.random.RandomState(10)
        prompts = make_prompts(8, rng)
        with Engine(model, **ENGINE_KW) as eng:
            base = [eng.submit(p, max_new_tokens=10).result(timeout=600)
                    for p in prompts]
        c0 = profiler.counters()
        with Engine(model, spec_k=3, **ENGINE_KW) as eng:
            seq = [eng.submit(p, max_new_tokens=10).result(timeout=600)
                   for p in prompts]
            hs = [eng.submit(p, max_new_tokens=10) for p in prompts]
            bat = [h.result(timeout=600) for h in hs]
            assert eng.stats()["pages_used"] == 0
        assert seq == base and bat == base
        d = _counters_delta(c0)
        assert d["serve_draft_proposed"] > 0
        assert 0 < d["serve_draft_accepted"] <= d["serve_draft_proposed"]

    def test_model_drafter_bit_identical(self, model):
        from paddle_tpu.models.gpt import GPTConfig, GPTForPretraining

        paddle.seed(7)
        dcfg = GPTConfig(vocab_size=211, hidden_size=16, num_layers=1,
                         num_heads=2, max_position_embeddings=128,
                         hidden_dropout=0.0, attention_dropout=0.0)
        drafter = GPTForPretraining(dcfg)
        drafter.eval()
        rng = np.random.RandomState(11)
        prompts = make_prompts(6, rng)
        with Engine(model, **ENGINE_KW) as eng:
            base = [eng.submit(p, max_new_tokens=10).result(timeout=600)
                    for p in prompts]
        with Engine(model, spec_k=4, drafter=drafter, draft_window=32,
                    **ENGINE_KW) as eng:
            hs = [eng.submit(p, max_new_tokens=10) for p in prompts]
            out = [h.result(timeout=600) for h in hs]
            assert eng.stats()["pages_used"] == 0
        assert out == base

    def test_llama_gqa_spec_and_prefix_bit_identical(self):
        from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM

        paddle.seed(3)
        cfg = LlamaConfig(vocab_size=193, hidden_size=32, num_layers=2,
                          num_heads=4, num_kv_heads=2, intermediate_size=64,
                          max_position_embeddings=128)
        m = LlamaForCausalLM(cfg)
        m.eval()
        rng = np.random.RandomState(12)
        prompts = [rng.randint(0, 193, (int(rng.randint(3, 20)),)).tolist()
                   for _ in range(6)]
        kw = dict(block_size=8, num_blocks=64, max_batch=8, max_seq_len=128)
        with Engine(m, **kw) as eng:
            base = [eng.submit(p, max_new_tokens=10).result(timeout=600)
                    for p in prompts]
        with Engine(m, spec_k=3, prefix_cache=True, **kw) as eng:
            hs = [eng.submit(p, max_new_tokens=10) for p in prompts]
            out = [h.result(timeout=600) for h in hs]
            hs2 = [eng.submit(p, max_new_tokens=10) for p in prompts]
            out2 = [h.result(timeout=600) for h in hs2]
        assert out == base and out2 == base

    def test_eos_and_budget_respected_mid_acceptance(self, model):
        """A burst of accepted drafts must stop emitting at eos or the
        token budget exactly like plain decode — the output contract
        (prompt + <= max_new, ending at eos when hit) is unchanged."""
        rng = np.random.RandomState(13)
        prompts = make_prompts(8, rng)
        for eos in (7, None):
            with Engine(model, **ENGINE_KW) as eng:
                base = [eng.submit(p, max_new_tokens=12,
                                   eos_token_id=eos).result(timeout=600)
                        for p in prompts]
            with Engine(model, spec_k=4, **ENGINE_KW) as eng:
                out = [eng.submit(p, max_new_tokens=12,
                                  eos_token_id=eos).result(timeout=600)
                       for p in prompts]
            assert out == base

    def test_sampling_rows_still_one_token_per_step(self, model):
        """temperature > 0 rows accept no drafts: generation completes with
        exactly prompt + max_new tokens and the pool conserves."""
        rng = np.random.RandomState(14)
        with Engine(model, spec_k=3, seed=5, **ENGINE_KW) as eng:
            p = rng.randint(0, 211, (9,)).tolist()
            out = eng.submit(p, max_new_tokens=8,
                             temperature=0.8).result(timeout=600)
            assert len(out) == 9 + 8
            assert eng.stats()["pages_used"] == 0


# ---------------------------------------------------------------------------
# Per-B-bucket decode gather width (satellite)
# ---------------------------------------------------------------------------

class TestGatherWidth:
    def test_width_tracks_high_water_and_compiles_stay_bounded(self, model):
        rng = np.random.RandomState(20)
        prompts = make_prompts(8, rng)
        with Engine(model, **ENGINE_KW) as eng:
            [eng.submit(p, max_new_tokens=6).result(timeout=600)
             for p in prompts[:4]]
            # short sequences: the gather width sits well under _max_blocks
            assert all(mb <= eng._max_blocks
                       for mb in eng._decode_mb.values())
            assert any(mb < eng._max_blocks
                       for mb in eng._decode_mb.values())
            compiles = eng.stats()["compiles"]
            # warm wave at the same lengths: no width change, no recompiles
            hs = [eng.submit(p, max_new_tokens=6) for p in prompts]
            [h.result(timeout=600) for h in hs]
            # decode entries stay <= one per bucket even after upgrades
            decode_keys = [k for k in eng._fns if k[0] == "decode"]
            assert len(decode_keys) == len({k[1] for k in decode_keys})
            assert eng.stats()["compiles"] >= compiles
            dup = [k for k in eng._fns if k[0] == "decode"]
            assert len(dup) <= len(eng.config.decode_buckets)

    def test_long_sequence_upgrades_width_bit_identically(self, model):
        """Crossing a width boundary mid-stream (the gather widens, the old
        executable is replaced) must not change a single token."""
        rng = np.random.RandomState(21)
        p = rng.randint(0, 211, (10,)).tolist()
        with Engine(model, **ENGINE_KW) as eng:
            base = eng.submit(p, max_new_tokens=100).result(timeout=600)
            assert len(eng._decode_mb) > 0
        with Engine(model, **ENGINE_KW) as eng:
            # warm the narrow width first so the upgrade happens mid-flight
            eng.submit(p[:4], max_new_tokens=4).result(timeout=600)
            out = eng.submit(p, max_new_tokens=100).result(timeout=600)
        assert out == base


# ---------------------------------------------------------------------------
# Inert tripwire: both flags off => the new paths are never touched
# ---------------------------------------------------------------------------

class TestInertTripwire:
    def test_unconfigured_engine_never_touches_new_paths(self, model, monkeypatch):
        """Default flags (prefix_cache off, spec_k 0): every refcount /
        prefix / drafter / speculative entry point is replaced with a bomb,
        traffic is served, and outputs stay byte-identical to PR 11/12."""
        rng = np.random.RandomState(30)
        prompts = make_prompts(6, rng)
        with Engine(model, **ENGINE_KW) as eng:
            base = [eng.submit(p, max_new_tokens=8).result(timeout=600)
                    for p in prompts]

        def boom(*a, **k):
            raise AssertionError("inert path reached while unconfigured")

        from paddle_tpu.serving import engine as E

        monkeypatch.setattr(PagePool, "share", boom)
        monkeypatch.setattr(E._PrefixCache, "match", boom)
        monkeypatch.setattr(E._PrefixCache, "insert", boom)
        monkeypatch.setattr(E._PrefixCache, "evict", boom)
        monkeypatch.setattr(E.Engine, "_decode_spec", boom)
        monkeypatch.setattr(E.Engine, "_propose", boom)
        monkeypatch.setattr(E.Engine, "_cow_guard", boom)
        monkeypatch.setattr(E.Engine, "_match_prefix", boom)
        monkeypatch.setattr(E, "_ngram_propose", boom)
        with Engine(model, **ENGINE_KW) as eng:
            assert eng._prefix is None and eng._spec_k == 0
            assert eng._drafter is None
            hs = [eng.submit(p, max_new_tokens=8) for p in prompts]
            out = [h.result(timeout=600) for h in hs]
            assert eng.stats()["pages_used"] == 0
            assert eng.stats()["pages_cached"] == 0
        assert out == base
