"""Inference deployment path — Config/Predictor over AOT artifacts.

Reference surface: paddle.inference (analysis_predictor.h:87 AnalysisPredictor,
paddle_inference_api.h Config/Predictor/ZeroCopyTensor). Tests cover
save_inference_model → create_predictor → named-handle run, the list API,
clone() thread-safety, dynamic batch, and error paths.
"""
import os
import tempfile
import threading

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.inference import Config, Predictor, create_predictor
from paddle_tpu.static import InputSpec


@pytest.fixture(scope="module")
def saved_model():
    paddle.seed(7)
    lin = paddle.nn.Sequential(
        paddle.nn.Linear(16, 32), paddle.nn.ReLU(), paddle.nn.Linear(32, 4)
    )
    lin.eval()
    d = tempfile.mkdtemp()
    prefix = os.path.join(d, "model")
    paddle.static.save_inference_model(
        prefix, [InputSpec([None, 16], "float32", name="feat")], lin
    )
    return prefix, lin


def test_config_surface(saved_model):
    prefix, _ = saved_model
    cfg = Config(prefix + ".pdmodel")
    assert cfg.model_dir() == prefix
    assert cfg.prog_file().endswith(".pdmodel")
    cfg.enable_memory_optim()
    cfg.switch_ir_optim(True)
    cfg.disable_gpu()
    assert not cfg.use_gpu()
    assert "Config(" in cfg.summary()


def test_predictor_handles(saved_model):
    prefix, lin = saved_model
    pred = create_predictor(Config(prefix))
    assert pred.get_input_names() == ["feat"]
    assert pred.get_output_names() == ["output_0"]
    x = np.random.randn(3, 16).astype(np.float32)
    h = pred.get_input_handle("feat")
    h.copy_from_cpu(x)
    assert pred.run() is True
    out = pred.get_output_handle("output_0").copy_to_cpu()
    want = np.asarray(lin(paddle.to_tensor(x))._data)
    np.testing.assert_allclose(out, want, atol=1e-5)
    assert pred.get_output_handle("output_0").shape() == [3, 4]


def test_predictor_list_api_dynamic_batch(saved_model):
    prefix, lin = saved_model
    pred = create_predictor(Config(prefix))
    for bs in (1, 6):
        x = np.random.randn(bs, 16).astype(np.float32)
        outs = pred.run([x])
        want = np.asarray(lin(paddle.to_tensor(x))._data)
        np.testing.assert_allclose(outs[0], want, atol=1e-5)


def test_predictor_clone_threads(saved_model):
    prefix, lin = saved_model
    pred = create_predictor(Config(prefix))
    clones = [pred.clone() for _ in range(4)]
    assert all(c._call is pred._call for c in clones)  # shared executable
    errs = []

    def work(p):
        x = np.random.randn(2, 16).astype(np.float32)
        out = p.run([x])[0]
        want = np.asarray(lin(paddle.to_tensor(x))._data)
        errs.append(float(np.abs(out - want).max()))

    threads = [threading.Thread(target=work, args=(c,)) for c in clones]
    [t.start() for t in threads]
    [t.join() for t in threads]
    assert len(errs) == 4 and max(errs) < 1e-5


def test_shared_predictor_concurrent_list_api(saved_model):
    """ONE predictor instance (no clones) hammered from two threads via the
    list API: run() stages + executes + returns under a single _lock hold
    (# guarded_by: covered by the lock-discipline checker), so concurrent
    callers serialize instead of tearing each other's slots."""
    prefix, lin = saved_model
    pred = create_predictor(Config(prefix))
    errs = []
    xs = [np.random.RandomState(s).randn(2, 16).astype(np.float32)
          for s in range(2)]

    def work(x):
        try:
            for _ in range(20):
                out = pred.run([x])[0]
                want = np.asarray(lin(paddle.to_tensor(x))._data)
                assert np.abs(out - want).max() < 1e-5
        except Exception as e:  # surfaced to the main thread below
            errs.append(e)

    threads = [threading.Thread(target=work, args=(x,)) for x in xs]
    [t.start() for t in threads]
    [t.join() for t in threads]
    assert not errs, errs


def test_error_paths(saved_model):
    prefix, _ = saved_model
    with pytest.raises(ValueError, match="not found"):
        Predictor(Config(os.path.join(tempfile.mkdtemp(), "missing")))
    pred = create_predictor(Config(prefix))
    with pytest.raises(RuntimeError, match="inputs not set"):
        pred.run()
    with pytest.raises(RuntimeError, match="output handle"):
        pred.get_output_handle("output_0").copy_from_cpu(np.zeros((1, 16), np.float32))


def test_save_inference_model_requires_callable():
    with pytest.raises(TypeError):
        paddle.static.save_inference_model(
            "/tmp/x", [InputSpec([1, 4], "float32")], fetch_vars=[1, 2]
        )


def test_load_inference_model(saved_model):
    prefix, lin = saved_model
    layer = paddle.static.load_inference_model(prefix)
    x = np.random.randn(2, 16).astype(np.float32)
    np.testing.assert_allclose(
        np.asarray(layer(paddle.to_tensor(x))._data),
        np.asarray(lin(paddle.to_tensor(x))._data),
        atol=1e-5,
    )
