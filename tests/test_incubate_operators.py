"""paddle.incubate.operators (reference incubate/operators/): graph message
passing, k-hop sampling, fused-softmax aliases, ResNetUnit."""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu.incubate import (
    softmax_mask_fuse, softmax_mask_fuse_upper_triangle, graph_send_recv,
    graph_khop_sampler, ResNetUnit,
)


class TestGraphSendRecv:
    def test_sum_matches_loop(self):
        x = paddle.to_tensor(np.arange(12, dtype=np.float32).reshape(4, 3))
        src, dst = [0, 1, 2, 0], [1, 2, 1, 0]
        out = np.asarray(graph_send_recv(
            x, paddle.to_tensor(np.array(src)), paddle.to_tensor(np.array(dst)), "sum")._data)
        want = np.zeros((4, 3), np.float32)
        xv = np.arange(12, dtype=np.float32).reshape(4, 3)
        for s, d in zip(src, dst):
            want[d] += xv[s]
        np.testing.assert_allclose(out, want)

    def test_mean_and_untouched_max(self):
        x = paddle.to_tensor(np.ones((3, 2), np.float32))
        src = paddle.to_tensor(np.array([0, 1]))
        dst = paddle.to_tensor(np.array([0, 0]))
        mean = np.asarray(graph_send_recv(x, src, dst, "mean")._data)
        np.testing.assert_allclose(mean[0], 1.0)
        mx = np.asarray(graph_send_recv(x, src, dst, "max")._data)
        assert mx[2].sum() == 0  # empty receive -> 0, not -inf

    def test_grad_flows(self):
        x = paddle.to_tensor(np.random.RandomState(0).randn(4, 3).astype(np.float32))
        x.stop_gradient = False
        out = graph_send_recv(x, paddle.to_tensor(np.array([0, 1])),
                              paddle.to_tensor(np.array([1, 1])), "sum")
        out.sum().backward()
        g = np.asarray(x.grad._data)
        assert g[0].sum() == 3.0 and g[2].sum() == 0.0


class TestKhopSampler:
    def test_samples_bounded_neighborhood(self):
        colptr = paddle.to_tensor(np.array([0, 2, 3, 5, 6]))
        row = paddle.to_tensor(np.array([1, 2, 0, 0, 3, 2]))
        es, ed, samp, re = graph_khop_sampler(
            row, colptr, paddle.to_tensor(np.array([0])), [2])
        es, ed = np.asarray(es._data), np.asarray(ed._data)
        assert len(es) == 2 and len(ed) == 2
        uniq = np.asarray(samp._data)
        assert 0 in uniq  # seeds always present
        assert es.max() < len(uniq) and ed.max() < len(uniq)  # reindexed


class TestFusedSoftmaxAliases:
    def test_mask_fuse(self):
        x = paddle.to_tensor(np.random.RandomState(0).randn(2, 4).astype(np.float32))
        out = np.asarray(softmax_mask_fuse(
            x, paddle.to_tensor(np.zeros((2, 4), np.float32)))._data)
        np.testing.assert_allclose(out.sum(-1), 1.0, rtol=1e-5)

    def test_upper_triangle(self):
        x = paddle.to_tensor(np.random.RandomState(0).randn(4, 4).astype(np.float32))
        out = np.asarray(softmax_mask_fuse_upper_triangle(x)._data)
        assert abs(out[0, 0] - 1.0) < 1e-5  # row 0 attends only position 0
        assert out[0, 1:].max() < 1e-6


class TestResNetUnit:
    def test_forward_and_shortcut(self):
        paddle.seed(0)
        u = ResNetUnit(3, 8, 3, stride=2, has_shortcut=True, num_channels_z=3)
        x = paddle.to_tensor(np.random.RandomState(0).randn(2, 3, 8, 8).astype(np.float32))
        y = u(x, z=x)
        assert tuple(y.shape) == (2, 8, 4, 4)
        assert float(np.asarray(y._data).min()) >= 0.0
