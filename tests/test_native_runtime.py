"""Native C++ runtime tests (queue / TCPStore / trace / arena)."""
import ctypes
import threading
import time

import numpy as np
import pytest

from paddle_tpu.core import native

pytestmark = pytest.mark.skipif(native.lib() is None, reason="native runtime not built")


class TestNativeQueue:
    def test_fifo_and_close(self):
        q = native.NativeQueue(4)
        for i in range(3):
            q.push(bytes([i]))
        assert len(q) == 3
        assert q.pop() == b"\x00"
        q.close()
        assert q.pop() == b"\x01"
        assert q.pop() == b"\x02"
        assert q.pop() is None  # drained + closed

    def test_blocking_producer_consumer(self):
        q = native.NativeQueue(2)
        received = []

        def consumer():
            while True:
                b = q.pop()
                if b is None:
                    return
                received.append(b)

        t = threading.Thread(target=consumer)
        t.start()
        for i in range(20):
            assert q.push(np.full(100, i, np.uint8).tobytes())
        q.close()
        t.join(timeout=10)
        assert len(received) == 20
        assert received[7][0] == 7

    def test_push_after_close_fails(self):
        q = native.NativeQueue(2)
        q.close()
        assert not q.push(b"x")


class TestTCPStore:
    def test_set_get_add_wait(self):
        master = native.TCPStore(port=29911, is_master=True)
        worker = native.TCPStore(port=29911)
        try:
            master.set("a", b"1")
            assert worker.get("a") == b"1"
            assert worker.get("missing") is None
            assert worker.add("n", 3) == 3
            assert master.add("n", -1) == 2
            got = []
            t = threading.Thread(target=lambda: got.append(worker.wait("later")))
            t.start()
            time.sleep(0.1)
            master.set("later", b"v")
            t.join(timeout=5)
            assert got == [b"v"]
            master.delete_key("a")
            assert worker.get("a") is None
        finally:
            worker.close()
            master.close()

    def test_barrier_pattern(self):
        """Rendezvous barrier: N participants count up then wait."""
        master = native.TCPStore(port=29912, is_master=True)
        clients = [native.TCPStore(port=29912) for _ in range(3)]
        try:
            def participant(c, i):
                n = c.add("barrier", 1)
                if n == 3:
                    c.set("barrier_done", b"1")
                c.wait("barrier_done")

            ts = [threading.Thread(target=participant, args=(c, i)) for i, c in enumerate(clients)]
            for t in ts:
                t.start()
            for t in ts:
                t.join(timeout=5)
            assert all(not t.is_alive() for t in ts)
        finally:
            for c in clients:
                c.close()
            master.close()


class TestTraceArena:
    def test_trace_records(self):
        L = native.lib()
        r = L.ptt_create(128)
        nid = L.ptt_intern(r, b"matmul")
        assert L.ptt_intern(r, b"matmul") == nid  # interned
        t0 = L.ptt_now_ns()
        L.ptt_record(r, nid, 1, t0, t0 + 500)
        buf = ctypes.create_string_buffer(24 * 8)
        n = L.ptt_drain(r, buf, 8)
        assert n == 1
        assert L.ptt_name(r, nid) == b"matmul"
        L.ptt_destroy(r)

    def test_arena_reuse(self):
        L = native.lib()
        a = L.pta_create(64)
        p = L.pta_alloc(a, 10_000)
        assert p % 64 == 0
        L.pta_free(a, p)
        p2 = L.pta_alloc(a, 12_000)  # same 16KiB size class → reused
        assert p2 == p
        assert L.pta_reused(a) == 1
        L.pta_destroy(a)

    def test_profiler_uses_native(self):
        import paddle_tpu.profiler as prof

        p = prof.Profiler(timer_only=True)
        p.start()
        with prof.RecordEvent("test_op"):
            time.sleep(0.001)
        p.stop()
        assert "test_op" in p.summary()


class TestDataLoaderNativePath:
    def test_native_queue_loader_matches_serial(self):
        import paddle_tpu as paddle
        from paddle_tpu.io import DataLoader, Dataset

        class DS(Dataset):
            def __len__(self):
                return 12

            def __getitem__(self, i):
                return np.full((4,), i, np.float32), np.int64(i)

        serial = sorted(float(b[1].numpy()[0]) for b in DataLoader(DS(), batch_size=3))
        native_batches = list(DataLoader(DS(), batch_size=3, num_workers=2))
        assert len(native_batches) == 4
        got = sorted(float(b[1].numpy()[0]) for b in native_batches)
        assert got == serial
