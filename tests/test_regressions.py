"""Regression tests for advisor/review findings.

Each test pins a previously-broken behavior: nondiff-output ops under grad,
GradScaler re-unscaling, dynamic-dim AOT export, non-leaf tensor hooks,
self-describing checkpoints, nan/inf debug flag, p2p stubs.
"""
import os
import pickle
import tempfile

import numpy as np
import pytest

import paddle_tpu as paddle


def test_topk_with_grad():
    # dispatch replay path for ops with nondiff outputs used an undefined name
    t = paddle.to_tensor(np.random.randn(4, 8).astype(np.float32))
    t.stop_gradient = False
    vals, idx = paddle.topk(t, k=3)
    vals.sum().backward()
    assert t.grad is not None
    assert float(np.asarray(t.grad._data).sum()) == pytest.approx(12.0)


def test_gradscaler_unscale_then_step_unscales_once():
    lin = paddle.nn.Linear(4, 2)
    opt = paddle.optimizer.SGD(learning_rate=0.0, parameters=lin.parameters())
    sc = paddle.amp.GradScaler(init_loss_scaling=8.0)
    x = paddle.to_tensor(np.ones((2, 4), np.float32))
    loss = lin(x).sum()
    sc.scale(loss).backward()
    p = next(p for p in lin.parameters() if p.grad is not None)
    scaled = np.asarray(p.grad._data).copy()
    sc.unscale_(opt)  # explicit unscale (e.g. for grad clipping)
    once = np.asarray(p.grad._data).copy()
    np.testing.assert_allclose(once, scaled / 8.0, rtol=1e-6)
    sc.step(opt)  # must NOT divide again
    np.testing.assert_allclose(np.asarray(p.grad._data), once, rtol=1e-6)
    sc.update()
    # next iteration unscales again
    opt.clear_grad()
    loss = lin(x).sum()
    sc.scale(loss).backward()
    sc.step(opt)
    np.testing.assert_allclose(np.asarray(p.grad._data), once, rtol=1e-6)


def test_gradscaler_static_scaling_unscales_every_step():
    # update() must clear per-step unscale state even with dynamic scaling off
    lin = paddle.nn.Linear(4, 2)
    opt = paddle.optimizer.SGD(learning_rate=0.0, parameters=lin.parameters())
    sc = paddle.amp.GradScaler(init_loss_scaling=8.0, use_dynamic_loss_scaling=False)
    x = paddle.to_tensor(np.ones((2, 4), np.float32))
    maxes = []
    for _ in range(2):
        opt.clear_grad()
        sc.scale(lin(x).sum()).backward()
        sc.step(opt)
        sc.update()
        p = next(p for p in lin.parameters() if p.grad is not None)
        maxes.append(float(np.abs(np.asarray(p.grad._data)).max()))
    assert maxes[0] == pytest.approx(maxes[1], rel=1e-5)


def test_jit_save_dynamic_batch():
    lin = paddle.nn.Linear(8, 3)
    lin.eval()
    d = tempfile.mkdtemp()
    from paddle_tpu.static import InputSpec

    paddle.jit.save(lin, os.path.join(d, "m"), input_spec=[InputSpec([None, 8], "float32")])
    loaded = paddle.jit.load(os.path.join(d, "m"))
    for bs in (1, 5, 13):
        x = paddle.to_tensor(np.random.randn(bs, 8).astype(np.float32))
        np.testing.assert_allclose(
            np.asarray(loaded(x)._data), np.asarray(lin(x)._data), atol=1e-5
        )


def test_nonleaf_register_hook_fires():
    a = paddle.to_tensor(np.ones((2, 2), np.float32))
    a.stop_gradient = False
    b = a * 2.0
    fired = []
    b.register_hook(lambda g: fired.append(1) or (g * 3.0))
    (b * 1.0).sum().backward()
    assert fired
    np.testing.assert_allclose(np.asarray(a.grad._data), 6.0)


def test_checkpoint_readable_without_framework():
    lin = paddle.nn.Linear(3, 3)
    d = tempfile.mkdtemp()
    path = os.path.join(d, "sd.pdparams")
    paddle.save(lin.state_dict(), path)
    raw = pickle.load(open(path, "rb"))  # plain pickle: no framework classes
    for k, v in raw.items():
        assert isinstance(v, dict) and v.get("__paddle_tpu_tensor__")
        assert isinstance(v["data"], (np.ndarray, bytes))
    # and the framework loads it back identically
    sd2 = paddle.load(path)
    for k in raw:
        np.testing.assert_array_equal(
            np.asarray(lin.state_dict()[k]._data), np.asarray(sd2[k]._data)
        )


def test_bf16_checkpoint_roundtrip():
    lin = paddle.nn.Linear(3, 3)
    lin.bfloat16()
    d = tempfile.mkdtemp()
    path = os.path.join(d, "sd16.pdparams")
    paddle.save(lin.state_dict(), path)
    sd2 = paddle.load(path)
    for k, v in lin.state_dict().items():
        assert str(sd2[k].dtype) == "bfloat16"
        np.testing.assert_array_equal(
            np.asarray(v._data, np.float32), np.asarray(sd2[k]._data, np.float32)
        )


def test_check_nan_inf_flag():
    paddle.set_flags({"FLAGS_check_nan_inf": True})
    try:
        a = paddle.to_tensor(np.array([0.0], np.float32))
        with pytest.raises(FloatingPointError, match="log"):
            # under the lazy engine the op stays recorded (fusion kept) and
            # the guard trips at the flush — still within the same step
            paddle.log(a - 1.0).numpy()
    finally:
        paddle.set_flags({"FLAGS_check_nan_inf": False})


def test_p2p_stubs_raise():
    from paddle_tpu.distributed import collective

    t = paddle.to_tensor(np.ones((2,), np.float32))
    for fn in (collective.send, collective.recv, collective.isend, collective.irecv):
        with pytest.raises(NotImplementedError):
            fn(t)
