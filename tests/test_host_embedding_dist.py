"""Distributed (sharded) host embedding: 2 real processes, table sharded by
id over the native TCPStore, pull/push parity with a single-process table
(reference PS methodology: test_dist_base.py loss-parity between 1-proc and
N-proc runs; capability of memory_sparse_table.cc + the_one_ps.py:606)."""
import json
import os
import socket
import subprocess
import sys
import textwrap

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


WORKER = textwrap.dedent(
    """
    import os, json
    os.environ["JAX_PLATFORMS"] = "cpu"
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.core.native import TCPStore, lib
    from paddle_tpu.incubate.host_embedding import (
        HostEmbedding, ShardedHostEmbeddingTable, sharded_host_embedding,
    )

    rank = int(os.environ["PADDLE_TRAINER_ID"])
    port = int(os.environ["PADDLE_EMB_STORE_PORT"])

    emb = sharded_host_embedding(64, 8, seed=3)
    assert isinstance(emb.table, ShardedHostEmbeddingTable), type(emb.table)

    # both ranks run the SAME global batches (dp would split them; identical
    # batches make the single-process comparison exact)
    steps = []
    for step in range(3):
        rng = np.random.RandomState(100 + step)
        ids = rng.randint(0, 64, (4, 5))
        out = emb(paddle.to_tensor(ids))
        loss = paddle.sum(out * out)
        loss.backward()
        emb.apply_gradients(lr=0.1)
        steps.append(float(loss.numpy()))
    print(json.dumps({"rank": rank, "losses": steps}), flush=True)
    """
)


class TestShardedHostEmbedding:
    def test_two_process_parity_with_single_table(self):
        from paddle_tpu.core.native import lib

        if lib() is None:
            pytest.skip("native runtime not built")
        port = _free_port()
        procs = []
        for rank in range(2):
            env = {k: v for k, v in os.environ.items() if k not in ("XLA_FLAGS",)}
            env.update(
                {
                    "PYTHONPATH": REPO,
                    "JAX_PLATFORMS": "cpu",
                    "PADDLE_TRAINER_ID": str(rank),
                    "PADDLE_TRAINERS_NUM": "2",
                    "PADDLE_EMB_STORE_PORT": str(port),
                }
            )
            procs.append(
                subprocess.Popen(
                    [sys.executable, "-c", WORKER],
                    env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                )
            )
        outs = []
        for p in procs:
            out, err = p.communicate(timeout=240)
            assert p.returncode == 0, err.decode()[-2000:]
            outs.append(json.loads(out.decode().strip().splitlines()[-1]))

        # both ranks observe identical losses (same global batch, sync PS)
        assert outs[0]["losses"] == outs[1]["losses"], outs

        # single-process reference: same seeds, same batches, plain table
        from paddle_tpu.incubate.host_embedding import HostEmbedding
        import paddle_tpu as paddle

        emb = HostEmbedding(64, 8, seed=3)
        ref = []
        for step in range(3):
            rng = np.random.RandomState(100 + step)
            ids = rng.randint(0, 64, (4, 5))
            out = emb(paddle.to_tensor(ids))
            loss = paddle.sum(out * out)
            loss.backward()
            # two ranks each pushed the same grads → the sharded run applied
            # a 2x summed update; mirror that for exact parity
            for uniq, rows in emb._pending:
                if rows.grad is not None:
                    rows.grad._set_data(rows.grad._data * 2.0)
            emb.apply_gradients(lr=0.1)
            ref.append(float(loss.numpy()))
        np.testing.assert_allclose(outs[0]["losses"], ref, rtol=1e-5)


CHUNK_WORKER = textwrap.dedent(
    """
    import os, json
    os.environ["JAX_PLATFORMS"] = "cpu"
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.framework import flags
    from paddle_tpu.incubate.host_embedding import sharded_host_embedding

    rank = int(os.environ["PADDLE_TRAINER_ID"])
    # tiny chunks force the multi-chunk parallel transport on every
    # exchange; fp16 push halves the grad payload when armed
    flags.set_flags({"FLAGS_host_emb_chunk_bytes": 4096,
                     "FLAGS_host_emb_transport_threads": 3,
                     "FLAGS_host_emb_push_fp16":
                         os.environ.get("HE_FP16", "0") == "1"})
    emb = sharded_host_embedding(512, 16, seed=3)
    steps = []
    for step in range(3):
        rng = np.random.RandomState(200 + step)
        ids = rng.randint(0, 512, (8, 32))  # 256 ids/step >> chunk
        out = emb(paddle.to_tensor(ids))
        loss = paddle.sum(out * out)
        loss.backward()
        emb.apply_gradients(lr=0.1)
        steps.append(float(loss.numpy()))
    from paddle_tpu import profiler
    c = profiler.counters()
    print(json.dumps({"rank": rank, "losses": steps,
                      "push_bytes": c.get("host_emb_push_bytes", 0)}), flush=True)
    """
)


def _run_world(worker, world=2, extra_env=None, timeout=240):
    port = _free_port()
    procs = []
    for rank in range(world):
        env = {k: v for k, v in os.environ.items() if k not in ("XLA_FLAGS",)}
        env.update({
            "PYTHONPATH": REPO,
            "JAX_PLATFORMS": "cpu",
            "PADDLE_TRAINER_ID": str(rank),
            "PADDLE_TRAINERS_NUM": str(world),
            "PADDLE_EMB_STORE_PORT": str(port),
        })
        env.update(extra_env or {})
        procs.append(subprocess.Popen(
            [sys.executable, "-c", worker],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        ))
    outs = []
    for p in procs:
        out, err = p.communicate(timeout=timeout)
        assert p.returncode == 0, err.decode()[-2000:]
        outs.append(json.loads(out.decode().strip().splitlines()[-1]))
    return outs


class TestChunkParallelTransport:
    def _single_proc_reference(self):
        import paddle_tpu as paddle
        from paddle_tpu.incubate.host_embedding import HostEmbedding

        emb = HostEmbedding(512, 16, seed=3)
        ref = []
        for step in range(3):
            rng = np.random.RandomState(200 + step)
            ids = rng.randint(0, 512, (8, 32))
            out = emb(paddle.to_tensor(ids))
            loss = paddle.sum(out * out)
            loss.backward()
            for uniq, rows in emb._pending:
                if rows.grad is not None:
                    rows.grad._set_data(rows.grad._data * 2.0)
            emb.apply_gradients(lr=0.1)
            ref.append(float(loss.numpy()))
        return ref

    def test_two_proc_parity_with_parallel_chunks(self):
        from paddle_tpu.core.native import lib

        if lib() is None:
            pytest.skip("native runtime not built")
        outs = _run_world(CHUNK_WORKER, world=2)
        assert outs[0]["losses"] == outs[1]["losses"], outs
        # the coalesced push payloads were actually counted
        assert outs[0]["push_bytes"] > 0
        np.testing.assert_allclose(
            outs[0]["losses"], self._single_proc_reference(), rtol=1e-5)

    def test_fp16_push_close_but_half_bytes(self):
        from paddle_tpu.core.native import lib

        if lib() is None:
            pytest.skip("native runtime not built")
        outs32 = _run_world(CHUNK_WORKER, world=2)
        outs16 = _run_world(CHUNK_WORKER, world=2, extra_env={"HE_FP16": "1"})
        assert outs16[0]["losses"] == outs16[1]["losses"]
        # lossy but close; payload bytes drop (ids stay 8B, grads 4B -> 2B)
        np.testing.assert_allclose(
            outs16[0]["losses"], outs32[0]["losses"], rtol=2e-2)
        assert outs16[0]["push_bytes"] < outs32[0]["push_bytes"]


class TestInstanceCounterThreadSafety:
    def test_concurrent_construction_distinct_namespaces(self):
        import threading
        from paddle_tpu.incubate.host_embedding import ShardedHostEmbeddingTable

        names = []
        lock = threading.Lock()

        def build():
            t = ShardedHostEmbeddingTable(64, 4, store=None, rank=0, world_size=2)
            with lock:
                names.append(t.name)

        threads = [threading.Thread(target=build) for _ in range(16)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert len(set(names)) == 16, f"colliding table namespaces: {names}"


class TestCoalescedPush:
    def test_duplicate_ids_across_microbatches_merge(self):
        import paddle_tpu as paddle
        from paddle_tpu.incubate.host_embedding import HostEmbedding

        emb = HostEmbedding(32, 4, seed=1)
        calls = []
        orig = emb.table.apply_update

        def counting(ids, grad, lr):
            calls.append(np.asarray(ids))
            return orig(ids, grad, lr)

        emb.table.apply_update = counting
        for _ in range(3):  # 3 microbatches touching overlapping ids
            out = emb(paddle.to_tensor(np.array([[1, 2], [2, 3]])))
            paddle.sum(out * out).backward()
        emb.apply_gradients(lr=0.05)
        assert len(calls) == 1, "pushes not coalesced"
        np.testing.assert_array_equal(calls[0], [1, 2, 3])

    def test_vectorized_init_deterministic_per_row(self):
        from paddle_tpu.incubate.host_embedding import HostEmbeddingTable

        a = HostEmbeddingTable(100, 16, seed=9)
        b = HostEmbeddingTable(100, 16, seed=9)
        r1 = a.gather(np.array([5, 50, 99]))
        r2 = b.gather(np.array([99, 5, 7, 50]))  # different touch order/set
        np.testing.assert_allclose(r1[0], r2[1])
        np.testing.assert_allclose(r1[1], r2[3])
        np.testing.assert_allclose(r1[2], r2[0])
        # distribution sanity: ~N(0, 0.01)
        big = a.gather(np.arange(100))
        assert abs(float(big.std()) - 0.01) < 0.003

    def test_prefetch_overlaps_and_matches(self):
        import paddle_tpu as paddle
        from paddle_tpu.incubate.host_embedding import HostEmbedding

        emb = HostEmbedding(64, 8, seed=2)
        ids = np.array([[3, 4, 5]])
        ref = emb(paddle.to_tensor(ids)).numpy()
        emb2 = HostEmbedding(64, 8, seed=2)
        emb2.prefetch(np.asarray(ids))
        got = emb2(paddle.to_tensor(ids)).numpy()
        np.testing.assert_allclose(got, ref)
