"""Autotuned kernel registry (ops/kernels/) — the ISSUE-18 contract.

Pins the three load-bearing promises of the layer:

- **Inert when off** (the default): config resolution is a dict probe
  returning the hand-frozen constants; the autotuner, the verifier, and the
  tuning DB are never touched (monkeypatch-exploded here) and the tune dir
  stays empty. Registered call sites (flash attention, fused CE, the paged
  and int8 serving kernels) behave byte-identically to the pre-registry
  code.
- **Search never does worse than the defaults**: the default config is
  always measured first and a candidate can only win if it is faster AND
  its output verifies against the default's; a broken candidate is a
  counted disqualification, never a result.
- **DB durability**: winners round-trip through the atomic-write DB; a
  torn/truncated/out-of-space entry is a structured reject (counted, file
  removed, re-tuned or defaulted) — a wrong config is never returned, and
  deleting the DB is always a silent fallback to the defaults.
"""
import os
import time

import numpy as np
import pytest

import paddle_tpu  # noqa: F401 — env/flag setup
from paddle_tpu.cost_model import CostModel
from paddle_tpu.framework import flags
from paddle_tpu.ops import kernels as K
from paddle_tpu.ops.kernels import autotune, db, registry
from paddle_tpu.profiler import counters

# the hand-frozen constants each call site used before the registry existed;
# the inert-mode contract is that resolve_config returns exactly these
PINNED = {
    "flash_attention": {"block_q": 512, "block_k": 512},
    "fused_ce": {"block_rows": 2048},
    "paged_attention": {"rows_per_program": 1, "score_mode": "live"},
    "int8_matmul": {"block_n": 512},
}


@pytest.fixture
def tune_env(tmp_path, monkeypatch):
    """Isolated tune dir + fast search knobs; the in-process memo is cleared
    on both sides so resolutions can't leak between tests."""
    monkeypatch.setitem(flags._FLAGS, "FLAGS_kernel_tune_dir", str(tmp_path))
    monkeypatch.setitem(flags._FLAGS, "FLAGS_kernel_tune_samples", 2)
    monkeypatch.setitem(flags._FLAGS, "FLAGS_kernel_tune_budget_s", 60.0)
    autotune.clear_cache()
    yield tmp_path
    autotune.clear_cache()


def _stub(name, sleeps, wrong=()):
    """Register a stub kernel whose per-config runtime/output is scripted:
    ``sleeps[width]`` seconds per call; widths in ``wrong`` return a
    different output (must be rejected by verify)."""

    def runner(key):
        def make(config):
            w = config["width"]

            def step():
                time.sleep(sleeps.get(w, 0.0))
                if w in wrong:
                    return np.full((4,), 7.0, np.float32)
                return np.zeros((4,), np.float32)

            return step

        return make

    return registry.register_kernel(
        name, defaults={"width": 8}, space={"width": (8, 16, 32)},
        runner=runner)


class TestInertOff:
    def test_defaults_are_the_pinned_constants(self):
        for name, want in PINNED.items():
            assert K.resolve_config(name, ()) == want

    def test_off_never_touches_autotuner_or_db(self, tmp_path, monkeypatch):
        """The tier-1 tripwire: with autotune off, a resolve through every
        registered kernel AND real traced call sites must never reach the
        autotuner, the verifier, or the DB — and must write zero files."""
        import jax.numpy as jnp

        monkeypatch.setitem(flags._FLAGS, "FLAGS_kernel_tune_dir",
                            str(tmp_path))
        assert flags.flag("FLAGS_kernel_autotune", "off") == "off"

        def boom(*a, **k):  # pragma: no cover - must never run
            raise AssertionError("autotune layer touched with autotune off")

        monkeypatch.setattr(autotune, "resolve", boom)
        monkeypatch.setattr(autotune, "search", boom)
        monkeypatch.setattr(autotune, "verify", boom)
        monkeypatch.setattr(db, "lookup", boom)
        monkeypatch.setattr(db, "store", boom)
        before = {k: v for k, v in counters().items()
                  if k.startswith("kernel_tune")}

        for name in K.kernel_names():
            cfg = K.resolve_config(name, ())
            assert isinstance(cfg, dict) and cfg

        # real registered call sites, config resolved inside the trace
        rng = np.random.RandomState(0)
        from paddle_tpu.ops.fused_ce import fused_linear_cross_entropy

        x = jnp.asarray(rng.randn(8, 16), jnp.float32)
        w = jnp.asarray(rng.randn(33, 16), jnp.float32)
        labels = jnp.asarray(rng.randint(0, 33, (8,)), jnp.int32)
        float(fused_linear_cross_entropy(x, w, labels))

        q = jnp.asarray(rng.randn(2, 4, 8, 16), jnp.float32)
        from paddle_tpu.ops.pallas.flash_attention import (
            flash_attention_array,
        )

        np.asarray(flash_attention_array(q, q, q, causal=True))

        kpool = jnp.asarray(rng.randn(16, 8, 2, 16), jnp.float32)
        tables = jnp.asarray(rng.randint(1, 16, (2, 2)), jnp.int32)
        pos = jnp.asarray([3, 9], jnp.int32)
        qr = jnp.asarray(rng.randn(2, 4, 16), jnp.float32)
        np.asarray(K.paged_attention_rows(qr, kpool, kpool, tables, pos))

        qw = jnp.asarray(rng.randint(-127, 127, (32, 16)), jnp.int8)
        np.asarray(K.int8_matmul(jnp.asarray(rng.randn(3, 16), jnp.float32),
                                 qw, jnp.asarray(2.0, jnp.float32)))

        after = {k: v for k, v in counters().items()
                 if k.startswith("kernel_tune")}
        assert after == before
        assert not os.path.exists(str(tmp_path)) or \
            os.listdir(str(tmp_path)) == []


class TestTuningDB:
    def test_store_lookup_roundtrip(self, tune_env):
        key = (64, 32, "float32")
        db.store("stub_rt", key, {"width": 16}, 1.0, 2.0)
        assert db.lookup("stub_rt", key) == {"width": 16}
        # a different key is a plain miss, no reject
        before = counters().get("kernel_tune_db_rejects", 0)
        assert db.lookup("stub_rt", (65, 32, "float32")) is None
        assert counters().get("kernel_tune_db_rejects", 0) == before

    def test_truncated_entry_is_structured_reject(self, tune_env):
        key = (64, 32, "float32")
        path = db.store("stub_torn", key, {"width": 16}, 1.0, 2.0)
        with open(path) as f:
            raw = f.read()
        with open(path, "w") as f:
            f.write(raw[: len(raw) // 2])  # torn write
        before = counters().get("kernel_tune_db_rejects", 0)
        assert db.lookup("stub_torn", key) is None  # never a wrong config
        assert counters().get("kernel_tune_db_rejects", 0) == before + 1
        assert not os.path.exists(path)  # bad file removed

    def test_db_deleted_is_silent_default_fallback(self, tune_env,
                                                   monkeypatch):
        monkeypatch.setitem(flags._FLAGS, "FLAGS_kernel_autotune", "ondemand")
        spec = _stub("stub_deleted", sleeps={})
        key = (1,)
        assert autotune.resolve(spec, key, "ondemand") == {"width": 8}
        assert os.listdir(str(tune_env)) == []  # ondemand never searches

    def test_out_of_space_entry_rejected_not_traced(self, tune_env):
        spec = _stub("stub_oos", sleeps={})
        key = (2,)
        db.store("stub_oos", key, {"width": 999}, 1.0, 2.0)
        before = counters().get("kernel_tune_db_rejects", 0)
        assert autotune.resolve(spec, key, "ondemand") == {"width": 8}
        assert counters().get("kernel_tune_db_rejects", 0) == before + 1


class TestSearch:
    def test_winner_is_fastest_verified_and_persists(self, tune_env):
        # width 16 is fastest and correct; 32 is slower than the default
        spec = _stub("stub_win", sleeps={8: 0.02, 16: 0.0, 32: 0.05})
        key = (64, "float32")
        c0 = dict(counters())
        cfg = autotune.resolve(spec, key, "search")
        assert cfg == {"width": 16}
        c1 = dict(counters())
        assert c1.get("kernel_tune_searches", 0) == \
            c0.get("kernel_tune_searches", 0) + 1
        assert os.path.exists(db.entry_path("stub_win", key))

        # a fresh process (memo cleared) resolves straight from disk:
        # zero re-search, counted as a DB hit
        autotune.clear_cache()
        cfg2 = autotune.resolve(spec, key, "search")
        c2 = dict(counters())
        assert cfg2 == cfg
        assert c2.get("kernel_tune_searches", 0) == \
            c1.get("kernel_tune_searches", 0)
        assert c2.get("kernel_tune_hits", 0) == \
            c1.get("kernel_tune_hits", 0) + 1

    def test_wrong_output_candidate_never_wins(self, tune_env):
        # width 16 would be fastest but returns a different output; 32 is
        # slower than the default — so the defaults must win
        spec = _stub("stub_wrong", sleeps={8: 0.02, 16: 0.0, 32: 0.05},
                     wrong=(16,))
        c0 = counters().get("kernel_tune_verify_fails", 0)
        cfg = autotune.resolve(spec, (3,), "search")
        assert cfg == {"width": 8}  # never worse than the pinned defaults
        assert counters().get("kernel_tune_verify_fails", 0) == c0 + 1

    def test_corrupt_db_entry_triggers_retune(self, tune_env):
        spec = _stub("stub_corrupt", sleeps={8: 0.01, 16: 0.0, 32: 0.05})
        key = (4,)
        autotune.resolve(spec, key, "search")
        path = db.entry_path("stub_corrupt", key)
        with open(path, "w") as f:
            f.write("{")  # torn
        autotune.clear_cache()
        c0 = dict(counters())
        cfg = autotune.resolve(spec, key, "search")
        c1 = dict(counters())
        assert cfg == {"width": 16}
        assert c1.get("kernel_tune_db_rejects", 0) == \
            c0.get("kernel_tune_db_rejects", 0) + 1
        assert c1.get("kernel_tune_searches", 0) == \
            c0.get("kernel_tune_searches", 0) + 1

    def test_broken_runner_degrades_to_defaults(self, tune_env):
        def runner(key):
            def make(config):
                raise RuntimeError("no backend")

            return make

        spec = registry.register_kernel(
            "stub_broken", defaults={"width": 8}, space={"width": (8, 16)},
            runner=runner)
        cfg = autotune.resolve(spec, (5,), "search")
        assert cfg == {"width": 8}
        # nothing was measured, so nothing may persist
        assert not os.path.exists(db.entry_path("stub_broken", (5,)))


class TestCostModel:
    def test_padding_waste_and_grid_overhead_ordering(self):
        cm = CostModel()
        # fused CE at N=1000: block_rows=8192 pads to 8x the real rows
        small = cm.kernel_estimate("fused_ce", (1000, 512, 50000, "float32"),
                                   {"block_rows": 512})
        huge = cm.kernel_estimate("fused_ce", (1000, 512, 50000, "float32"),
                                  {"block_rows": 8192})
        assert small < huge
        # flash at t=8192: 128-wide blocks launch 4x the programs of 512
        key = (8, 8, 8192, 8192, 128, "bfloat16", True)
        assert cm.kernel_estimate("flash_attention", key,
                                  {"block_q": 512, "block_k": 512}) < \
            cm.kernel_estimate("flash_attention", key,
                               {"block_q": 128, "block_k": 128})
        assert cm.kernel_estimate("no_such_kernel", (), {}) == 0.0

    def test_candidates_visit_order_matches_estimates(self):
        spec = registry.get_kernel("fused_ce")
        key = (1000, 512, 50000, "float32")
        cands = autotune.candidates(spec, key)
        assert cands  # non-default configs exist
        assert all(c != dict(spec.defaults) for c in cands)
        cm = CostModel()
        ests = [cm.kernel_estimate("fused_ce", key, c) for c in cands]
        assert ests == sorted(ests)
