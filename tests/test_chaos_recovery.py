"""Multi-process chaos recovery: kill (and separately wedge) a rank
mid-collective under injection and assert the full recovery contract:

1. the failure is DETECTED within FLAGS_collective_timeout_s (plus dump
   slack) — no survivor blocks forever;
2. every surviving rank writes a flight-recorder post-mortem naming the
   suspect rank;
3. the launcher loop relaunches and the world resumes from the last
   COORDINATED checkpoint;
4. the resumed run's per-step losses are BIT-FOR-BIT equal to an
   uninterrupted run — sample order (DataLoader state), RNG stream
   (program_rng), and weights (coordinated commit) all replayed exactly.

Workers are fresh interpreters (subprocess) coordinating over a FileStore +
progress dir — the same substrate ``spawn``/``launch`` provision — so the
suite is heavy; the ``chaos`` marker auto-skips it on the CPU CI tier
(opt in with PADDLE_TPU_CHAOS=1).
"""
import json
import os
import subprocess
import sys
import time
from pathlib import Path

import pytest

pytestmark = [pytest.mark.faults, pytest.mark.chaos]

WORLD = 2
TOTAL_STEPS = 9
CKPT_INTERVAL = 3
FAIL_STEP = 5
TIMEOUT_S = 4.0

_WORKER = r"""
import json, os, sys
import numpy as np

os.environ.setdefault("JAX_PLATFORMS", "cpu")
import jax
import jax.numpy as jnp

import paddle_tpu
from paddle_tpu.core import random as prandom
from paddle_tpu.distributed import watchdog
from paddle_tpu.distributed.checkpoint import CoordinatedCheckpoint
from paddle_tpu.distributed.coord import wait_for
from paddle_tpu.framework import flags as fw_flags
from paddle_tpu.io import DataLoader, Dataset

rank = int(os.environ["PADDLE_TRAINER_ID"])
world = int(os.environ["PADDLE_TRAINERS_NUM"])
run_dir = os.environ["CHAOS_RUN_DIR"]
total_steps = int(os.environ["CHAOS_TOTAL_STEPS"])
ckpt_interval = int(os.environ["CHAOS_CKPT_INTERVAL"])
incarnation = os.environ["CHAOS_INCARNATION"]

fw_flags.set_flags({"FLAGS_collective_timeout_s": float(os.environ["CHAOS_TIMEOUT_S"])})
watchdog.configure()  # rank/world/store/progress all from the launcher env
store = watchdog._cfg["store"]
assert store is not None, "chaos worker needs PADDLE_TPU_STORE_DIR"


class ArangeDS(Dataset):
    def __getitem__(self, i):
        return np.float32([i, i * 0.5, -i, 1.0])

    def __len__(self):
        return 64


paddle_tpu.seed(1234)
loader = DataLoader(ArangeDS(), batch_size=4, shuffle=True, seed=99)
w = paddle_tpu.to_tensor(np.zeros(4, np.float32))
state = {"w": w, "rng": paddle_tpu.program_rng, "loader": loader}

cc = CoordinatedCheckpoint(
    os.path.join(run_dir, "ckpt"), world_size=world, rank=rank, store=store,
    interval_steps=ckpt_interval, commit_timeout_s=10.0,
)
start = cc.resume(state) + 1

loss_log = open(os.path.join(run_dir, f"losses_rank{rank}_{incarnation}.jsonl"), "w")
it = iter(loader)

for step in range(start, total_steps):
    # the chaos points (rank.kill / rank.hang / rank.slow) fire here
    watchdog.publish(step=step, phase="train_step", force=True)
    try:
        batch = next(it)
    except StopIteration:
        it = iter(loader)
        batch = next(it)
    x = jnp.asarray(batch._data)
    noise = jax.random.normal(prandom.next_key(), (4,), jnp.float32) * 0.01
    wv = jnp.asarray(w._data)
    pred = x @ (wv + noise)
    loss = jnp.mean((pred - jnp.sum(x, axis=1)) ** 2)
    grad = jax.grad(lambda ww: jnp.mean((x @ (ww + noise) - jnp.sum(x, axis=1)) ** 2))(wv)
    w._set_data(wv - 0.01 * grad)
    loss_log.write(json.dumps({
        "step": step,
        "loss_hex": float(loss).hex(),
        "w_hex": [float(v).hex() for v in np.asarray(w._data)],
    }) + "\n")
    loss_log.flush()

    # the per-step collective: every rank must arrive; a dead/wedged peer
    # leaves the survivors inside the guard until the watchdog deadline
    bar = f"chaos/bar/{incarnation}/{step}"
    store.add(bar, 1)
    with watchdog.guard(f"barrier:step{step}"):
        wait_for(lambda: int(store.get(bar) or 0) >= world,
                 f"barrier step {step}", 0.0, interval_s=0.01)

    cc.maybe_save(step, state)

loss_log.close()
with open(os.path.join(run_dir, f"done_rank{rank}_{incarnation}"), "w") as f:
    f.write("ok")
sys.exit(0)
"""


def _launch_world(run_dir, incarnation, inject_spec=None, timeout_s=TIMEOUT_S):
    script = run_dir / "worker.py"
    script.write_text(_WORKER)
    flight_dir = run_dir / "flight"
    procs = []
    for rank in range(WORLD):
        env = dict(os.environ)
        repo_root = str(Path(__file__).resolve().parent.parent)
        env.update({
            "PYTHONPATH": repo_root + (
                os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else ""
            ),
            "JAX_PLATFORMS": "cpu",
            "PADDLE_TRAINER_ID": str(rank),
            "PADDLE_TRAINERS_NUM": str(WORLD),
            "PADDLE_TPU_STORE_DIR": str(run_dir / "store"),
            "PADDLE_TPU_PROGRESS_DIR": str(run_dir / "progress"),
            "PADDLE_TPU_FLIGHT_DIR": str(flight_dir),
            "CHAOS_RUN_DIR": str(run_dir),
            "CHAOS_TOTAL_STEPS": str(TOTAL_STEPS),
            "CHAOS_CKPT_INTERVAL": str(CKPT_INTERVAL),
            "CHAOS_INCARNATION": incarnation,
            "CHAOS_TIMEOUT_S": str(timeout_s),
        })
        env.pop("PADDLE_FAULT_INJECT", None)
        if inject_spec:
            env["PADDLE_FAULT_INJECT"] = inject_spec
        procs.append(subprocess.Popen(
            [sys.executable, str(script)], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        ))
    return procs


def _wait_world(procs, deadline_s=180.0):
    """Poll until every proc exits (or deadline); returns (codes, leftovers).
    A wedged rank (rank.hang) never exits — the launcher reaps it once the
    survivors have rendered their verdict, exactly like spawn's join."""
    t0 = time.monotonic()
    while time.monotonic() - t0 < deadline_s:
        codes = [p.poll() for p in procs]
        exited = [c for c in codes if c is not None]
        if len(exited) == len(procs):
            return codes, []
        # all SURVIVORS done, only the wedged injected rank still alive
        if len(exited) == len(procs) - 1 and any(c == 75 for c in exited):
            time.sleep(1.0)
            leftovers = [p for p in procs if p.poll() is None]
            if leftovers:
                for p in leftovers:
                    p.terminate()
                for p in leftovers:
                    p.wait(10)
                return [p.poll() for p in procs], leftovers
        time.sleep(0.2)
    for p in procs:
        if p.poll() is None:
            p.kill()
    raise AssertionError(
        "chaos world did not settle within the recovery budget; codes="
        f"{[p.poll() for p in procs]}, logs="
        f"{[p.stdout.read().decode()[-800:] for p in procs]}"
    )


def _read_losses(run_dir, rank, incarnations):
    """step -> record, later incarnations winning; overlapping replayed
    steps must agree bit-for-bit (asserted) — the sample-exact pin."""
    merged = {}
    for inc in incarnations:
        path = run_dir / f"losses_rank{rank}_{inc}.jsonl"
        if not path.exists():
            continue
        for line in path.read_text().splitlines():
            rec = json.loads(line)
            if rec["step"] in merged:
                assert merged[rec["step"]] == rec, (
                    f"replayed step {rec['step']} diverged between "
                    f"incarnations on rank {rank}: {merged[rec['step']]} "
                    f"vs {rec}"
                )
            merged[rec["step"]] = rec
    return merged


def _flight_dumps(run_dir):
    out = []
    fdir = run_dir / "flight"
    if fdir.exists():
        for p in sorted(fdir.glob("flight_*.json")):
            out.append(json.loads(p.read_text()))
    return out


@pytest.mark.parametrize("failure", ["kill", "hang"])
def test_chaos_recovery_bit_for_bit(tmp_path, failure):
    # ---- reference: uninterrupted run ----------------------------------
    ref_dir = tmp_path / "ref"
    ref_dir.mkdir()
    codes, _ = _wait_world(_launch_world(ref_dir, "0"))
    assert codes == [0] * WORLD
    ref = {r: _read_losses(ref_dir, r, ["0"]) for r in range(WORLD)}
    assert all(len(ref[r]) == TOTAL_STEPS for r in range(WORLD))

    # ---- chaos run: rank 1 dies/wedges at FAIL_STEP mid-collective -----
    run_dir = tmp_path / "chaos"
    run_dir.mkdir()
    spec = (f"rank.kill:rank=1,step={FAIL_STEP}" if failure == "kill"
            else f"rank.hang:rank=1,step={FAIL_STEP}")
    t_start = time.monotonic()
    procs = _launch_world(run_dir, "0", inject_spec=spec)
    codes, reaped = _wait_world(procs)
    detect_elapsed = time.monotonic() - t_start

    if failure == "kill":
        assert codes[1] == 137, codes  # the injected hard kill
    else:
        assert reaped, "wedged rank should have needed reaping"
    # every SURVIVOR detected the stall and exited resumably (75)
    assert codes[0] == 75, codes
    # bounded-time detection: worker startup (jax import) + steps + the
    # watchdog deadline + dump slack — generously bounded, never a hang
    assert detect_elapsed < 150.0

    dumps = _flight_dumps(run_dir)
    timeout_dumps = [d for d in dumps if d["reason"] == "collective_timeout"]
    assert timeout_dumps, "surviving rank wrote no post-mortem"
    for d in timeout_dumps:
        assert d["extra"]["suspect_rank"] == 1
        assert "barrier:step" in d["extra"]["what"]
        assert d["context"]["watchdog"]["suspect_rank"] == 1

    # ---- relaunch (the launcher's resume leg), no injection ------------
    codes, _ = _wait_world(_launch_world(run_dir, "1"))
    assert codes == [0] * WORLD
    for r in range(WORLD):
        assert (run_dir / f"done_rank{r}_1").exists()

    # ---- bit-for-bit: interrupted+resumed == uninterrupted -------------
    for r in range(WORLD):
        got = _read_losses(run_dir, r, ["0", "1"])
        assert set(got) == set(ref[r]), (
            f"rank {r}: steps differ: {sorted(set(ref[r]) ^ set(got))}"
        )
        for step in sorted(ref[r]):
            assert got[step] == ref[r][step], (
                f"rank {r} step {step}: resumed run diverged: "
                f"{got[step]} vs {ref[r][step]}"
            )


def test_chaos_slow_rank_only_delays(tmp_path):
    """rank.slow is a straggler, not a failure: the world completes with no
    trips and no dumps — the watchdog tolerates slowness inside deadline."""
    run_dir = tmp_path / "slow"
    run_dir.mkdir()
    codes, _ = _wait_world(_launch_world(
        run_dir, "0", inject_spec="rank.slow:rank=1,ms=300,times=2",
        timeout_s=30.0))
    assert codes == [0] * WORLD
    assert not [d for d in _flight_dumps(run_dir)
                if d["reason"] == "collective_timeout"]
