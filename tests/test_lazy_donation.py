"""Donation-aware lazy flush (core/lazy.py liveness pass).

The flush engine classifies dead-after-flush inputs (params/moments/grads
rebound through the pending graph) and passes them as ``donate_argnums`` so
XLA updates weights in place. Pins: numerical parity donate-on vs donate-off
(bit-identical on CPU), the refcount aliasing guard (a user-held alias
blocks donation of that buffer), per-step donation + executable-cache-hit
counters via ``paddle_tpu.profiler``, and the ``FLAGS_lazy_donate``
kill-switch.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu import profiler
from paddle_tpu.core import lazy


@pytest.fixture(autouse=True)
def _lazy_donate_on():
    lazy.set_lazy_mode(True)
    paddle.set_flags({"FLAGS_lazy_donate": True})
    profiler.reset_counters()
    yield
    lazy.set_lazy_mode(True)
    paddle.set_flags({"FLAGS_lazy_donate": True})


def _train(donate, steps=5, opt_cls=None):
    paddle.set_flags({"FLAGS_lazy_donate": donate})
    paddle.seed(11)
    m = nn.Linear(16, 8)
    opt_cls = opt_cls or paddle.optimizer.Adam
    opt = opt_cls(learning_rate=0.01, parameters=m.parameters())
    x = paddle.to_tensor(np.random.RandomState(0).randn(4, 16).astype("float32"))
    y = paddle.to_tensor(np.random.RandomState(1).randn(4, 8).astype("float32"))
    losses = []
    for _ in range(steps):
        loss = F.mse_loss(m(x), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss.numpy()))
    return losses, m.weight.numpy().copy()


class TestDonationParity:
    def test_losses_bit_identical_donate_on_off(self):
        on_losses, on_w = _train(True)
        off_losses, off_w = _train(False)
        assert on_losses == off_losses  # bit-identical, not just allclose
        np.testing.assert_array_equal(on_w, off_w)

    @pytest.mark.parametrize("opt_cls_name", ["SGD", "Adam", "AdamW"])
    def test_optimizers_donate_and_match(self, opt_cls_name):
        opt_cls = getattr(paddle.optimizer, opt_cls_name)
        profiler.reset_counters()
        on_losses, _ = _train(True, opt_cls=opt_cls)
        donated = profiler.counters().get("lazy_donated_buffers", 0)
        assert donated > 0, f"{opt_cls_name}: no buffers donated"
        off_losses, _ = _train(False, opt_cls=opt_cls)
        assert on_losses == off_losses


class TestAliasingGuard:
    def test_user_held_alias_survives_donation(self):
        """detach() shares the underlying buffer; the liveness pass must see
        the extra reference and keep that buffer out of donate_argnums."""
        paddle.seed(0)
        m = nn.Linear(4, 4)
        opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=m.parameters())
        held = m.weight.detach()
        before = held.numpy().copy()
        x = paddle.to_tensor(np.random.RandomState(0).randn(2, 4).astype("float32"))
        for _ in range(3):
            loss = (m(x) ** 2).mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
        np.testing.assert_array_equal(before, held.numpy())
        # the weight itself kept training
        assert not np.array_equal(before, m.weight.numpy())

    def test_numpy_view_of_old_buffer_unaffected(self):
        paddle.seed(0)
        m = nn.Linear(4, 4)
        opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=m.parameters())
        snapshot = m.weight.numpy()  # host copy taken before any step
        ref = snapshot.copy()
        x = paddle.to_tensor(np.random.RandomState(0).randn(2, 4).astype("float32"))
        for _ in range(2):
            loss = (m(x) ** 2).mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
        float(loss.numpy())
        np.testing.assert_array_equal(snapshot, ref)


class TestCounters:
    def test_cache_hits_and_donations_per_step(self):
        """After warmup every identical iteration must hit the executable
        cache (hits >= steps-1) and each flushed train step must donate >0
        buffers (params + moments)."""
        steps = 6
        profiler.reset_counters()
        _train(True, steps=steps)
        c = profiler.counters()
        assert c.get("lazy_flushes", 0) >= steps
        assert c.get("lazy_cache_hits", 0) >= steps - 1
        # Adam: weight+bias params + 2 moments each = 6 donatable per step;
        # require the steady-state steps each donated something
        assert c.get("lazy_donated_buffers", 0) >= (steps - 1) * 2
        assert c.get("lazy_donation_fallbacks", 0) == 0

    def test_kill_switch_disables_donation(self):
        profiler.reset_counters()
        _train(False, steps=3)
        assert profiler.counters().get("lazy_donated_buffers", 0) == 0


class TestGradAccumulation:
    def test_microbatch_grad_accumulation_parity(self):
        """Accumulated-grad rebinds (engine.py grad_acc) are donation
        candidates; accumulation across microbatches must stay exact."""

        def run(donate):
            paddle.set_flags({"FLAGS_lazy_donate": donate})
            paddle.seed(3)
            m = nn.Linear(8, 4)
            opt = paddle.optimizer.SGD(learning_rate=0.05, parameters=m.parameters())
            out = []
            for step in range(3):
                for micro in range(3):  # 3 microbatches, no clear in between
                    x = paddle.to_tensor(
                        np.random.RandomState(10 * step + micro).randn(2, 8).astype("float32")
                    )
                    loss = (m(x) ** 2).mean()
                    loss.backward()
                    out.append(float(loss.numpy()))
                opt.step()
                opt.clear_grad()
            return out, m.weight.numpy().copy()

        on_l, on_w = run(True)
        off_l, off_w = run(False)
        assert on_l == off_l
        np.testing.assert_array_equal(on_w, off_w)
