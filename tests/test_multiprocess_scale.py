"""Multi-process scale beyond world=2 (VERDICT r4 item 5).

Reference methodology: ``test_dist_base.py:1032`` runs N-proc clusters and
checks loss parity with the single-process run; ``fleet/launch_utils.py``
handles real multi-node topologies. Here: 4- and 8-process CPU
``jax.distributed`` jobs through the package's own bootstrap
(``init_parallel_env``), the sharded host-embedding PS at world=4, and an
elastic scale-down mid-train with checkpoint resume at the smaller world.
"""
import json
import os
import socket
import subprocess
import sys
import textwrap
import time

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Same environment limit as tests/test_multiprocess.py: this jaxlib's CPU
# client rejects cross-process collectives ("INVALID_ARGUMENT: Multiprocess
# computations aren't implemented on the CPU backend") — rendezvous works,
# the worker's psum doesn't, so every worker exits nonzero. Non-strict
# xfail so a capable jaxlib surfaces these as XPASS instead of hiding them.
_CPU_MULTIPROC_XFAIL = pytest.mark.xfail(
    os.environ.get("JAX_PLATFORMS", "cpu") == "cpu",
    reason="environment limit: jaxlib CPU backend does not implement "
    "multiprocess computations (XlaRuntimeError INVALID_ARGUMENT in the "
    "worker's collective)",
    strict=False,
)


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _env(rank, world, coord_port, extra=None):
    env = {k: v for k, v in os.environ.items()
           if k not in ("PYTHONPATH", "XLA_FLAGS")}
    env.update({
        "PYTHONPATH": REPO,
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
        "PADDLE_TRAINER_ID": str(rank),
        "PADDLE_TRAINERS_NUM": str(world),
        "PADDLE_TPU_COORDINATOR": f"127.0.0.1:{coord_port}",
    })
    env.update(extra or {})
    return env


DP_WORKER = textwrap.dedent(
    """
    import os, json
    import numpy as np
    rank = int(os.environ["PADDLE_TRAINER_ID"])
    world = int(os.environ["PADDLE_TRAINERS_NUM"])
    from paddle_tpu.distributed import parallel_env

    env = parallel_env.init_parallel_env()
    assert env.world_size == world, env.world_size
    import jax, jax.numpy as jnp

    # data-parallel least squares: each rank holds 1/world of the batch;
    # grads all-reduce over the process world (1 device per proc)
    rng = np.random.RandomState(0)
    X = rng.randn(32, 8).astype(np.float32)
    Y = X @ rng.randn(8, 1).astype(np.float32)
    shard = slice(rank * (32 // world), (rank + 1) * (32 // world))
    Xs, Ys = jnp.asarray(X[shard]), jnp.asarray(Y[shard])
    w = jnp.zeros((8, 1), jnp.float32)
    # pmap IS the jit: 1 local device per proc, psum spans the process world
    allreduce = jax.pmap(lambda g: jax.lax.psum(g, "i"), axis_name="i")
    gradf = jax.jit(jax.grad(lambda w, x, y: jnp.mean((x @ w - y) ** 2)))

    for _ in range(5):
        g = allreduce(gradf(w, Xs, Ys)[None])[0] / world
        w = w - 0.1 * g
    print(json.dumps({"rank": rank, "w0": float(w[0, 0]), "wsum": float(jnp.sum(w))}), flush=True)
    """
)


def _run_world(worker, world, extra=None, timeout=300):
    coord = _free_port()
    procs = [
        subprocess.Popen([sys.executable, "-c", worker],
                         env=_env(r, world, coord, extra),
                         stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
        for r in range(world)
    ]
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=timeout)
        assert p.returncode == 0, out.decode()[-3000:]
        outs.append(json.loads(out.decode().strip().splitlines()[-1]))
    return outs


class TestWorldScale:
    @_CPU_MULTIPROC_XFAIL
    @pytest.mark.parametrize("world", [4, 8])
    def test_dp_train_parity(self, world):
        outs = _run_world(DP_WORKER, world)
        # every rank converges to the SAME weights...
        wsums = [o["wsum"] for o in outs]
        assert max(wsums) - min(wsums) < 1e-5, wsums
        # ...equal to the single-process full-batch run
        rng = np.random.RandomState(0)
        X = rng.randn(32, 8).astype(np.float32)
        Y = X @ rng.randn(8, 1).astype(np.float32)
        w = np.zeros((8, 1), np.float32)
        for _ in range(5):
            g = 2 * X.T @ (X @ w - Y) / len(X)
            w = w - 0.1 * g
        np.testing.assert_allclose(wsums[0], float(w.sum()), rtol=1e-4)


EMB_WORKER = textwrap.dedent(
    """
    import os, json
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.incubate.host_embedding import (
        ShardedHostEmbeddingTable, sharded_host_embedding,
    )

    rank = int(os.environ["PADDLE_TRAINER_ID"])
    emb = sharded_host_embedding(128, 8, seed=3)
    assert isinstance(emb.table, ShardedHostEmbeddingTable)
    losses = []
    for step in range(3):
        rng = np.random.RandomState(50 + step)
        ids = rng.randint(0, 128, (4, 5))
        out = emb(paddle.to_tensor(ids))
        loss = paddle.sum(out * out)
        loss.backward()
        emb.apply_gradients(lr=0.1)
        losses.append(float(loss.numpy()))
    print(json.dumps({"rank": rank, "losses": losses}), flush=True)
    """
)


class TestShardedEmbeddingWorld4:
    def test_world4_parity_with_single_table(self):
        from paddle_tpu.core.native import lib

        if lib() is None:
            pytest.skip("native runtime not built")
        world = 4
        outs = _run_world(EMB_WORKER, world,
                          extra={"PADDLE_EMB_STORE_PORT": str(_free_port())})
        for o in outs[1:]:
            assert o["losses"] == outs[0]["losses"], outs

        from paddle_tpu.incubate.host_embedding import HostEmbedding
        import paddle_tpu as paddle

        emb = HostEmbedding(128, 8, seed=3)
        ref = []
        for step in range(3):
            rng = np.random.RandomState(50 + step)
            ids = rng.randint(0, 128, (4, 5))
            out = emb(paddle.to_tensor(ids))
            loss = paddle.sum(out * out)
            loss.backward()
            # 4 ranks pushed identical grads -> 4x summed update
            for uniq, rows in emb._pending:
                if rows.grad is not None:
                    rows.grad._set_data(rows.grad._data * float(world))
            emb.apply_gradients(lr=0.1)
            ref.append(float(loss.numpy()))
        np.testing.assert_allclose(outs[0]["losses"], ref, rtol=1e-5)


ELASTIC_WORKER = textwrap.dedent(
    """
    import os, json, sys
    import numpy as np
    rank = int(os.environ["PADDLE_TRAINER_ID"])
    world = int(os.environ["PADDLE_TRAINERS_NUM"])
    ckpt = os.environ["CKPT_PATH"]
    die_at = int(os.environ.get("DIE_AT_STEP", "-1"))
    from paddle_tpu.distributed import parallel_env

    parallel_env.init_parallel_env()
    import jax, jax.numpy as jnp

    rng = np.random.RandomState(0)
    X = rng.randn(32, 8).astype(np.float32)
    Y = X @ rng.randn(8, 1).astype(np.float32)
    per = 32 // world
    Xs = jnp.asarray(X[rank * per:(rank + 1) * per])
    Ys = jnp.asarray(Y[rank * per:(rank + 1) * per])

    # resume: AutoCheckpoint-style — pick up step/weights if present
    start, w = 0, jnp.zeros((8, 1), jnp.float32)
    if os.path.exists(ckpt):
        data = np.load(ckpt)
        start, w = int(data["step"]), jnp.asarray(data["w"])

    allreduce = jax.pmap(lambda g: jax.lax.psum(g, "i"), axis_name="i")
    gradf = jax.jit(jax.grad(lambda w, x, y: jnp.mean((x @ w - y) ** 2)))

    for step in range(start, 6):
        if rank == world - 1 and die_at >= 0 and step == die_at:
            os._exit(17)  # hard exit: sys.exit would hang in jax.distributed's atexit shutdown barrier
        w = w - 0.1 * allreduce(gradf(w, Xs, Ys)[None])[0] / world
        if rank == 0:
            np.savez(ckpt, step=step + 1, w=np.asarray(w))
    print(json.dumps({"rank": rank, "world": world, "wsum": float(jnp.sum(w))}), flush=True)
    """
)


class TestElasticScaleDown:
    @_CPU_MULTIPROC_XFAIL
    def test_scale_down_mid_train_resumes_at_world3(self, tmp_path):
        """4-proc job loses a worker at step 2; the elastic supervisor
        relaunches at world=3 and training RESUMES from the checkpoint
        (reference: elastic/manager.py scale-in + AutoCheckpoint resume)."""
        ckpt = str(tmp_path / "ckpt.npz")

        def launch(world, die_at):
            coord = _free_port()
            return [
                subprocess.Popen(
                    [sys.executable, "-c", ELASTIC_WORKER],
                    env=_env(r, world, coord,
                             {"CKPT_PATH": ckpt, "DIE_AT_STEP": str(die_at)}),
                    stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
                for r in range(world)
            ]

        procs = launch(4, die_at=2)
        # the failing rank exits; survivors BLOCK in the dead collective —
        # exactly why the elastic supervisor kills and relaunches the world
        assert procs[-1].wait(timeout=300) == 17
        time.sleep(1.0)
        for p in procs[:-1]:
            p.kill()  # SIGKILL: blocked in gloo, SIGTERM is ignored
        for p in procs[:-1]:
            p.wait(timeout=60)
        assert os.path.exists(ckpt)  # progress survived
        step_before = int(np.load(ckpt)["step"])
        assert 1 <= step_before < 6

        # supervisor decision: scale down to the 3 survivors and resume
        procs = launch(3, die_at=-1)
        outs = []
        for p in procs:
            out, _ = p.communicate(timeout=300)
            assert p.returncode == 0, out.decode()[-3000:]
            outs.append(json.loads(out.decode().strip().splitlines()[-1]))
        assert all(o["world"] == 3 for o in outs)
        # resumed run completes all 6 steps and converges like 1-proc SGD
        # seeded from the same checkpointed trajectory
        assert int(np.load(ckpt)["step"]) == 6
        wsums = [o["wsum"] for o in outs]
        assert max(wsums) - min(wsums) < 1e-5
