"""Stability-sentinel chaos suite: REPEATED loss/grad spikes under 2-proc
training, recovered by coordinated sentinel rollback with bit-exact parity.

Each worker runs a deterministic per-rank train loop with a
``StabilitySentinel`` anchored on a ``CoordinatedCheckpoint``. The armed
``grad.spike`` / ``loss.spike`` points fire on BOTH ranks at two different
steps (two separate incidents — the cooldown resets the ladder between
them); detection is deferred (≤1 step late, ``FLAGS_lazy_async``), so each
incident escalates to rollback. Both ranks resolve the same anchor through
the store-mediated resume agreement (``resume(max_step=...)``), replay with
the quarantined steps skipped, and the final per-step records — loss and
weights, hex-exact — must equal a reference world that excluded those
batches up front.

Workers are fresh interpreters over a FileStore (the ``spawn`` substrate),
so the suite carries the ``chaos`` marker: auto-skipped on the CPU tier,
opt in with ``PADDLE_TPU_CHAOS=1``.
"""
import json
import os
import subprocess
import sys
import time
from pathlib import Path

import pytest

pytestmark = [pytest.mark.faults, pytest.mark.chaos]

WORLD = 2
TOTAL_STEPS = 9
SPIKE_SPEC = "grad.spike:step=4,scale=1000000;loss.spike:step=7,scale=1000000"
QUARANTINED = (4, 7)

_WORKER = r"""
import json, os, sys
import numpy as np

os.environ.setdefault("JAX_PLATFORMS", "cpu")
import jax

import paddle_tpu
from paddle_tpu.distributed import watchdog
from paddle_tpu.distributed.checkpoint import CoordinatedCheckpoint
from paddle_tpu.distributed.coord import wait_for
from paddle_tpu.fault import inject
from paddle_tpu.fault.sentinel import StabilitySentinel
from paddle_tpu.core import lazy

rank = int(os.environ["PADDLE_TRAINER_ID"])
world = int(os.environ["PADDLE_TRAINERS_NUM"])
run_dir = os.environ["CHAOS_RUN_DIR"]
total_steps = int(os.environ["CHAOS_TOTAL_STEPS"])
pre_q = [int(s) for s in os.environ.get("CHAOS_PRE_Q", "").split(",") if s]

watchdog.configure()  # rank/world/store from the launcher env
store = watchdog._cfg["store"]
assert store is not None, "stability chaos worker needs PADDLE_TPU_STORE_DIR"


def data_for(step):
    rng = np.random.RandomState(7000 + 100 * rank + step)
    return rng.randn(8, 4).astype(np.float32), rng.randn(8, 1).astype(np.float32)


w = paddle_tpu.to_tensor(np.full((4, 1), 0.5, np.float32))
w.stop_gradient = False
opt = paddle_tpu.optimizer.Adam(learning_rate=0.05, parameters=[w])
state = {"w": w, "opt": opt}

cc = CoordinatedCheckpoint(
    os.path.join(run_dir, "ckpt"), world_size=world, rank=rank, store=store,
    interval_steps=1, commit_timeout_s=30.0,
)
sent = StabilitySentinel(window=32, warmup=3, zmax=50, max_skips=2,
                         max_rollbacks=2, cooldown=2, anchor=cc)
for s in pre_q:
    sent.quarantine.add(-1, pos=(0, s), action="skip")

records = {}
step = 0
rollbacks = []
while step < total_steps:
    if sent.is_quarantined(pos=(0, step)):
        step += 1
        continue
    x, y = data_for(step)
    xt, yt = paddle_tpu.to_tensor(x), paddle_tpu.to_tensor(y)
    loss = ((paddle_tpu.matmul(xt, w) - yt) ** 2).mean()
    s = inject.spike("loss.spike", step=step)
    if s is not None:
        loss = loss * s
    loss.backward()
    s = inject.spike("grad.spike", step=step)
    if s is not None:
        w.grad._set_data((w.grad * s)._data)
    v = sent.observe(step, loss=loss, grads=[w.grad], params=[w],
                     lr=opt.get_lr(), pos=(0, step))
    if v is not None:
        opt.clear_grad()
        if v.action == "skip" and v.step == step:
            step += 1
            continue
        if v.action == "rollback":
            # every rank reaches the same verdict on the same step (the
            # spike fires world-wide); the coordinated resume agreement
            # inside cc.resume pins them to one anchor
            a = sent.rollback(v, state)
            rollbacks.append([v.step, a])
            step = a + 1
            continue
        sent.halt(v)
    opt.step()
    opt.clear_grad()
    records[step] = {
        "loss_hex": float(loss.item()).hex(),
        "w_hex": [float(x_) for x_ in np.asarray(lazy.concrete(w._data)).ravel()],
    }
    # lockstep barrier so both ranks observe/rollback in the same window
    bar = f"stab/bar/{step}/{len(rollbacks)}"
    store.add(bar, 1)
    wait_for(lambda: int(store.get(bar) or 0) >= world,
             f"stability barrier step {step}", 60.0, interval_s=0.01)
    sent.maybe_anchor(step, state)
    step += 1

sent.poll()
sent.close()
# quarantined steps' stale (poisoned) records are not part of the final
# timeline — the replay skipped them
for e in sent.quarantine.entries():
    records.pop(e["step"], None)
out = {
    "records": {str(k): v for k, v in sorted(records.items())},
    "rollbacks": rollbacks,
    "quarantined": sorted({e["step"] for e in sent.quarantine.entries()}),
}
with open(os.path.join(run_dir, f"out_rank{rank}.json"), "w") as f:
    json.dump(out, f)
sys.exit(0)
"""


_COORD_WORKER = r"""
import json, os, sys
import numpy as np

os.environ.setdefault("JAX_PLATFORMS", "cpu")
import jax

import paddle_tpu
from paddle_tpu.distributed import watchdog
from paddle_tpu.distributed.checkpoint import CoordinatedCheckpoint
from paddle_tpu.fault import inject
from paddle_tpu.fault.sentinel import StabilitySentinel, VerdictBarrier
from paddle_tpu.core import lazy

rank = int(os.environ["PADDLE_TRAINER_ID"])
world = int(os.environ["PADDLE_TRAINERS_NUM"])
run_dir = os.environ["CHAOS_RUN_DIR"]
total_steps = int(os.environ["CHAOS_TOTAL_STEPS"])
pre_q = [int(s) for s in os.environ.get("CHAOS_PRE_Q", "").split(",") if s]

watchdog.configure()
store = watchdog._cfg["store"]
assert store is not None, "stability chaos worker needs PADDLE_TPU_STORE_DIR"


def data_for(step):
    # WORLD-SHARED batches (lockstep DP semantics): the rank-LOCAL anomaly
    # is the spike — host memory corruption on one rank — not the data
    rng = np.random.RandomState(9000 + step)
    return rng.randn(8, 4).astype(np.float32), rng.randn(8, 1).astype(np.float32)


w = paddle_tpu.to_tensor(np.full((4, 1), 0.5, np.float32))
w.stop_gradient = False
opt = paddle_tpu.optimizer.Adam(learning_rate=0.05, parameters=[w])
state = {"w": w, "opt": opt}

cc = CoordinatedCheckpoint(
    os.path.join(run_dir, "ckpt"), world_size=world, rank=rank, store=store,
    interval_steps=1, commit_timeout_s=30.0,
)
sent = StabilitySentinel(window=32, warmup=3, zmax=50, max_skips=2,
                         max_rollbacks=2, cooldown=2, anchor=cc)
# the verdict barrier: every rank leaves each step boundary with the SAME
# verdict, even when only ONE rank's detector tripped
vb = VerdictBarrier(store, world, rank, sentinel=sent, timeout_s=60.0)
for s in pre_q:
    sent.quarantine.add(-1, pos=(0, s), action="skip")

records = {}
rollbacks = []
adopted = []
step = 0
while step < total_steps:
    if sent.is_quarantined(pos=(0, step)):
        step += 1
        continue
    x, y = data_for(step)
    xt, yt = paddle_tpu.to_tensor(x), paddle_tpu.to_tensor(y)
    loss = ((paddle_tpu.matmul(xt, w) - yt) ** 2).mean()
    s = inject.spike("loss.spike", step=step, rank=rank)
    if s is not None:
        loss = loss * s
    loss.backward()
    v_local = sent.observe(step, loss=loss, grads=[w.grad], params=[w],
                           lr=opt.get_lr(), pos=(0, step))
    # the exchange doubles as the per-step lockstep barrier
    v = vb.exchange(v_local)
    if v is not None:
        opt.clear_grad()
        if v.origin_rank is not None:
            adopted.append([v.step, v.origin_rank])
        if v.action == "skip" and v.step == step:
            step += 1
            continue
        if v.action == "rollback":
            a = sent.rollback(v, state)
            rollbacks.append([v.step, a])
            step = a + 1
            continue
        sent.halt(v)
    opt.step()
    opt.clear_grad()
    records[step] = {
        "loss_hex": float(loss.item()).hex(),
        "w_hex": [float(x_) for x_ in np.asarray(lazy.concrete(w._data)).ravel()],
    }
    sent.maybe_anchor(step, state)
    step += 1

sent.poll()
sent.close()
for e in sent.quarantine.entries():
    records.pop(e["step"], None)
out = {
    "records": {str(k): v for k, v in sorted(records.items())},
    "rollbacks": rollbacks,
    "adopted": adopted,
    "quarantined": sorted({e["step"] for e in sent.quarantine.entries()}),
}
with open(os.path.join(run_dir, f"out_rank{rank}.json"), "w") as f:
    json.dump(out, f)
sys.exit(0)
"""


def _launch_world(run_dir, inject_spec=None, pre_q=(), worker_src=_WORKER):
    script = run_dir / "worker.py"
    script.write_text(worker_src)
    procs = []
    for rank in range(WORLD):
        env = dict(os.environ)
        repo_root = str(Path(__file__).resolve().parent.parent)
        env.update({
            "PYTHONPATH": repo_root + (
                os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else ""
            ),
            "JAX_PLATFORMS": "cpu",
            "PADDLE_TRAINER_ID": str(rank),
            "PADDLE_TRAINERS_NUM": str(WORLD),
            "PADDLE_TPU_STORE_DIR": str(run_dir / "store"),
            "PADDLE_TPU_PROGRESS_DIR": str(run_dir / "progress"),
            "PADDLE_TPU_FLIGHT_DIR": str(run_dir / "flight"),
            "CHAOS_RUN_DIR": str(run_dir),
            "CHAOS_TOTAL_STEPS": str(TOTAL_STEPS),
            "CHAOS_PRE_Q": ",".join(str(s) for s in pre_q),
        })
        env.pop("PADDLE_FAULT_INJECT", None)
        if inject_spec:
            env["PADDLE_FAULT_INJECT"] = inject_spec
        procs.append(subprocess.Popen(
            [sys.executable, str(script)], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        ))
    return procs


def _wait_world(procs, deadline_s=240.0):
    t0 = time.monotonic()
    while time.monotonic() - t0 < deadline_s:
        codes = [p.poll() for p in procs]
        if all(c is not None for c in codes):
            return codes
        time.sleep(0.2)
    for p in procs:
        if p.poll() is None:
            p.kill()
    raise AssertionError(
        "stability chaos world did not finish; logs="
        f"{[p.stdout.read().decode()[-800:] for p in procs]}"
    )


def _read_out(run_dir, rank):
    return json.loads((run_dir / f"out_rank{rank}.json").read_text())


def test_repeated_spikes_recovered_bit_exact_2proc(tmp_path):
    # reference world: the two condemned batches excluded up front
    ref_dir = tmp_path / "ref"
    ref_dir.mkdir()
    procs = _launch_world(ref_dir, pre_q=QUARANTINED)
    codes = _wait_world(procs)
    assert codes == [0] * WORLD, [p.stdout.read().decode()[-800:] for p in procs]

    # chaos world: both spikes fire on both ranks, detection deferred
    run_dir = tmp_path / "chaos"
    run_dir.mkdir()
    procs = _launch_world(run_dir, inject_spec=SPIKE_SPEC)
    codes = _wait_world(procs)
    assert codes == [0] * WORLD, [p.stdout.read().decode()[-800:] for p in procs]

    for rank in range(WORLD):
        ref = _read_out(ref_dir, rank)
        got = _read_out(run_dir, rank)
        # two separate incidents, each rolled back to an anchor strictly
        # before the poisoned step
        assert len(got["rollbacks"]) == 2
        for bad, anchor in got["rollbacks"]:
            assert anchor < bad
        assert got["quarantined"] == sorted(QUARANTINED)
        assert not ref["rollbacks"]
        # bit-exact parity: every surviving step's loss and weights match
        assert set(got["records"]) == set(ref["records"])
        for k in ref["records"]:
            assert got["records"][k] == ref["records"][k], (
                f"rank {rank} step {k}: post-recovery divergence"
            )


RANK_SPIKE_STEP = 4


def test_rank_local_spike_triggers_coordinated_rollback(tmp_path):
    """PR 13 follow-up pin: a spike firing on ONE rank only
    (``loss.spike:rank=1``) — the host-memory-corruption shape — must roll
    back BOTH ranks through the store-mediated VerdictBarrier: rank 0's
    detector never trips, it ADOPTS rank 1's verdict, both quarantine the
    batch and resolve one anchor via the coordinated resume agreement, and
    the surviving timeline is bit-exact against a world that excluded the
    batch up front."""
    ref_dir = tmp_path / "ref"
    ref_dir.mkdir()
    procs = _launch_world(ref_dir, pre_q=(RANK_SPIKE_STEP,),
                          worker_src=_COORD_WORKER)
    codes = _wait_world(procs)
    assert codes == [0] * WORLD, [p.stdout.read().decode()[-800:] for p in procs]

    run_dir = tmp_path / "chaos"
    run_dir.mkdir()
    procs = _launch_world(
        run_dir,
        inject_spec=f"loss.spike:rank=1,step={RANK_SPIKE_STEP},scale=1000000",
        worker_src=_COORD_WORKER,
    )
    codes = _wait_world(procs)
    assert codes == [0] * WORLD, [p.stdout.read().decode()[-800:] for p in procs]

    out = {rank: _read_out(run_dir, rank) for rank in range(WORLD)}
    # rank 1 tripped locally; rank 0 adopted the verdict across the store
    assert out[0]["adopted"] == [[RANK_SPIKE_STEP, 1]]
    assert out[1]["adopted"] == []
    for rank in range(WORLD):
        ref = _read_out(ref_dir, rank)
        got = out[rank]
        assert len(got["rollbacks"]) == 1
        bad, anchor = got["rollbacks"][0]
        assert bad == RANK_SPIKE_STEP and anchor < bad
        assert got["quarantined"] == [RANK_SPIKE_STEP]
        assert not ref["rollbacks"] and not ref["adopted"]
        assert set(got["records"]) == set(ref["records"])
        for k in ref["records"]:
            assert got["records"][k] == ref["records"][k], (
                f"rank {rank} step {k}: coordinated-rollback divergence"
            )
